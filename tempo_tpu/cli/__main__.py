from __future__ import annotations

import argparse
import json
import sys


def _open_db(path: str):
    from ..db.tempodb import TempoDB, TempoDBConfig
    import tempfile

    db = TempoDB(
        TempoDBConfig(
            backend={"backend": "local", "path": path},
            wal_path=tempfile.mkdtemp(prefix="tempo-cli-wal"),
        )
    )
    db.poll_now()
    return db


def cmd_list_blocks(args):
    db = _open_db(args.backend)
    tenants = [args.tenant] if args.tenant else db.tenants()
    for tenant in tenants:
        for m in db.blocklist.metas(tenant):
            print(
                f"{tenant}\t{m.block_id}\tlevel={m.compaction_level}\t"
                f"traces={m.total_traces}\tspans={m.total_spans}\t"
                f"size={m.size_bytes}\tgroups={len(m.row_groups)}"
            )
    db.close()


def cmd_view_block(args):
    db = _open_db(args.backend)
    for m in db.blocklist.metas(args.tenant):
        if m.block_id == args.block_id:
            print(json.dumps(json.loads(m.to_json()), indent=2))
            db.close()
            return
    print(f"block {args.block_id} not found for tenant {args.tenant}", file=sys.stderr)
    db.close()
    sys.exit(1)


def cmd_query_trace(args):
    """The BASELINE config #1 path: trace-ID lookup over a local backend."""
    from ..util.traceid import parse_trace_id
    from ..wire import otlp_json

    db = _open_db(args.backend)
    tr = db.find_trace_by_id(args.tenant, parse_trace_id(args.trace_id))
    db.close()
    if tr is None:
        print("trace not found", file=sys.stderr)
        sys.exit(1)
    print(otlp_json.dumps(tr))


def _print_kernel_stats():
    """Post-query kernel telemetry on stderr (the CLI face of
    /status/kernels): compiles, routing reasons, staging waste."""
    from ..util.kerneltel import TEL

    print(json.dumps(TEL.snapshot(), indent=2), file=sys.stderr)


def cmd_search(args):
    from ..db.search import SearchRequest

    db = _open_db(args.backend)
    tags = {}
    for part in args.tags or []:
        k, _, v = part.partition("=")
        tags[k] = v
    req = SearchRequest(tags=tags, query=args.q or "", limit=args.limit)
    if args.concurrency > 1:
        # drive the cross-query batching executor by hand: N identical
        # queries in parallel; latency + launch/occupancy summary on
        # stderr, first response on stdout
        import time
        from concurrent.futures import ThreadPoolExecutor

        from ..util.kerneltel import TEL

        db.search(args.tenant, req)  # warm: staging + compiles

        def one(_):
            t0 = time.perf_counter()
            r = db.search(args.tenant, req)
            return time.perf_counter() - t0, r

        l0 = TEL.launch_count()
        with ThreadPoolExecutor(args.concurrency) as ex:
            outs = list(ex.map(one, range(args.concurrency)))
        launches = TEL.launch_count() - l0
        lats = sorted(dt for dt, _ in outs)
        resp = outs[0][1]
        summary = {
            "concurrency": args.concurrency,
            "p50_ms": round(lats[len(lats) // 2] * 1e3, 3),
            "p95_ms": round(lats[min(len(lats) - 1, int(len(lats) * 0.95))] * 1e3, 3),
            "launches_per_query": round(launches / args.concurrency, 3),
            "batching": TEL.batch_stats(),
        }
        print(json.dumps(summary, indent=2), file=sys.stderr)
    else:
        resp = db.search(args.tenant, req)
    db.close()
    print(json.dumps({"traces": [t.to_dict() for t in resp.traces]}, indent=2))
    if args.kernel_stats:
        _print_kernel_stats()


def cmd_stream_search(args):
    """Progressive search against a RUNNING instance: consume
    /api/search?stream=true (NDJSON) and print each partial the moment
    its shard completes -- the operator's live tail. Partials go to
    stderr as they arrive; the final (done=true) body goes to stdout,
    so piping to jq sees exactly the blocking-response shape."""
    import urllib.parse
    import urllib.request

    params = {"limit": str(args.limit), "stream": "true"}
    if args.q:
        params["q"] = args.q
    if args.tags:
        params["tags"] = " ".join(args.tags)
    if args.recent:
        import time

        now = int(time.time())
        params["start"], params["end"] = str(now - args.recent), str(now + 5)
    url = args.target.rstrip("/") + "/api/search?" + urllib.parse.urlencode(params)
    req = urllib.request.Request(
        url, headers={"X-Scope-OrgID": args.tenant} if args.tenant else {})
    last = None
    with urllib.request.urlopen(req, timeout=args.timeout) as r:
        for line in r:
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            last = ev
            if not ev.get("done"):
                print(json.dumps({
                    "partial": True,
                    "jobs": f"{ev['jobsCompleted']}/{ev['jobsTotal']}",
                    "traces": len(ev["traces"]),
                }), file=sys.stderr)
    if last is not None:
        print(json.dumps({"traces": last["traces"],
                          "metrics": last.get("metrics", {})}, indent=2))


def _render_timeline(tr) -> None:
    """Render a self-trace as an indented timeline tree: per span its
    wall time, offset from the root, and attrs -- the flame view of one
    query's life across frontend, queue, engines and remote legs."""
    spans = [sp for _, _, sp in tr.all_spans()]
    if not spans:
        print("(empty trace)")
        return
    by_id = {sp.span_id: sp for sp in spans}
    children: dict[bytes, list] = {}
    roots = []
    for sp in spans:
        if sp.parent_span_id and sp.parent_span_id in by_id:
            children.setdefault(sp.parent_span_id, []).append(sp)
        else:
            roots.append(sp)
    roots.sort(key=lambda s: s.start_unix_nano)
    t0 = roots[0].start_unix_nano

    def fmt_attrs(attrs: dict) -> str:
        parts = []
        for k in sorted(attrs):
            v = attrs[k]
            parts.append(f"{k}={v}")
        return ("  [" + " ".join(parts) + "]") if parts else ""

    def walk(sp, prefix: str, last: bool, top: bool) -> None:
        dur_ms = max(0, sp.end_unix_nano - sp.start_unix_nano) / 1e6
        off_ms = (sp.start_unix_nano - t0) / 1e6
        branch = "" if top else ("└─ " if last else "├─ ")
        print(f"{prefix}{branch}{sp.name}  {dur_ms:.2f}ms @+{off_ms:.2f}ms"
              f"{fmt_attrs(sp.attrs)}")
        kids = sorted(children.get(sp.span_id, []),
                      key=lambda s: s.start_unix_nano)
        ext = "" if top else ("   " if last else "│  ")
        for i, k in enumerate(kids):
            walk(k, prefix + ext, i == len(kids) - 1, False)

    for i, r in enumerate(roots):
        walk(r, "", i == len(roots) - 1, True)


def cmd_self_trace(args):
    """Dogfood: fetch one of the system's OWN query traces through the
    system's own find-by-ID path and render the timeline tree. `latest`
    resolves the most recent self-traced query from /status/kernels'
    slow-query log. With --target unset, reads flushed self-tenant
    blocks straight off the backend path (offline mode)."""
    import urllib.error
    import urllib.request

    from ..util.traceid import parse_trace_id
    from ..wire import otlp_json

    trace_id = args.trace_id
    if args.target:
        base = args.target.rstrip("/")
        if trace_id == "latest":
            with urllib.request.urlopen(base + "/status/kernels",
                                        timeout=args.timeout) as r:
                status = json.load(r)
            logged = sorted(
                (q for q in status.get("slow_queries", [])
                 if q.get("self_trace_id")),
                key=lambda q: -q.get("at_unix", 0))
            if not logged:
                print("no self-traced queries in the slow-query log "
                      "(is --self-tracing.tenant set?)", file=sys.stderr)
                sys.exit(1)
            trace_id = logged[0]["self_trace_id"]
            print(f"latest self-traced {logged[0]['op']} query: {trace_id} "
                  f"({logged[0]['seconds'] * 1e3:.1f}ms)", file=sys.stderr)
        req = urllib.request.Request(
            f"{base}/api/traces/{trace_id}",
            headers={"X-Scope-OrgID": args.tenant})
        try:
            with urllib.request.urlopen(req, timeout=args.timeout) as r:
                tr = otlp_json.loads(r.read())
        except urllib.error.HTTPError as e:
            print(f"trace {trace_id} not found under tenant {args.tenant!r}: "
                  f"{e.code} (still in the live head? it is searchable "
                  f"there too)", file=sys.stderr)
            sys.exit(1)
    else:
        if trace_id == "latest":
            print("self-trace latest needs --target (a running instance)",
                  file=sys.stderr)
            sys.exit(1)
        db = _open_db(args.backend)
        tr = db.find_trace_by_id(args.tenant, parse_trace_id(trace_id))
        db.close()
        if tr is None:
            print(f"trace {trace_id} not found in backend tenant "
                  f"{args.tenant!r}", file=sys.stderr)
            sys.exit(1)
    _render_timeline(tr)


def _render_folded(text: str, top_k: int = 25) -> None:
    """Render a folded (flamegraph-collapsed) profile artifact as a
    hottest-stacks table: header comments pass through, stack lines
    aggregate and sort by sample count."""
    stacks: list[tuple[int, str]] = []
    total = 0
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            print(line)
            continue
        stack, _, count = line.rpartition(" ")
        try:
            n = int(count)
        except ValueError:
            continue
        stacks.append((n, stack))
        total += n
    stacks.sort(key=lambda s: -s[0])
    print(f"# {total} samples, {len(stacks)} distinct stacks")
    for n, stack in stacks[:top_k]:
        print(f"\n{n:>6} samples ({100.0 * n / max(1, total):5.1f}%)")
        for frame in stack.split(";")[-12:]:
            print(f"        {frame}")


def cmd_profile(args):
    """Continuous-profiling tooling against a running instance:

      cpu       burst CPU profile via /debug/profile (text, or raw
                folded flamegraph-collapsed lines with --folded);
      device    record a jax.profiler trace via /debug/profile/device
                and download the zipped artifact;
      lock      render the lock-contention table from /status/profile;
      artifact  fetch one profile artifact by id (slow-query captures
                from the slow-query log, device zips) and render
                folded text or save binary with -o.
    """
    import urllib.error
    import urllib.request

    base = args.target.rstrip("/")
    headers = {}
    if getattr(args, "internal_token", ""):
        headers["X-Tempo-Internal-Token"] = args.internal_token

    def fetch(path: str, timeout: float) -> bytes:
        req = urllib.request.Request(base + path, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            print(f"{base}{path}: HTTP {e.code}: "
                  f"{e.read().decode(errors='replace')[:300]}",
                  file=sys.stderr)
            sys.exit(1)

    if args.profile_cmd == "cpu":
        fmt = "folded" if args.folded else "text"
        data = fetch(f"/debug/profile?seconds={args.seconds}"
                     f"&hz={args.hz}&format={fmt}",
                     timeout=args.seconds + 30.0)
        sys.stdout.write(data.decode(errors="replace"))
        return
    if args.profile_cmd == "device":
        out = json.loads(fetch(
            f"/debug/profile/device?seconds={args.seconds}",
            timeout=args.seconds + 60.0))
        aid = out["artifact_id"]
        data = fetch(f"/debug/profile/artifact/{aid}", timeout=60.0)
        path = args.output or aid
        with open(path, "wb") as f:
            f.write(data)
        print(f"device profile {aid}: {out.get('files', '?')} trace "
              f"file(s), {len(data)} bytes -> {path}")
        return
    if args.profile_cmd == "lock":
        status = json.loads(fetch("/status/profile", timeout=15.0))
        locks = status.get("locks", {})
        if not locks:
            print("no timed locks armed (start the server with "
                  "TEMPO_LOCK_PROFILE=1)")
            return
        print(f"{'lock':24} {'acquisitions':>12} {'contended':>10} "
              f"{'wait_sum_s':>12} {'wait_max_s':>12}")
        for name, row in locks.items():
            print(f"{name:24} {row['acquisitions']:>12} "
                  f"{row['contended']:>10} {row['wait_sum_s']:>12.6f} "
                  f"{row['wait_max_s']:>12.6f}")
        return
    # artifact: fetch + render (or save)
    data = fetch(f"/debug/profile/artifact/{args.artifact_id}", timeout=60.0)
    if args.output:
        with open(args.output, "wb") as f:
            f.write(data)
        print(f"{args.artifact_id}: {len(data)} bytes -> {args.output}")
        return
    if args.artifact_id.endswith(".folded"):
        _render_folded(data.decode(errors="replace"))
    else:
        print(f"{args.artifact_id}: {len(data)} bytes (binary; use "
              f"-o FILE to save)", file=sys.stderr)
        sys.exit(1)


def cmd_calibrate(args):
    """Measure THIS box's host-vs-device crossovers and commit them to
    the CostLedger (util/costledger) so `auto` routing stops guessing:

      find        -- the device-vs-host find race (ops/find
                     calibrate_find) over real backend blocks, or one
                     synthesized block when the backend is empty;
      block_scan  -- cold host column-scan rate (bytes/s incl. IO +
                     decode) + the measured link RTT, the two inputs of
                     db/search's host-vs-device engine estimate;
      live_search -- live-head engine rates (host s/row vs device fixed
                     seconds) from a synthetic ingester instance, the
                     seed db/live_engine loads at startup.

    The artifact publishes atomically; every entry is stamped with
    measured_at_unix. Run it once per box (or per topology change)."""
    import os
    import time

    import numpy as np

    from ..util import costledger

    path = (args.ledger or os.environ.get(costledger.LEDGER_ENV, "")
            or os.path.join(args.backend, "cost_ledger.json"))
    led = costledger.configure(path)
    db = _open_db(args.backend)
    scratch = None  # throwaway db when the real backend has no blocks
    out: dict = {}
    try:
        # ---- find race over backend blocks; an empty backend gets a
        # synthetic block in a THROWAWAY temp store (never a junk
        # tenant written into the operator's real backend)
        tenants = [args.tenant] if args.tenant else db.tenants()
        picked = next(
            ((t, db.blocklist.metas(t)) for t in tenants if db.blocklist.metas(t)),
            None)
        if picked is None:
            import tempfile

            from ..util.testdata import make_traces

            scratch = _open_db(tempfile.mkdtemp(prefix="tempo-calibrate-store-"))
            meta = scratch.write_block(
                "_calibrate", make_traces(512, seed=1, n_spans=8))
            picked = ("_calibrate", [meta])
            from ..util.log import get_logger

            get_logger("cli").info(
                "backend empty: calibrating against one synthetic block "
                "in a throwaway store")
        tenant, metas = picked
        src_db = scratch or db
        blocks = [src_db.open_block(m) for m in metas[:8]]
        idx = blocks[0].trace_index["trace.id_codes"]
        rng = np.random.default_rng(7)
        q = np.asarray(
            idx[rng.integers(0, idx.shape[0], size=min(256, idx.shape[0]))],
            np.int32)
        from ..ops.find import calibrate_find

        out["find"] = calibrate_find(blocks, q, repeats=args.repeats)

        # ---- cold host scan rate: fresh reader, so the bytes come off
        # the backend through the ranged-read + decode path the cold
        # engine actually pays
        from ..block.versioned import open_block_versioned

        fresh = open_block_versioned(src_db.backend, metas[0])
        names = [n for n in ("span.trace_sid", "span.dur_us", "span.name_id",
                             "span.start_ms", "span.res_idx")
                 if fresh.pack.has(n)]
        t0 = time.perf_counter()
        fresh.pack.warm_columns(names)
        nbytes = sum(fresh.pack.read(n).nbytes for n in names)
        dt = time.perf_counter() - t0
        from ..util.linkcost import link_rtt_ms

        out["block_scan"] = led.update(
            costledger.KEY_BLOCK_SCAN,
            host_rate_bps=round(nbytes / max(dt, 1e-9), 1),
            scanned_bytes=int(nbytes),
            link_rtt_ms=round(link_rtt_ms(), 3))
        led.publish()

        # ---- live-head engine race (synthetic ingester instance)
        if not args.skip_live:
            out["live_search"] = _calibrate_live(args.repeats)
    finally:
        if scratch is not None:
            scratch.close()
        db.close()
    print(json.dumps({"ledger": path, "entries": out}, indent=2))


def _calibrate_live(repeats: int) -> dict:
    """Run the live-head device engine and its host twin over a
    synthetic instance so both EMAs get real measurements, then persist
    them (LiveEngine.persist_crossover)."""
    import os
    import random
    import tempfile

    from ..backend import MemBackend
    from ..db.search import SearchRequest
    from ..db.tempodb import TempoDB, TempoDBConfig
    from ..db.wal import WAL
    from ..services.ingester import Ingester, IngesterConfig
    from ..services.overrides import Overrides
    from ..util.testdata import make_trace, make_trace_id
    from ..wire.segment import segment_for_write

    tmp = tempfile.mkdtemp(prefix="tempo-calibrate-")
    dbl = TempoDB(TempoDBConfig(wal_path=tmp + "/wal-db"), backend=MemBackend())
    ing = Ingester(WAL(tmp + "/wal"), dbl, Overrides(), IngesterConfig())
    inst = ing.instance("_calibrate")
    rng = random.Random(11)
    for i in range(512):
        tid = make_trace_id(rng)
        tr = make_trace(rng, trace_id=tid, n_spans=4,
                        base_time_ns=1_700_000_000_000_000_000 + i * 10**9)
        lo, hi = tr.time_range_nanos()
        s, e = lo // 10**9, hi // 10**9 + 1
        inst.push_segments([(tid, s, e, segment_for_write(tr, s, e))])
    req = SearchRequest(tags={"service.name": "db"}, limit=20)
    prev = os.environ.get("TEMPO_LIVE_ENGINE")
    try:
        for engine in ("device", "host"):
            os.environ["TEMPO_LIVE_ENGINE"] = engine
            for _ in range(max(2, repeats + 1)):  # first run warms compiles
                inst.search_live(req)
    finally:
        if prev is None:
            os.environ.pop("TEMPO_LIVE_ENGINE", None)
        else:
            os.environ["TEMPO_LIVE_ENGINE"] = prev
    eng = inst.live_engine
    eng.persist_crossover()
    stats = eng.stats()
    dbl.close()
    return {"crossover_rows": stats["crossover_rows"],
            "host_s_per_row": eng._host_s_per_row,
            "device_fixed_s": eng._dev_fixed_s}


def cmd_vulture(args):
    """Run the continuous-verification prober (tempo_tpu/vulture)
    against a running instance for N cycles: every probe family (by-id,
    batched find, blocking/streaming search, query_range, live-head,
    cold reads, durability ledger), freshness measured, summary with
    SLO verdicts on stdout. Exit 1 if any probe failed."""
    from ..vulture import Vulture, VultureConfig

    cfg = VultureConfig(
        push_url=args.target, query_url=args.target, tenant=args.tenant,
        visibility_timeout_s=args.visibility_timeout,
        flush_every=args.flush_every, internal_token=args.internal_token,
        backend_path=args.backend_path, seed=args.seed)
    v = Vulture(cfg)
    all_ok = True
    try:
        for n in range(args.cycles):
            results = v.cycle()
            all_ok = all_ok and Vulture.ok(results)
            print(json.dumps({
                "cycle": v.cycles, "ok": Vulture.ok(results),
                "results": [{"family": r.family, "outcome": r.outcome,
                             **({"detail": r.detail}
                                if r.outcome != "ok" else {})}
                            for r in results]}), file=sys.stderr, flush=True)
            if n + 1 < args.cycles:
                import time

                time.sleep(args.interval)
        print(json.dumps(v.status(), indent=2))
    finally:
        v.close()  # drops the fresh-reader scratch WAL dir
    if not all_ok:
        sys.exit(1)


def cmd_chaos(args):
    """Chaos-plane tooling: `sites` lists every injectable seam,
    `validate` checks a rules file without running anything, `inject`
    swaps the fault rules of a RUNNING instance over /internal/chaos
    (and `--clear` tears them down), `status` prints /status/chaos."""
    from ..chaos import plane as chaos_plane

    if args.chaos_cmd == "sites":
        for site in sorted(chaos_plane.SITES):
            print(f"{site:22} {chaos_plane.SITES[site]}")
        print(f"\nactions: {', '.join(chaos_plane.ACTIONS)}")
        print("triggers: p (probability), nth, begin_s/for_s window, "
              "max_fires; one plane seed replays the whole run")
        return
    if args.chaos_cmd == "validate":
        try:
            with open(args.rules) as f:
                doc = json.load(f)
            rules, seed = chaos_plane.parse_rules(doc)
        except (OSError, ValueError) as e:
            from ..util.log import get_logger

            get_logger("cli").error("invalid chaos rules: %s", e)
            sys.exit(1)
        from dataclasses import asdict

        print(json.dumps({"seed": seed,
                          "rules": [{k: v for k, v in asdict(r).items()
                                     if k not in ("calls", "fires")}
                                    for r in rules]}, indent=2))
        print(f"ok: {len(rules)} rule(s)", file=sys.stderr)
        return

    # inject / status against a running instance
    import urllib.request

    base = args.target.rstrip("/")
    headers = {"Content-Type": "application/json"}
    if args.internal_token:
        headers["X-Tempo-Internal-Token"] = args.internal_token
    if args.chaos_cmd == "status":
        with urllib.request.urlopen(base + "/status/chaos",
                                    timeout=args.timeout) as r:
            print(json.dumps(json.load(r), indent=2))
        return
    if args.clear:
        payload: dict = {"clear": True}
    else:
        if args.rules:
            with open(args.rules) as f:
                doc = json.load(f)
        elif args.rule:
            doc = json.loads(args.rule)
            if isinstance(doc, dict) and "site" in doc:
                doc = [doc]
        else:
            print("chaos inject needs --rules FILE, --rule JSON or --clear",
                  file=sys.stderr)
            sys.exit(1)
        rules, seed = chaos_plane.parse_rules(doc)  # validate client-side
        payload = {"seed": args.seed if args.seed is not None else seed,
                   "rules": (doc.get("rules") if isinstance(doc, dict)
                             else doc)}
    req = urllib.request.Request(
        base + "/internal/chaos", data=json.dumps(payload).encode(),
        headers=headers)
    with urllib.request.urlopen(req, timeout=args.timeout) as r:
        print(json.dumps(json.load(r), indent=2))


def cmd_slo(args):
    """Fetch /status/slo from a running instance and render the
    objective table: per-window burn rates and verdicts -- the
    operator's one-look answer to "are we meeting our targets right
    now"."""
    import urllib.request

    with urllib.request.urlopen(args.target.rstrip("/") + "/status/slo",
                                timeout=args.timeout) as r:
        st = json.load(r)
    if args.json:
        print(json.dumps(st, indent=2))
        return
    windows = list(st.get("windows", {}))
    hdr = f"{'objective':24} {'kind':13} {'target':>7} " + " ".join(
        f"{'burn ' + w:>10}" for w in windows) + "  verdict"
    print(hdr)
    for name, obj in st.get("objectives", {}).items():
        if "error" in obj:
            print(f"{name:24} SLI error: {obj['error']}")
            continue
        burns = obj.get("burn_rates", {})
        print(f"{name:24} {obj['kind']:13} {obj['target']:>7} "
              + " ".join(f"{burns.get(w, 0):>10.2f}" for w in windows)
              + f"  {obj['verdict']}")
    print(f"overall: {st.get('verdict')}")
    if st.get("verdict") != "ok":
        sys.exit(1)


def cmd_query_range(args):
    """Offline TraceQL metrics over a backend path: the CLI face of
    /api/metrics/query_range (db/metrics_exec), Prometheus matrix JSON
    on stdout."""
    import time

    from ..db.metrics_exec import align_params, to_prometheus

    db = _open_db(args.backend)
    try:
        end = args.end if args.end is not None else time.time()
        start = args.start if args.start is not None else end - 3600.0
        req = align_params(args.q, start, end, args.step)
        resp = db.metrics_query_range(args.tenant, req)
    finally:
        db.close()
    print(json.dumps(to_prometheus(resp), indent=2))
    if args.kernel_stats:
        _print_kernel_stats()


def cmd_gen(args):
    """Generate a synthetic block (bench/test fixture)."""
    from ..util.testdata import make_traces

    db = _open_db(args.backend)
    traces = make_traces(args.traces, seed=args.seed, n_spans=args.spans)
    m = db.write_block(args.tenant, traces)
    db.close()
    print(f"wrote block {m.block_id}: {m.total_traces} traces, {m.total_spans} spans")


def _require_block(db, tenant: str, block_id: str):
    metas = db.blocklist.metas_by_id(tenant, [block_id])
    if not metas:
        print(f"block {block_id} not found for tenant {tenant}", file=sys.stderr)
        db.close()
        sys.exit(1)
    return metas[0]


def cmd_gen_bloom(args):
    """Regenerate a block's bloom filter from its trace-id index
    (reference: tempo-cli gen bloom) -- the recovery path for corrupted
    or lost bloom shards."""
    from ..block.bloom import ShardedBloom
    from ..block.builder import BLOOM_PREFIX

    db = _open_db(args.backend)
    meta = _require_block(db, args.tenant, args.block_id)
    blk = db.open_block(meta)
    ids = blk.trace_index["trace.id"]
    bloom = ShardedBloom.for_estimated_items(max(1, ids.shape[0]))
    bloom.add_array(ids)
    for i in range(bloom.n_shards):
        db.backend.write(args.tenant, args.block_id, f"{BLOOM_PREFIX}{i}",
                         bloom.shard_bytes(i))
    m = meta
    m.bloom_shards, m.bloom_shard_bits = bloom.n_shards, bloom.shard_bits
    db.backend.write(args.tenant, args.block_id, "meta.json", m.to_json())
    db.close()
    print(f"regenerated bloom: {bloom.n_shards} shard(s), "
          f"{bloom.shard_bits} bits/shard, {ids.shape[0]} ids")


def cmd_dump_columns(args):
    """Per-column layout of a block's data object (reference: tempo-cli
    column dump): dtype, rows, chunks, stored vs raw bytes, codecs."""
    db = _open_db(args.backend)
    meta = _require_block(db, args.tenant, args.block_id)
    pack = db.open_block(meta).pack
    total_stored = total_raw = 0
    print(f"{'column':24} {'dtype':8} {'rows':>10} {'chunks':>6} "
          f"{'stored':>12} {'raw':>12} {'codecs'}")
    for st in pack.column_stats():
        total_stored += st["stored"]
        total_raw += st["raw"]
        print(f"{st['name']:24} {st['dtype']:8} {st['rows']:>10} "
              f"{st['chunks']:>6} {st['stored']:>12} {st['raw']:>12} "
              f"{','.join(st['codecs'])}")
    ratio = total_raw / total_stored if total_stored else 0
    print(f"{'TOTAL':24} {'':8} {'':>10} {'':>6} {total_stored:>12} "
          f"{total_raw:>12} ratio={ratio:.2f}x")
    db.close()


def _rewrite_block(args, **write_kwargs):
    """Shared rewrite loop: materialize in bounded batches, re-encode,
    write with the given write_block kwargs, mark the old block
    compacted. Writes the new block fully first; between the two writes
    pollers may briefly see both (the same transient-duplicate window
    normal compaction has -- result dedupe covers it)."""
    from ..block.builder import BlockBuilder, write_block

    db = _open_db(args.backend)
    meta = _require_block(db, args.tenant, args.block_id)
    blk = db.open_block(meta)
    n = meta.total_traces
    ids = blk.trace_index["trace.id"]
    b = BlockBuilder(args.tenant, compaction_level=meta.compaction_level)
    for lo in range(0, n, 1024):  # bounded memory: one batch decoded at a time
        sids = list(range(lo, min(lo + 1024, n)))
        for s, t in zip(sids, blk.materialize_traces(sids)):
            b.add_trace(ids[s].tobytes(), t)
    new = write_block(db.backend, b.finalize(), **write_kwargs)
    db.backend.mark_compacted(args.tenant, args.block_id)
    db.close()
    return meta, new


def cmd_rewrite_block(args):
    """Rewrite a block at the CURRENT encoding version/codec (reference:
    tempo-cli's convert/migrate role)."""
    _, new = _rewrite_block(args, codec=args.codec)
    print(f"rewrote {args.block_id} -> {new.block_id} "
          f"(codec={args.codec}, {new.total_traces} traces); "
          f"old block marked compacted")


def cmd_convert_block(args):
    """Rewrite one block at a TARGET encoding version (reference:
    cmd/tempo-cli/cmd-convert-block.go): open through the versioned
    seam, re-encode, write at --to. Used for forward-migrating vtpu1
    blocks (or producing vtpu1 blocks for a down-level fleet)."""
    from ..block.versioned import supported_versions

    if args.to not in supported_versions():
        raise SystemExit(
            f"unknown target version {args.to!r} (supported: {supported_versions()})")
    meta, new = _rewrite_block(args, version=args.to)
    print(f"converted {args.block_id} ({meta.version}) -> {new.block_id} "
          f"({new.version}, {new.total_traces} traces); old block marked compacted")


def main(argv=None):
    ap = argparse.ArgumentParser(prog="tempo-tpu-cli")
    ap.add_argument("--backend.path", dest="backend", default="./tempo-data")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("list-blocks", help="list blocks (all tenants or one)")
    p.add_argument("tenant", nargs="?", default="")
    p.set_defaults(fn=cmd_list_blocks)

    p = sub.add_parser("view-block", help="dump one block's meta")
    p.add_argument("tenant")
    p.add_argument("block_id")
    p.set_defaults(fn=cmd_view_block)

    p = sub.add_parser("query", help="trace-ID lookup against the backend")
    p.add_argument("tenant")
    p.add_argument("trace_id")
    p.set_defaults(fn=cmd_query_trace)

    p = sub.add_parser("search", help="search the backend")
    p.add_argument("tenant")
    p.add_argument("--tags", nargs="*", help="k=v pairs")
    p.add_argument("-q", help="TraceQL query")
    p.add_argument("--limit", type=int, default=20)
    p.add_argument("--concurrency", type=int, default=1,
                   help="run N identical queries in parallel through the "
                        "cross-query batching executor; latency/launch "
                        "summary on stderr")
    p.add_argument("--kernel-stats", dest="kernel_stats", action="store_true",
                   help="print kernel telemetry (compiles, routing) to stderr")
    p.set_defaults(fn=cmd_search)

    p = sub.add_parser("stream-search",
                       help="progressive search against a running instance "
                            "(/api/search?stream=true): partials on stderr "
                            "as shards land, final body on stdout")
    p.add_argument("target", help="base URL, e.g. http://localhost:3200")
    p.add_argument("--tenant", default="", help="X-Scope-OrgID header")
    p.add_argument("--tags", nargs="*", help="k=v pairs")
    p.add_argument("-q", help="TraceQL query")
    p.add_argument("--limit", type=int, default=20)
    p.add_argument("--recent", type=int, default=0, metavar="SECONDS",
                   help="query only the last N seconds (the live-head shape)")
    p.add_argument("--timeout", type=float, default=60.0)
    p.set_defaults(fn=cmd_stream_search)

    p = sub.add_parser("self-trace",
                       help="fetch + render one of the system's own query "
                            "timelines (the self tenant) as a span tree; "
                            "`latest` picks the most recent self-traced "
                            "query from /status/kernels")
    p.add_argument("trace_id", help="self-trace id (hex) or `latest`")
    p.add_argument("--target", default="",
                   help="base URL of a running instance (uses the system's "
                        "own find path incl. the live head); empty = read "
                        "flushed blocks from --backend.path")
    p.add_argument("--tenant", default="self",
                   help="self-tracing tenant (default: self)")
    p.add_argument("--timeout", type=float, default=30.0)
    p.set_defaults(fn=cmd_self_trace)

    p = sub.add_parser("profile",
                       help="continuous-profiling tooling: burst CPU "
                            "profile, device trace capture, lock-"
                            "contention table, artifact fetch/render")
    psub = p.add_subparsers(dest="profile_cmd", required=True)
    pp = psub.add_parser("cpu", help="burst CPU profile (/debug/profile)")
    pp.add_argument("--target", required=True,
                    help="base URL, e.g. http://localhost:3200")
    pp.add_argument("--seconds", type=float, default=2.0)
    pp.add_argument("--hz", type=float, default=200.0)
    pp.add_argument("--folded", action="store_true",
                    help="raw flamegraph-collapsed lines instead of the "
                         "hottest-stacks text")
    pp.add_argument("--internal-token", default="",
                    help="shared token for non-loopback targets")
    pp.set_defaults(fn=cmd_profile)
    pp = psub.add_parser("device",
                         help="record a jax.profiler device trace "
                              "(/debug/profile/device) and download the "
                              "zipped artifact")
    pp.add_argument("--target", required=True)
    pp.add_argument("--seconds", type=float, default=2.0)
    pp.add_argument("-o", "--output", default="",
                    help="output path (default: the artifact id)")
    pp.add_argument("--internal-token", default="")
    pp.set_defaults(fn=cmd_profile)
    pp = psub.add_parser("lock",
                         help="lock-contention table from /status/profile "
                              "(arm with TEMPO_LOCK_PROFILE=1)")
    pp.add_argument("--target", required=True)
    pp.add_argument("--internal-token", default="")
    pp.set_defaults(fn=cmd_profile)
    pp = psub.add_parser("artifact",
                         help="fetch one profile artifact by id (ids in "
                              "the slow-query log and /status/profile) "
                              "and render folded text or save binary")
    pp.add_argument("artifact_id")
    pp.add_argument("--target", required=True)
    pp.add_argument("-o", "--output", default="",
                    help="save raw bytes instead of rendering")
    pp.add_argument("--internal-token", default="")
    pp.set_defaults(fn=cmd_profile)

    p = sub.add_parser("calibrate",
                       help="measure host-vs-device crossovers (find race, "
                            "cold scan rate, live-head engines) and commit "
                            "them to the CostLedger for `auto` routing")
    p.add_argument("--tenant", default="",
                   help="tenant whose blocks the find race runs over "
                        "(default: first tenant with blocks)")
    p.add_argument("--ledger", default="",
                   help="ledger artifact path (default: TEMPO_COST_LEDGER "
                        "env, else <backend.path>/cost_ledger.json)")
    p.add_argument("--repeats", type=int, default=3,
                   help="timed repetitions per engine (best-of)")
    p.add_argument("--skip-live", action="store_true",
                   help="skip the synthetic live-head engine race")
    p.set_defaults(fn=cmd_calibrate)

    p = sub.add_parser("vulture",
                       help="run the continuous-verification prober "
                            "against a running instance (all probe "
                            "families, freshness, SLO verdicts)")
    p.add_argument("target", help="base URL, e.g. http://localhost:3200")
    p.add_argument("--tenant", default="", help="X-Scope-OrgID header")
    p.add_argument("--cycles", type=int, default=3)
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--visibility-timeout", type=float, default=15.0)
    p.add_argument("--flush-every", type=int, default=1,
                   help="cold-read probe cadence in cycles (0 = never)")
    p.add_argument("--internal-token", default="",
                   help="shared token for /flush on non-loopback targets")
    p.add_argument("--backend-path", default="",
                   help="storage path for fresh-reader cold probes")
    p.add_argument("--seed", type=int, default=None)
    p.set_defaults(fn=cmd_vulture)

    p = sub.add_parser("chaos",
                       help="fault-injection tooling: list sites, "
                            "validate a rules file, inject/clear rules "
                            "on a running instance")
    csub = p.add_subparsers(dest="chaos_cmd", required=True)
    cp = csub.add_parser("sites", help="list every injectable seam")
    cp.set_defaults(fn=cmd_chaos)
    cp = csub.add_parser("validate", help="parse + check a rules file")
    cp.add_argument("rules", help="JSON rules file")
    cp.set_defaults(fn=cmd_chaos)
    for name, hlp in (("inject", "swap the fault rules of a running "
                                 "instance (POST /internal/chaos)"),
                      ("status", "print /status/chaos")):
        cp = csub.add_parser(name, help=hlp)
        cp.add_argument("target", help="base URL, e.g. http://localhost:3200")
        cp.add_argument("--rules", default="", help="JSON rules file")
        cp.add_argument("--rule", default="",
                        help="one inline JSON rule (or a rule list)")
        cp.add_argument("--seed", type=int, default=None)
        cp.add_argument("--clear", action="store_true",
                        help="tear the fault plane down")
        cp.add_argument("--internal-token", default="",
                        help="shared token for non-loopback targets")
        cp.add_argument("--timeout", type=float, default=15.0)
        cp.set_defaults(fn=cmd_chaos)

    p = sub.add_parser("slo",
                       help="fetch /status/slo and render burn rates + "
                            "verdicts per objective (exit 1 unless ok)")
    p.add_argument("target", help="base URL, e.g. http://localhost:3200")
    p.add_argument("--json", action="store_true",
                   help="raw /status/slo JSON instead of the table")
    p.add_argument("--timeout", type=float, default=15.0)
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser("query-range",
                       help="TraceQL metrics range query against the backend")
    p.add_argument("tenant")
    p.add_argument("-q", required=True,
                   help='metrics query, e.g. \'{ span.foo = "bar" } | rate() by(resource.service.name)\'')
    p.add_argument("--start", type=float, default=None, help="unix seconds (default: end-3600)")
    p.add_argument("--end", type=float, default=None, help="unix seconds (default: now)")
    p.add_argument("--step", type=float, default=60.0, help="step seconds")
    p.add_argument("--kernel-stats", dest="kernel_stats", action="store_true",
                   help="print kernel telemetry (compiles, routing) to stderr")
    p.set_defaults(fn=cmd_query_range)

    p = sub.add_parser("gen", help="generate a synthetic block")
    p.add_argument("tenant")
    p.add_argument("--traces", type=int, default=100)
    p.add_argument("--spans", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(fn=cmd_gen)

    p = sub.add_parser("gen-bloom", help="regenerate a block's bloom filter")
    p.add_argument("tenant")
    p.add_argument("block_id")
    p.set_defaults(fn=cmd_gen_bloom)

    p = sub.add_parser("dump-columns", help="per-column layout of a block")
    p.add_argument("tenant")
    p.add_argument("block_id")
    p.set_defaults(fn=cmd_dump_columns)

    p = sub.add_parser("rewrite-block",
                       help="rewrite a block at the current version/codec")
    p.add_argument("tenant")
    p.add_argument("block_id")
    p.add_argument("--codec", default="zstd")
    p.set_defaults(fn=cmd_rewrite_block)

    p = sub.add_parser("convert-block",
                       help="rewrite a block at a target encoding version")
    p.add_argument("tenant")
    p.add_argument("block_id")
    p.add_argument("--to", default="vtpu2")
    p.set_defaults(fn=cmd_convert_block)

    args = ap.parse_args(argv)
    try:
        args.fn(args)
    except BrokenPipeError:  # output piped into head etc.
        sys.stderr.close()


if __name__ == "__main__":
    main()
