"""tempo-cli equivalent: offline block ops against a backend directory.

Reference: cmd/tempo-cli (kong command tree, main.go:40-79) -- list/view
blocks, query a backend directly without a running cluster.

Usage: python -m tempo_tpu.cli <command> ... --backend.path DIR
"""
