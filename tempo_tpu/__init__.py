"""tempo-tpu: a TPU-native distributed tracing backend.

A brand-new framework with the capabilities of Grafana Tempo (reference:
/root/reference): OTLP/Jaeger/Zipkin ingest sharded over a hash ring,
WAL-backed ingesters, immutable columnar trace blocks on object storage,
background compaction/retention, multi-tenant limits, a query-frontend /
querier read path, and a metrics-generator.

The differentiator: the read-side hot path -- trace-ID lookup, columnar
search with TraceQL predicate pushdown, compaction's bloom/index merge,
and span-metrics aggregation -- executes as jit-compiled JAX/XLA kernels,
sharded across a TPU mesh with `shard_map`, instead of Go iterator trees
on CPU.

Package layout (mirrors the reference's layer map, SURVEY.md section 1):
  wire/      L0: OTLP-compatible trace model + codecs
  backend/   L2: object-store abstraction (local, in-memory, ...)
  block/     L3: the `vtpu` columnar block format (device-friendly SoA)
  ops/       TPU kernels: predicate scans, segmented ops, bloom, lookup
  db/        L3: tempodb facade -- WAL, blocklist, compaction, retention
  traceql/   L4: TraceQL subset parser + device predicate planner
  parallel/  mesh/sharding: multi-chip find/search via shard_map
  services/  L5: distributor, ingester, querier, frontend, compactor
  generator/ metrics-generator (span-metrics, service-graphs)
  api/       HTTP API + param codecs
  cli/       offline block tools (tempo-cli equivalent)
"""

__version__ = "0.1.0"
