"""Load/soak rig: sustained concurrent write+query against a running
tempo-tpu instance with latency assertions.

The reference drives this with k6 (integration/bench/smoke_test.js:
checked write/read cycles; stress_test_write_path.js: sustained write
load with p95 thresholds). Same contract here, self-contained: N writer
threads push OTLP batches, M reader threads search + read back ids
that were written, for a wall-clock duration; the run FAILS (exit 1)
on any error, any written-then-unfindable trace at the end, or
latency percentiles above thresholds.

Mixed-tenant mode (--tenants N): writers round-robin across N tenants,
readers draw their tenant from a Zipf distribution (--zipf skew, rank 1
hottest) so a few heavy tenants dominate exactly like production read
traffic, and the report carries per-tenant p50/p95/p99 plus per-tenant
429 shed counts -- the harness the cache-affinity/QoS acceptance gates
run on. 429 responses count as sheds (the per-tenant QoS budget doing
its job), not errors.

Run against a live instance:
    python soak.py --target http://localhost:3200 --duration 60
or self-hosted (spawns a single-binary app on an ephemeral port):
    python soak.py --self-host --duration 30
mixed-tenant with QoS overrides:
    python soak.py --self-host --tenants 4 --overrides overrides.yaml
dashboard-shaped repeat traffic (result-cache acceptance):
    python soak.py --self-host --repeat-zipf 1.1 --duration 30
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
import urllib.error
import urllib.request


def _pct(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * p))]


def _lat_summary(xs) -> dict:
    return {
        "p50_ms": round(_pct(xs, 0.5) * 1e3, 2),
        "p95_ms": round(_pct(xs, 0.95) * 1e3, 2),
        "p99_ms": round(_pct(xs, 0.99) * 1e3, 2),
        "n": len(xs),
    }


class Soak:
    def __init__(self, target: str, writers: int, readers: int,
                 spans_per_trace: int = 8, batch: int = 5,
                 tenants: list[str] | None = None, zipf: float = 1.2,
                 live_tail: bool = False, query_target: str = "",
                 repeat_zipf: float = 0.0):
        self.target = target.rstrip("/")
        # split-role fleets write to the distributor and read from the
        # query-frontend; "" = one process serves both (today's default)
        self.query_target = (query_target or target).rstrip("/")
        self.writers = writers
        self.readers = readers
        self.spans_per_trace = spans_per_trace
        self.batch = batch
        # live-tail mode: searches ask for the most recent window only
        # (start=now-60s), the recent-data shape the live-head device
        # engine serves from the ingester's staged columns
        self.live_tail = live_tail
        # "" = single-tenant (no X-Scope-OrgID header), today's default
        self.tenants: list[str] = list(tenants) if tenants else [""]
        # Zipf read skew over tenant rank: weight 1/(rank+1)^s
        self.zipf_weights = [1.0 / (i + 1) ** zipf
                             for i in range(len(self.tenants))]
        self.lock = threading.Lock()
        self.written: dict[str, list[str]] = {t: [] for t in self.tenants}
        self.errors: list[str] = []
        self.write_lat: dict[str, list[float]] = {t: [] for t in self.tenants}
        self.search_lat: dict[str, list[float]] = {t: [] for t in self.tenants}
        self.find_lat: dict[str, list[float]] = {t: [] for t in self.tenants}
        self.sheds: dict[str, int] = {t: 0 for t in self.tenants}  # 429s
        self.found = 0
        self.not_yet = 0  # reads that raced ingest (retried at the end)
        # --repeat-zipf: dashboard-shaped read traffic -- a FIXED pool
        # of query templates drawn Zipf(s) by rank, so the same few
        # queries repeat exactly like auto-refreshing dashboard panels
        # and the result cache has something to hit. Each response is
        # classified by its X-Tempo-Cache header.
        self.repeat_zipf = repeat_zipf
        self.cache_lat: dict[str, list[float]] = {
            k: [] for k in ("hit", "extend", "miss", "off")}
        if repeat_zipf > 0:
            t0 = int(time.time())

            def hist(svc: str, off_s: int):
                # immutable historical window: end sits behind the
                # live window, so only a blocklist change invalidates
                return lambda: (f"/api/search?tags=service.name%3D{svc}"
                                f"&limit=20&start={t0 - off_s}&end={t0 - 60}")

            def edge(svc: str):
                # moving now-edge window: the auto-refresh panel shape
                # the incremental-extension path exists for
                def f():
                    now = int(time.time())
                    return (f"/api/search?tags=service.name%3D{svc}"
                            f"&limit=20&start={now - 600}&end={now}")
                return f

            self._qtemplates = (
                [hist(f"soak-svc-{i}", 3600) for i in range(4)]
                + [hist(f"soak-svc-{i}", 1800) for i in range(4)]
                + [edge("soak-svc-0"), edge("soak-svc-1")])
            self._qweights = [1.0 / (r + 1) ** repeat_zipf
                              for r in range(len(self._qtemplates))]

    def _headers(self, tenant: str, ctype: str = "") -> dict:
        h = {}
        if ctype:
            h["Content-Type"] = ctype
        if tenant:
            h["X-Scope-OrgID"] = tenant
        return h

    def _post(self, path: str, body: bytes, ctype="application/json",
              tenant: str = ""):
        req = urllib.request.Request(self.target + path, data=body,
                                     headers=self._headers(tenant, ctype))
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.read()

    def _get(self, path: str, tenant: str = ""):
        req = urllib.request.Request(self.query_target + path,
                                     headers=self._headers(tenant))
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.read()

    def _get_with_cache_header(self, path: str, tenant: str = ""):
        """GET returning (body, X-Tempo-Cache header) -- "" when the
        result cache is disabled or the target predates it."""
        req = urllib.request.Request(self.query_target + path,
                                     headers=self._headers(tenant))
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.read(), r.headers.get("X-Tempo-Cache", "")

    def _pick_tenant(self, rng: random.Random) -> str:
        if len(self.tenants) == 1:
            return self.tenants[0]
        return rng.choices(self.tenants, weights=self.zipf_weights)[0]

    def _trace_json(self, tid_hex: str, svc: str) -> dict:
        now = time.time_ns()
        spans = []
        for i in range(self.spans_per_trace):
            spans.append({
                "traceId": tid_hex,
                "spanId": os.urandom(8).hex(),
                "parentSpanId": spans[0]["spanId"] if spans else "",
                "name": f"op-{i % 4}",
                "startTimeUnixNano": str(now + i * 1000),
                "endTimeUnixNano": str(now + i * 1000 + 2_000_000),
                "attributes": [{"key": "i", "value": {"intValue": str(i)}}],
            })
        return {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": svc}}]},
            "scopeSpans": [{"scope": {"name": "soak"}, "spans": spans}],
        }]}

    def _writer(self, stop: threading.Event, wid: int):
        svc = f"soak-svc-{wid % 4}"
        tenant = self.tenants[wid % len(self.tenants)]
        # alternate transports: even writers push OTLP-proto (the raw
        # native-scan fast path, the production OTel transport), odd
        # writers push OTLP-JSON (the model path) -- the soak hammers
        # both write paths concurrently
        use_proto = wid % 2 == 0
        if use_proto:
            try:
                from tempo_tpu.wire import otlp_json, otlp_pb
            except ImportError as e:
                # --target mode may run where the package isn't importable;
                # a writer dying silently would pass the soak vacuously
                with self.lock:
                    self.errors.append(f"write: proto transport unavailable: {e}")
                return
        while not stop.is_set():
            ids = [os.urandom(16).hex() for _ in range(self.batch)]
            try:
                # bodies built BEFORE the timed window: write_lat measures
                # the POSTs, not client-side encoding
                bodies = []
                for tid in ids:
                    j = json.dumps(self._trace_json(tid, svc)).encode()
                    if use_proto:
                        bodies.append((otlp_pb.encode_trace(otlp_json.loads(j)),
                                       "application/x-protobuf"))
                    else:
                        bodies.append((j, "application/json"))
                t0 = time.perf_counter()
                posted, shed = [], 0
                for tid, (body, ctype) in zip(ids, bodies):
                    try:
                        self._post("/v1/traces", body, ctype=ctype,
                                   tenant=tenant)
                        posted.append(tid)
                    except urllib.error.HTTPError as e:
                        # an ingest-side 429 (rate limit from the same
                        # overrides file) is a shed doing its job, not a
                        # soak failure -- and its fast-fail must not
                        # enter the write percentiles
                        if e.code != 429:
                            raise
                        shed += 1
                dt = (time.perf_counter() - t0) / self.batch
                with self.lock:
                    if not shed:
                        self.write_lat[tenant].append(dt)
                    self.sheds[tenant] += shed
                    self.written[tenant].extend(posted)
            except Exception as e:
                with self.lock:
                    self.errors.append(f"write[{tenant}]: {type(e).__name__}: {e}")
                return

    def _reader(self, stop: threading.Event, rid: int):
        rng = random.Random(0x50AC + rid)
        while not stop.is_set():
            tenant = self._pick_tenant(rng)
            with self.lock:
                ids = self.written[tenant]
                tid = rng.choice(ids) if ids else None
            try:
                # a 429 shed is counted but its (fast-fail) latency is
                # NOT: percentiles must measure served reads, or a
                # mostly-shed tenant would report flattering numbers
                if tid is not None:
                    t0 = time.perf_counter()
                    shed = False
                    try:
                        self._get(f"/api/traces/{tid}", tenant=tenant)
                        with self.lock:
                            self.found += 1
                    except urllib.error.HTTPError as e:
                        if e.code == 429:  # QoS shed-load: counted, not fatal
                            shed = True
                            with self.lock:
                                self.sheds[tenant] += 1
                        elif e.code != 404:
                            raise
                        else:
                            with self.lock:  # raced ingest; re-checked at the end
                                self.not_yet += 1
                    if not shed:
                        with self.lock:
                            self.find_lat[tenant].append(time.perf_counter() - t0)
                outcome = None
                if self.repeat_zipf > 0:
                    path = rng.choices(self._qtemplates,
                                       weights=self._qweights)[0]()
                else:
                    path = "/api/search?tags=service.name%3Dsoak-svc-1&limit=20"
                    if self.live_tail:
                        now = int(time.time())
                        path += f"&start={now - 60}&end={now + 5}"
                t0 = time.perf_counter()
                shed = False
                try:
                    if self.repeat_zipf > 0:
                        _body, hdr = self._get_with_cache_header(
                            path, tenant=tenant)
                        outcome = hdr if hdr in ("hit", "extend", "miss") \
                            else "off"
                    else:
                        self._get(path, tenant=tenant)
                except urllib.error.HTTPError as e:
                    if e.code != 429:
                        raise
                    shed = True
                    with self.lock:
                        self.sheds[tenant] += 1
                if not shed:
                    dt = time.perf_counter() - t0
                    with self.lock:
                        self.search_lat[tenant].append(dt)
                        if outcome is not None:
                            self.cache_lat[outcome].append(dt)
            except Exception as e:
                with self.lock:
                    self.errors.append(f"read[{tenant}]: {type(e).__name__}: {e}")
                return
            time.sleep(0.01)

    def run(self, duration_s: float, settle_s: float = 5.0,
            max_write_p95_s: float = 1.0, max_search_p95_s: float = 3.0,
            sample_verify: int = 50) -> dict:
        stop = threading.Event()
        threads = [threading.Thread(target=self._writer, args=(stop, i), daemon=True)
                   for i in range(self.writers)]
        threads += [threading.Thread(target=self._reader, args=(stop, i), daemon=True)
                    for i in range(self.readers)]
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=20)

        time.sleep(settle_s)  # let live traces become queryable
        missing = []
        verified = 0
        per_tenant_verify = max(1, sample_verify // len(self.tenants))
        for tenant in self.tenants:
            sample = random.sample(self.written[tenant],
                                   min(per_tenant_verify, len(self.written[tenant])))
            verified += len(sample)
            for tid in sample:
                try:
                    self._get(f"/api/traces/{tid}", tenant=tenant)
                except Exception:
                    missing.append(tid)

        all_writes = [x for xs in self.write_lat.values() for x in xs]
        all_search = [x for xs in self.search_lat.values() for x in xs]
        all_find = [x for xs in self.find_lat.values() for x in xs]
        report = {
            "written": sum(len(v) for v in self.written.values()),
            "found_live": self.found,
            "raced_reads": self.not_yet,
            "errors": self.errors[:5],
            "error_count": len(self.errors),
            "write_p50_ms": round(_pct(all_writes, 0.5) * 1e3, 2),
            "write_p95_ms": round(_pct(all_writes, 0.95) * 1e3, 2),
            "search_p50_ms": round(_pct(all_search, 0.5) * 1e3, 2),
            "search_p95_ms": round(_pct(all_search, 0.95) * 1e3, 2),
            "search_p99_ms": round(_pct(all_search, 0.99) * 1e3, 2),
            "find_p50_ms": round(_pct(all_find, 0.5) * 1e3, 2),
            "sheds_429": sum(self.sheds.values()),
            "verified_sample": verified,
            "missing_after_settle": missing,
        }
        if len(self.tenants) > 1:
            # per-tenant QoS/affinity view: rank order == Zipf weight
            # order, so tenants[0] is the heavy tenant by construction
            report["tenants"] = {
                t or "single-tenant": {
                    "written": len(self.written[t]),
                    "sheds_429": self.sheds[t],
                    "search": _lat_summary(self.search_lat[t]),
                    "find": _lat_summary(self.find_lat[t]),
                    "write": _lat_summary(self.write_lat[t]),
                }
                for t in self.tenants
            }
        report["ok"] = (
            not self.errors
            and not missing
            and report["written"] > 0
            and _pct(all_writes, 0.95) <= max_write_p95_s
            and _pct(all_search, 0.95) <= max_search_p95_s
        )
        if self.repeat_zipf > 0:
            hits, ext = self.cache_lat["hit"], self.cache_lat["extend"]
            misses, off = self.cache_lat["miss"], self.cache_lat["off"]
            total = len(hits) + len(ext) + len(misses)
            cached = hits + ext
            report["result_cache"] = {
                "enabled": total > 0,  # 0 classified = kill switch off
                "requests": total + len(off),
                "hits": len(hits),
                "extensions": len(ext),
                "misses": len(misses),
                "uncached": len(off),
                "hit_rate": round(len(cached) / total, 3) if total else 0.0,
                "cached_p50_ms": round(_pct(cached, 0.5) * 1e3, 3),
                "cached_p95_ms": round(_pct(cached, 0.95) * 1e3, 3),
                "fresh_p50_ms": round(_pct(misses, 0.5) * 1e3, 2),
            }
            # the acceptance gate: dashboard-shaped traffic must
            # mostly hit (>= 50%) -- but only when the cache is on
            # (a kill-switch run measures the baseline, not the cache)
            if total >= 20 and len(cached) / total < 0.5:
                report["ok"] = False
                self.errors.append(
                    f"result_cache: hit rate {len(cached) / total:.2f} "
                    f"< 0.5 under repeat-zipf traffic")
                report["errors"] = self.errors[:5]
                report["error_count"] = len(self.errors)
        return report


# the default --chaos mix: transient backend 5xx at 5% plus a little
# injected RPC latency -- the faults the resilience plane (retries,
# hedging, shard degradation, breaker half-open) exists to mask. The
# soak must still pass end to end with this active.
DEFAULT_CHAOS_SPEC = json.dumps({
    "seed": 1,
    "rules": [
        {"site": "backend.read", "action": "error", "p": 0.05},
        {"site": "rpc.client", "action": "latency", "latency_s": 0.02,
         "p": 0.1},
    ],
})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("tempo-tpu-soak")
    ap.add_argument("--target", default="", help="base URL of a running instance")
    ap.add_argument("--query-target", default="",
                    help="base URL reads go to (fleet topologies: the "
                         "query-frontend; '' = same as --target)")
    ap.add_argument("--self-host", action="store_true",
                    help="spawn a single-binary app for the run")
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--writers", type=int, default=4)
    ap.add_argument("--readers", type=int, default=2)
    ap.add_argument("--tenants", type=int, default=1,
                    help="mixed-tenant mode: N tenants, Zipf-skewed reads")
    ap.add_argument("--zipf", type=float, default=1.2,
                    help="Zipf skew exponent for mixed-tenant read traffic")
    ap.add_argument("--overrides", default="",
                    help="per-tenant overrides YAML for the self-hosted app "
                         "(QoS budgets, limits)")
    ap.add_argument("--live-tail", action="store_true",
                    help="searches query only the most recent 60s window "
                         "(exercises the live-head device engine)")
    ap.add_argument("--repeat-zipf", type=float, default=0.0, metavar="S",
                    help="dashboard-shaped reads: draw searches from a "
                         "fixed template pool Zipf(S)-skewed by rank "
                         "(incl. a moving now-edge window), classify "
                         "each response by its X-Tempo-Cache header and "
                         "report result-cache hit rate + cached p50; "
                         "hit rate < 0.5 fails the run when the cache "
                         "is on")
    ap.add_argument("--vulture", action="store_true",
                    help="run the continuous-verification prober beside "
                         "the soak; its SLO verdicts + freshness "
                         "percentiles fold into the summary (probe "
                         "failures fail the run)")
    ap.add_argument("--vulture-interval", type=float, default=2.0)
    ap.add_argument("--generator", action="store_true",
                    help="fold generated-series freshness verdicts into "
                         "the summary: runs the vulture sidecar (if not "
                         "already on) with its span_metrics / "
                         "service_graph probe families and reports the "
                         "push->series-visible percentiles, the "
                         "series_visible SLO verdict and the target's "
                         "generator plane counters; generator probe "
                         "failures or a critical freshness SLO fail "
                         "the run")
    ap.add_argument("--chaos", nargs="?", const=DEFAULT_CHAOS_SPEC,
                    default="", metavar="SPEC",
                    help="run the soak under fault injection: SPEC is "
                         "inline JSON rules / a rules file for "
                         "TEMPO_CHAOS (bare --chaos = a transient 5%% "
                         "backend-fault + RPC-latency mix the retry/"
                         "hedge/breaker armor must mask); self-host "
                         "only -- the env reaches the spawned app")
    ap.add_argument("--write-p95", type=float, default=1.0)
    ap.add_argument("--search-p95", type=float, default=3.0)
    args = ap.parse_args(argv)

    tenants = ([f"soak-tenant-{i}" for i in range(args.tenants)]
               if args.tenants > 1 else None)

    proc = None
    target = args.target
    if args.self_host or not target:
        import subprocess
        import tempfile

        port = random.randint(20000, 40000)
        d = tempfile.mkdtemp(prefix="soak-")
        cmd = [sys.executable, "-m", "tempo_tpu.services.app", "--target=all",
               f"--storage.path={d}", f"--http.port={port}"]
        if tenants:
            cmd.append("--multitenancy")
        if args.overrides:
            cmd.append(f"--overrides.path={args.overrides}")
        env = {**os.environ, "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")}
        if args.chaos:
            env["TEMPO_CHAOS"] = args.chaos
        proc = subprocess.Popen(cmd, env=env)
        target = f"http://127.0.0.1:{port}"
        for _ in range(100):
            try:
                urllib.request.urlopen(target + "/ready", timeout=1)
                break
            except Exception:
                time.sleep(0.2)

    # vulture sidecar: black-box probes of every read path WHILE the
    # soak hammers the instance -- the combination the prober exists
    # for (correctness under load, not at rest). Runs in its own
    # thread against the same target/tenant.
    vult = vstop = vthread = None
    if args.vulture or args.generator:
        from tempo_tpu.vulture import Vulture, VultureConfig

        # Vulture itself disables cold-read /flush probes for remote
        # tokenless targets (loopback-trust guard), so a remote soak
        # still runs every other family
        vult = Vulture(VultureConfig(
            push_url=target, query_url=target,
            tenant=tenants[0] if tenants else "",
            visibility_timeout_s=10.0, flush_every=2, seed=1))
        vstop = threading.Event()

        def vloop():
            while not vstop.is_set():
                try:
                    vult.cycle()
                except Exception:  # a dying sidecar must not kill the soak
                    pass
                vstop.wait(args.vulture_interval)

        vthread = threading.Thread(target=vloop, daemon=True,
                                   name="soak-vulture")
        vthread.start()

    try:
        soak = Soak(target, args.writers, args.readers, tenants=tenants,
                    zipf=args.zipf, live_tail=args.live_tail,
                    query_target=args.query_target,
                    repeat_zipf=args.repeat_zipf)
        report = soak.run(args.duration, max_write_p95_s=args.write_p95,
                          max_search_p95_s=args.search_p95)
        if vult is not None:
            vstop.set()
            vthread.join(timeout=30)
            vs = vult.status()
            bad = sum(n for fam in vs["outcomes"].values()
                      for out, n in fam.items()
                      if out not in ("ok", "shed"))
            report["vulture"] = {
                "cycles": vs["cycles"],
                "probe_failures": bad,
                "outcomes": vs["outcomes"],
                "freshness": vs["freshness"],
                "slo_verdict": vs["slo"].get("verdict", "ok"),
                "slo": {name: {"verdict": o.get("verdict"),
                               "burn_rates": o.get("burn_rates")}
                        for name, o in vs["slo"].get("objectives", {}).items()},
                "failures": vs["failures"][:5],
            }
            report["ok"] = bool(report["ok"]) and bad == 0
            if args.generator:
                # series-freshness verdicts: the vulture generator
                # families' outcomes + the series_visible SLO beside
                # the target's own generator plane counters, so one
                # soak summary answers "are generated series fresh
                # and correct UNDER this load"
                fams = {f: vs["outcomes"].get(f, {})
                        for f in ("span_metrics", "service_graph")}
                gen_bad = sum(n for fam in fams.values()
                              for out, n in fam.items()
                              if out not in ("ok", "shed"))
                slo_obj = vs["slo"].get("objectives", {}).get(
                    "freshness-series_visible", {})
                try:
                    ks = json.loads(urllib.request.urlopen(
                        target + "/status/kernels", timeout=10).read())
                    tgt = ks.get("generator", {})
                except Exception:
                    tgt = {}
                report["generator"] = {
                    "series_freshness": vs["freshness"].get(
                        "series_visible", {}),
                    "slo_verdict": slo_obj.get("verdict"),
                    "burn_rates": slo_obj.get("burn_rates"),
                    "outcomes": fams,
                    "probe_failures": gen_bad,
                    "target": {k: tgt.get(k) for k in (
                        "windows", "window_spans", "edges_completed",
                        "unpaired", "expired", "freshness_avg_s",
                        "freshness_max_s")},
                }
                report["ok"] = (bool(report["ok"]) and gen_bad == 0
                                and slo_obj.get("verdict") != "critical")
        if args.chaos:
            # the proof artifact: how many faults were actually
            # injected (a chaos soak that injected nothing proves
            # nothing) next to the retry/hedge/breaker counters that
            # absorbed them
            if proc is None:
                print("soak: --chaos only arms a --self-host app; the "
                      "remote target keeps its own TEMPO_CHAOS",
                      file=sys.stderr)
            try:
                st = json.loads(urllib.request.urlopen(
                    target + "/status/chaos", timeout=10).read())
                report["chaos"] = {
                    "enabled": st.get("enabled", False),
                    "injected_total": st.get("injected_total", 0),
                    "retries": st.get("retries", {}),
                    "hedging": st.get("hedging", {}),
                    "breakers": {leg: b.get("state")
                                 for leg, b in st.get("breakers", {}).items()},
                }
                if proc is not None and not st.get("injected_total"):
                    report["ok"] = False
                    report.setdefault("errors", []).append(
                        "chaos: plane armed but zero faults injected")
            except Exception as e:
                report["chaos"] = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1
    finally:
        if vstop is not None:
            vstop.set()
        if proc is not None:
            proc.terminate()


if __name__ == "__main__":
    sys.exit(main())
