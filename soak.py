"""Load/soak rig: sustained concurrent write+query against a running
tempo-tpu instance with latency assertions.

The reference drives this with k6 (integration/bench/smoke_test.js:
checked write/read cycles; stress_test_write_path.js: sustained write
load with p95 thresholds). Same contract here, self-contained: N writer
threads push OTLP batches, M reader threads search + read back ids
that were written, for a wall-clock duration; the run FAILS (exit 1)
on any error, any written-then-unfindable trace at the end, or
latency percentiles above thresholds.

Run against a live instance:
    python soak.py --target http://localhost:3200 --duration 60
or self-hosted (spawns a single-binary app on an ephemeral port):
    python soak.py --self-host --duration 30
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
import urllib.request


def _pct(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * p))]


class Soak:
    def __init__(self, target: str, writers: int, readers: int,
                 spans_per_trace: int = 8, batch: int = 5):
        self.target = target.rstrip("/")
        self.writers = writers
        self.readers = readers
        self.spans_per_trace = spans_per_trace
        self.batch = batch
        self.lock = threading.Lock()
        self.written: list[str] = []  # hex trace ids pushed (ack'd)
        self.errors: list[str] = []
        self.write_lat: list[float] = []
        self.search_lat: list[float] = []
        self.find_lat: list[float] = []
        self.found = 0
        self.not_yet = 0  # reads that raced ingest (retried at the end)

    def _post(self, path: str, body: bytes, ctype="application/json"):
        req = urllib.request.Request(self.target + path, data=body,
                                     headers={"Content-Type": ctype})
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.read()

    def _get(self, path: str):
        with urllib.request.urlopen(self.target + path, timeout=15) as r:
            return r.read()

    def _trace_json(self, tid_hex: str, svc: str) -> dict:
        now = time.time_ns()
        spans = []
        for i in range(self.spans_per_trace):
            spans.append({
                "traceId": tid_hex,
                "spanId": os.urandom(8).hex(),
                "parentSpanId": spans[0]["spanId"] if spans else "",
                "name": f"op-{i % 4}",
                "startTimeUnixNano": str(now + i * 1000),
                "endTimeUnixNano": str(now + i * 1000 + 2_000_000),
                "attributes": [{"key": "i", "value": {"intValue": str(i)}}],
            })
        return {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": svc}}]},
            "scopeSpans": [{"scope": {"name": "soak"}, "spans": spans}],
        }]}

    def _writer(self, stop: threading.Event, wid: int):
        svc = f"soak-svc-{wid % 4}"
        # alternate transports: even writers push OTLP-proto (the raw
        # native-scan fast path, the production OTel transport), odd
        # writers push OTLP-JSON (the model path) -- the soak hammers
        # both write paths concurrently
        use_proto = wid % 2 == 0
        if use_proto:
            try:
                from tempo_tpu.wire import otlp_json, otlp_pb
            except ImportError as e:
                # --target mode may run where the package isn't importable;
                # a writer dying silently would pass the soak vacuously
                with self.lock:
                    self.errors.append(f"write: proto transport unavailable: {e}")
                return
        while not stop.is_set():
            ids = [os.urandom(16).hex() for _ in range(self.batch)]
            try:
                # bodies built BEFORE the timed window: write_lat measures
                # the POSTs, not client-side encoding
                bodies = []
                for tid in ids:
                    j = json.dumps(self._trace_json(tid, svc)).encode()
                    if use_proto:
                        bodies.append((otlp_pb.encode_trace(otlp_json.loads(j)),
                                       "application/x-protobuf"))
                    else:
                        bodies.append((j, "application/json"))
                t0 = time.perf_counter()
                for body, ctype in bodies:
                    self._post("/v1/traces", body, ctype=ctype)
                dt = (time.perf_counter() - t0) / self.batch
                with self.lock:
                    self.write_lat.append(dt)
                    self.written.extend(ids)
            except Exception as e:
                with self.lock:
                    self.errors.append(f"write: {type(e).__name__}: {e}")
                return

    def _reader(self, stop: threading.Event):
        while not stop.is_set():
            with self.lock:
                tid = random.choice(self.written) if self.written else None
            try:
                if tid is not None:
                    t0 = time.perf_counter()
                    try:
                        self._get(f"/api/traces/{tid}")
                        with self.lock:
                            self.found += 1
                    except urllib.error.HTTPError as e:
                        if e.code != 404:
                            raise
                        with self.lock:  # raced ingest; re-checked at the end
                            self.not_yet += 1
                    with self.lock:
                        self.find_lat.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                self._get("/api/search?tags=service.name%3Dsoak-svc-1&limit=20")
                with self.lock:
                    self.search_lat.append(time.perf_counter() - t0)
            except Exception as e:
                with self.lock:
                    self.errors.append(f"read: {type(e).__name__}: {e}")
                return
            time.sleep(0.01)

    def run(self, duration_s: float, settle_s: float = 5.0,
            max_write_p95_s: float = 1.0, max_search_p95_s: float = 3.0,
            sample_verify: int = 50) -> dict:
        stop = threading.Event()
        threads = [threading.Thread(target=self._writer, args=(stop, i), daemon=True)
                   for i in range(self.writers)]
        threads += [threading.Thread(target=self._reader, args=(stop,), daemon=True)
                    for _ in range(self.readers)]
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=20)

        time.sleep(settle_s)  # let live traces become queryable
        missing = []
        sample = random.sample(self.written, min(sample_verify, len(self.written)))
        for tid in sample:
            try:
                self._get(f"/api/traces/{tid}")
            except Exception:
                missing.append(tid)

        report = {
            "written": len(self.written),
            "found_live": self.found,
            "raced_reads": self.not_yet,
            "errors": self.errors[:5],
            "error_count": len(self.errors),
            "write_p50_ms": round(_pct(self.write_lat, 0.5) * 1e3, 2),
            "write_p95_ms": round(_pct(self.write_lat, 0.95) * 1e3, 2),
            "search_p50_ms": round(_pct(self.search_lat, 0.5) * 1e3, 2),
            "search_p95_ms": round(_pct(self.search_lat, 0.95) * 1e3, 2),
            "find_p50_ms": round(_pct(self.find_lat, 0.5) * 1e3, 2),
            "verified_sample": len(sample),
            "missing_after_settle": missing,
        }
        report["ok"] = (
            not self.errors
            and not missing
            and len(self.written) > 0
            and _pct(self.write_lat, 0.95) <= max_write_p95_s
            and _pct(self.search_lat, 0.95) <= max_search_p95_s
        )
        return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("tempo-tpu-soak")
    ap.add_argument("--target", default="", help="base URL of a running instance")
    ap.add_argument("--self-host", action="store_true",
                    help="spawn a single-binary app for the run")
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--writers", type=int, default=4)
    ap.add_argument("--readers", type=int, default=2)
    ap.add_argument("--write-p95", type=float, default=1.0)
    ap.add_argument("--search-p95", type=float, default=3.0)
    args = ap.parse_args(argv)

    proc = None
    target = args.target
    if args.self_host or not target:
        import subprocess
        import tempfile

        port = random.randint(20000, 40000)
        d = tempfile.mkdtemp(prefix="soak-")
        proc = subprocess.Popen(
            [sys.executable, "-m", "tempo_tpu.services.app", "--target=all",
             f"--storage.path={d}", f"--http.port={port}"],
            env={**os.environ, "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")},
        )
        target = f"http://127.0.0.1:{port}"
        for _ in range(100):
            try:
                urllib.request.urlopen(target + "/ready", timeout=1)
                break
            except Exception:
                time.sleep(0.2)

    try:
        soak = Soak(target, args.writers, args.readers)
        report = soak.run(args.duration, max_write_p95_s=args.write_p95,
                          max_search_p95_s=args.search_p95)
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1
    finally:
        if proc is not None:
            proc.terminate()


if __name__ == "__main__":
    sys.exit(main())
