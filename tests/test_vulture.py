"""Vulture consistency checker against an in-process single binary."""

import socket

import pytest

from tempo_tpu.services.app import App, AppConfig
from tempo_tpu.services.ingester import IngesterConfig
from tempo_tpu.vulture import Vulture


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_vulture_cycles(tmp_path):
    cfg = AppConfig(storage_path=str(tmp_path / "data"), http_port=_free_port(),
                    compaction_cycle_s=9999,
                    ingester=IngesterConfig(flush_check_period_s=9999))
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    try:
        v = Vulture(f"http://127.0.0.1:{cfg.http_port}",
                    f"http://127.0.0.1:{cfg.http_port}",
                    read_back_delay_s=0.05, seed=1)
        for _ in range(3):
            assert v.cycle()
        assert v.metrics.requests == 3
        assert v.metrics.notfound_byid == 0
        assert v.metrics.missing_spans == 0
        assert v.metrics.notfound_search == 0
        # an unknown trace id IS reported missing
        import urllib.request, urllib.error
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{cfg.http_port}/api/traces/{'ab' * 16}")
    finally:
        app.stop()
