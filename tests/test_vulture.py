"""Vulture continuous-verification plane against an in-process single
binary: clean-run coverage, the 429-shed outcome contract, the
injected-regression matrix (the plane's proof of value), the one-shot
self-hosted CLI mode, and the soak sidecar."""

import glob
import json
import os
import socket
import subprocess
import sys
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tempo_tpu.services.app import App, AppConfig
from tempo_tpu.services.ingester import IngesterConfig
from tempo_tpu.vulture import Vulture, VultureConfig

from test_observability import parse_openmetrics_strict


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _mk_app(tmp_path):
    cfg = AppConfig(storage_path=str(tmp_path / "store"),
                    http_port=_free_port(),
                    compaction_cycle_s=9999,
                    ingester=IngesterConfig(flush_check_period_s=9999))
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    return app, f"http://127.0.0.1:{cfg.http_port}", str(tmp_path / "store")


def _mk_vulture(base, storage, **kw):
    cfg = VultureConfig(
        push_url=base, query_url=base, backend_path=storage,
        visibility_timeout_s=kw.pop("visibility_timeout_s", 10.0),
        retry_interval_s=0.05, spans_per_trace=3, batch_ids=3,
        flush_every=1, seed=kw.pop("seed", 11), **kw)
    return Vulture(cfg)


def _outcomes(v: Vulture) -> dict:
    return v.status()["outcomes"]


def test_vulture_clean_cycles(tmp_path):
    """Two clean cycles: every probe family ok, freshness histograms
    populated for all three kinds, SLO objectives green, and vulture's
    own /metrics passes the strict OpenMetrics parse."""
    app, base, storage = _mk_app(tmp_path)
    try:
        v = _mk_vulture(base, storage)
        for _ in range(2):
            results = v.cycle()
            assert Vulture.ok(results), [
                (r.family, r.outcome, r.detail) for r in results
                if r.outcome != "ok"]
        st = v.status()
        assert st["cycles"] == 2
        for fam in ("push", "find_by_id", "find_batched", "search",
                    "live_head", "search_stream", "query_range",
                    "cold_read", "durability"):
            assert st["outcomes"].get(fam, {}).get("ok", 0) >= 1, (
                fam, st["outcomes"])
        for kind in ("live_visible", "searchable", "cold_readable"):
            assert st["freshness"][kind]["n"] >= 1, st["freshness"]
        assert st["ledger_entries"] >= 3  # cold probes feed durability
        assert st["slo"]["verdict"] == "ok"
        for name, obj in st["slo"]["objectives"].items():
            assert obj["verdict"] == "ok", (name, obj)
        fams = parse_openmetrics_strict(v.exposition())
        assert fams.get("tempo_vulture_probes") == "counter"
        assert fams.get("tempo_vulture_freshness_seconds") == "histogram"
        assert fams.get("tempo_vulture_slo_burn_rate") == "gauge"
        assert fams.get("tempo_vulture_slo_verdict") == "gauge"

        # vulture's own /metrics + /status endpoints serve the same
        port = _free_port()
        v.serve_metrics(port)
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        parse_openmetrics_strict(text)
        js = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=10))
        assert js["cycles"] == 2
        v.close()

        # an unknown trace id IS still a 404 through the app
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/api/traces/{'ab' * 16}")
    finally:
        app.stop()


def test_vulture_429_is_shed_not_error():
    """Regression (QoS interplay, PR 7): an HTTP 429 shed is its own
    outcome, excluded from the availability SLI -- a tenant at its
    budget must not page the on-call for data loss."""

    class Deny429(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _deny(self):
            body = b'{"error":"TooManyRequests"}'
            self.send_response(429)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        do_GET = do_POST = _deny

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Deny429)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        v = _mk_vulture(base, "", visibility_timeout_s=1.0)
        results = v.cycle()
        # the push was shed -> the cycle stops there, nothing is an error
        assert [r.outcome for r in results] == ["shed"]
        assert Vulture.ok(results)  # sheds do not fail the prober
        out = _outcomes(v)
        assert out["push"]["shed"] == 1
        assert all(o in ("ok", "shed")
                   for fam in out.values() for o in fam), out
        # availability SLI: sheds are neither good nor bad
        st = v.slo.evaluate()
        av = st["objectives"]["probe-availability"]
        assert av["good_total"] == 0 and av["bad_total"] == 0
        assert av["verdict"] == "ok"
    finally:
        srv.shutdown()


def test_vulture_injected_regression_matrix(tmp_path):
    """The plane's acceptance gate: three injected faults, each caught
    by its matching probe family within ONE probe cycle, the SLO
    verdict going critical; plus the app-side /status/slo burn for the
    fault that breaks the serving path itself."""
    from tempo_tpu.db.blocklist import Poller

    app, base, storage = _mk_app(tmp_path)
    try:
        v = _mk_vulture(base, storage, visibility_timeout_s=3.0)

        # ---- clean baseline: everything green
        results = v.cycle()
        assert Vulture.ok(results), [
            (r.family, r.outcome, r.detail) for r in results]
        assert v.status()["slo"]["verdict"] == "ok"
        app_slo = json.load(urllib.request.urlopen(base + "/status/slo",
                                                   timeout=10))
        assert app_slo["verdict"] == "ok"

        # ---- fault C: SKIP LIVE-STAGE REFRESH -- new pushes never
        # reach the live engine's staged tails. The search + live_head
        # families (the staged read paths) time out within the cycle;
        # by-id (hash map) and query_range (direct live fold) still
        # pass, localizing the fault.
        inst = app.ingester.instance("single-tenant")
        stager = inst.live_engine.stager
        orig_refresh = stager.refresh

        def skip_new(items, stage_device=True):
            return orig_refresh(
                {t: g for t, g in items.items() if t in stager.tails},
                stage_device=stage_device)

        stager.refresh = skip_new
        try:
            results = v.cycle()
        finally:
            stager.refresh = orig_refresh
        by_fam = {r.family: r for r in results}
        assert by_fam["search"].outcome == "timeout", (
            by_fam["search"].outcome, by_fam["search"].detail)
        assert by_fam["live_head"].outcome in ("timeout", "miss")
        assert by_fam["find_by_id"].outcome == "ok"  # fault localized
        assert by_fam["query_range"].outcome == "ok"
        assert v.status()["slo"]["verdict"] == "critical"
        assert (v.status()["slo"]["objectives"]["probe-availability"]
                ["burn_rates"]["5m"] > 14.4)

        # ---- fault B: STALL THE BLOCKLIST POLL -- pollers keep
        # serving a frozen snapshot, so the block this cycle flushes
        # never becomes visible to fresh readers. The cold_read family
        # (fresh TempoDB per attempt) times out within the cycle.
        frozen = Poller.poll(app.db.poller)
        orig_poll = Poller.poll
        Poller.poll = lambda self: frozen
        try:
            results = v.cycle()
        finally:
            Poller.poll = orig_poll
        by_fam = {r.family: r for r in results}
        assert by_fam["cold_read"].outcome == "timeout", (
            by_fam["cold_read"].outcome, by_fam["cold_read"].detail)
        assert by_fam["durability"].outcome == "ok"  # old blocks fine
        assert by_fam["search"].outcome == "ok"      # fault C cleared
        app.db.poll_now()  # resync after the stall

        # ---- fault A: DELETE A FLUSHED BLOCK OBJECT -- the durability
        # ledger's re-probe catches the loss within one cycle. Reader
        # caches are dropped to simulate the reader churn that makes
        # the deletion visible in production.
        removed = 0
        for path in glob.glob(os.path.join(storage, "single-tenant",
                                           "*", "data.vtpu")):
            os.remove(path)
            removed += 1
        assert removed >= 1
        with app.db._cache_lock:
            app.db._block_cache.clear()
        results = v.cycle()
        by_fam = {r.family: r for r in results}
        assert by_fam["durability"].outcome in ("miss", "corrupt"), (
            by_fam["durability"].outcome, by_fam["durability"].detail)
        # the failure report names the lost id (and best-effort links
        # the self-trace timeline of the query that failed)
        fail = [f for f in v.status()["failures"]
                if f["family"] == "durability"][-1]
        assert "id=" in fail["detail"]
        assert v.status()["slo"]["verdict"] == "critical"

        # the app's own SLO plane sees this one too (its find path is
        # serving 500s): drive a little client traffic at a lost id
        # and /status/slo goes critical on read availability
        lost = fail["detail"].split("id=", 1)[1].split(",", 1)[0]
        for _ in range(10):
            try:
                urllib.request.urlopen(f"{base}/api/traces/{lost}",
                                       timeout=15)
            except urllib.error.HTTPError:
                pass
        app_slo = json.load(urllib.request.urlopen(base + "/status/slo",
                                                   timeout=10))
        av = app_slo["objectives"]["read-availability"]
        assert av["verdict"] == "critical", av
        assert app_slo["verdict"] == "critical"
    finally:
        app.stop()


def test_vulture_self_hosted_one_shot():
    """The tier-1 CI wiring: `python -m tempo_tpu.vulture --self-hosted
    --cycles 3` runs the full probe surface against an in-process
    single binary and exits 0 with every cycle ok."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(
        [sys.executable, "-m", "tempo_tpu.vulture", "--self-hosted",
         "--cycles", "3", "--interval", "0.1",
         "--visibility-timeout", "10", "--seed", "5"],
        capture_output=True, text=True, timeout=420, env=env)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    cycles = [json.loads(ln) for ln in out.stdout.splitlines()
              if ln.startswith('{"cycle"')]
    assert len(cycles) == 3
    assert all(c["ok"] for c in cycles), cycles
    summary = json.loads(
        out.stdout[out.stdout.index('{\n  "summary"'):])["summary"]
    assert summary["slo"]["verdict"] == "ok"
    assert summary["freshness"]["cold_readable"]["n"] >= 1


def test_soak_vulture_sidecar(tmp_path):
    """soak --vulture runs the prober beside the mixed read/write load
    and folds SLO verdicts + freshness percentiles into the summary."""
    import soak

    app, base, _storage = _mk_app(tmp_path)
    try:
        rc = None
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = soak.main(["--target", base, "--duration", "3",
                            "--writers", "1", "--readers", "1",
                            "--vulture", "--vulture-interval", "0.5"])
        report = json.loads(buf.getvalue())
        assert rc == 0, report
        assert report["ok"]
        vs = report["vulture"]
        assert vs["cycles"] >= 1
        assert vs["probe_failures"] == 0
        assert vs["slo_verdict"] == "ok"
        assert "searchable" in vs["freshness"]
    finally:
        app.stop()
