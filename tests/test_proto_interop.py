"""Wire-format interop: protoc independently parses what the hand-rolled
codecs emit.

The OTLP/OC codecs in wire/ are written against the public proto specs
with no protoc toolchain at runtime; these tests use the toolchain's
`protoc --decode_raw` as an INDEPENDENT parser to prove the emitted
bytes are well-formed protobuf with the documented field numbers --
the interop evidence that an off-the-shelf OTel SDK can talk to the
receivers (conformance with our own decoder alone would not catch a
field-numbering bug on both sides)."""

import os
import shutil
import subprocess

import pytest

from tempo_tpu.util.testdata import make_trace
from tempo_tpu.wire import otlp_pb

protoc = shutil.which("protoc")
# Interop evidence must not vanish silently on an image change: fail
# loudly when protoc is missing unless the skip is explicitly requested.
if protoc is None and not os.environ.get("TEMPO_TPU_ALLOW_PROTOC_SKIP"):
    pytest.fail("protoc not on PATH -- interop suite cannot run "
                "(set TEMPO_TPU_ALLOW_PROTOC_SKIP=1 to skip deliberately)",
                pytrace=False)
pytestmark = pytest.mark.skipif(protoc is None, reason="protoc not available")


def _decode_raw(data: bytes) -> str:
    out = subprocess.run([protoc, "--decode_raw"], input=data,
                         capture_output=True, timeout=30)
    assert out.returncode == 0, out.stderr.decode()
    return out.stdout.decode()


def test_otlp_trace_wire_parses():
    tid = bytes(range(16))
    tr = make_trace(3, trace_id=tid, n_spans=4)
    text = _decode_raw(otlp_pb.encode_trace(tr))
    # resource_spans = 1 { resource = 1 { attributes = 1 {...} },
    #                      scope_spans = 2 { spans = 2 {...} } }
    assert text.startswith("1 {")
    # the trace id bytes surface inside span field 1
    assert "1:" in text and "2 {" in text
    # span start/end are fixed64 field 7/8: protoc renders `7: 0x...`
    assert "7: 0x" in text and "8: 0x" in text


def test_otlp_export_request_roundtrip_fields():
    """Field-level equality: every span protoc sees carries the same
    kind (6) and status nesting (15) our decoder reads back."""
    tid = b"\x42" * 16
    tr = make_trace(9, trace_id=tid, n_spans=6)
    data = otlp_pb.encode_trace(tr)
    text = _decode_raw(data)
    n_spans = tr.span_count()
    # each span submessage renders one `5: "name"` (span.name, field 5)
    assert text.count('5: "') >= n_spans
    back = otlp_pb.decode_trace(data)
    assert back.span_count() == n_spans


def test_segment_splice_bytes_parse():
    """Segments produced by the raw-ingest byte splicer are themselves
    protoc-parseable TracesData."""
    from tempo_tpu.wire.model import Trace
    from tempo_tpu.wire.otlp_splice import split_by_trace
    from tempo_tpu.wire.segment import segment_payload

    t1 = make_trace(1, trace_id=b"\x01" * 16, n_spans=3)
    t2 = make_trace(2, trace_id=b"\x02" * 16, n_spans=2)
    mixed = Trace(t1.resource_spans + t2.resource_spans)
    out = split_by_trace(otlp_pb.encode_trace(mixed))
    if out is None:
        pytest.skip("native scanner unavailable")
    segs, n_spans = out
    assert n_spans == 5 and len(segs) == 2
    for tid, (_, _, seg) in segs.items():
        text = _decode_raw(segment_payload(seg))
        assert text.startswith("1 {"), tid.hex()


def test_opencensus_decode_against_protoc_encode(tmp_path):
    """protoc --encode produces authoritative OpenCensus Span bytes from
    the spec's field numbers (mirrored from the census-instrumentation
    codegen); our decoder must read them. This is the direction the
    self-consistent receiver test can't check -- an OC numbering bug on
    both encode and decode sides cancels out (exactly the bug class a
    review caught in this receiver's first draft)."""
    proto = tmp_path / "oc_span.proto"
    proto.write_text("""
syntax = "proto3";
package opencensus.proto.trace.v1;
message TruncatableString { string value = 1; }
message AttributeValue {
  oneof value { TruncatableString string_value = 1; int64 int_value = 2;
                bool bool_value = 3; double double_value = 4; }
}
message Attributes {
  map<string, AttributeValue> attribute_map = 1;
  int32 dropped_attributes_count = 2;
}
message Timestamp { int64 seconds = 1; int32 nanos = 2; }
message Status { int32 code = 1; string message = 2; }
message Span {
  bytes trace_id = 1;
  bytes span_id = 2;
  bytes parent_span_id = 3;
  TruncatableString name = 4;
  Timestamp start_time = 5;
  Timestamp end_time = 6;
  Attributes attributes = 7;
  Status status = 11;
  enum SpanKind { SPAN_KIND_UNSPECIFIED = 0; SERVER = 1; CLIENT = 2; }
  SpanKind kind = 14;
  Resource resource = 16;
}
message Resource { string type = 1; map<string, string> labels = 2; }
""")
    textpb = """
trace_id: "0123456789abcdef"
span_id: "01234567"
parent_span_id: "76543210"
name { value: "authoritative-span" }
start_time { seconds: 1700000000 nanos: 5 }
end_time { seconds: 1700000001 nanos: 7 }
attributes {
  attribute_map { key: "k1" value { string_value { value: "v1" } } }
  attribute_map { key: "k2" value { int_value: -3 } }
  attribute_map { key: "k3" value { double_value: 2.5 } }
}
status { code: 13 message: "boom" }
kind: CLIENT
resource { type: "container" labels { key: "region" value: "eu" } }
"""
    out = subprocess.run(
        [protoc, f"--proto_path={tmp_path}", "oc_span.proto",
         "--encode=opencensus.proto.trace.v1.Span"],
        input=textpb.encode(), capture_output=True, timeout=30)
    assert out.returncode == 0, out.stderr.decode()

    from tempo_tpu.wire import oc_pb
    from tempo_tpu.wire.model import SpanKind, StatusCode

    sp, res = oc_pb.decode_span(out.stdout)
    assert sp.trace_id == b"0123456789abcdef"
    assert sp.span_id == b"01234567"
    assert sp.parent_span_id == b"76543210"
    assert sp.name == "authoritative-span"
    assert sp.start_unix_nano == 1700000000 * 10**9 + 5
    assert sp.end_unix_nano == 1700000001 * 10**9 + 7
    assert sp.attrs == {"k1": "v1", "k2": -3, "k3": 2.5}
    assert sp.kind == SpanKind.CLIENT
    assert sp.status_code == StatusCode.ERROR and sp.status_message == "boom"
    assert res == {"opencensus.resourcetype": "container", "region": "eu"}


def test_otlp_decode_against_protoc_encode(tmp_path):
    """Same authoritative-bytes check for the OTLP decoder: protoc
    encodes a spec-mirrored opentelemetry Span; our decoder reads it."""
    proto = tmp_path / "otlp_span.proto"
    proto.write_text("""
syntax = "proto3";
package opentelemetry.proto.trace.v1;
message AnyValue {
  oneof value { string string_value = 1; bool bool_value = 2;
                int64 int_value = 3; double double_value = 4; }
}
message KeyValue { string key = 1; AnyValue value = 2; }
message Status {
  string message = 2;
  enum StatusCode { STATUS_CODE_UNSET = 0; STATUS_CODE_OK = 1;
                    STATUS_CODE_ERROR = 2; }
  StatusCode code = 3;
}
message Span {
  bytes trace_id = 1;
  bytes span_id = 2;
  string trace_state = 3;
  bytes parent_span_id = 4;
  string name = 5;
  enum SpanKind { UNSPECIFIED = 0; INTERNAL = 1; SERVER = 2; CLIENT = 3;
                  PRODUCER = 4; CONSUMER = 5; }
  SpanKind kind = 6;
  fixed64 start_time_unix_nano = 7;
  fixed64 end_time_unix_nano = 8;
  repeated KeyValue attributes = 9;
  Status status = 15;
}
""")
    textpb = """
trace_id: "fedcba9876543210"
span_id: "abcd0123"
trace_state: "a=b"
name: "authoritative-otlp"
kind: PRODUCER
start_time_unix_nano: 1700000000000000005
end_time_unix_nano: 1700000000000000777
attributes { key: "s" value { string_value: "x" } }
attributes { key: "i" value { int_value: 42 } }
attributes { key: "b" value { bool_value: true } }
status { code: STATUS_CODE_ERROR message: "deadline" }
"""
    out = subprocess.run(
        [protoc, f"--proto_path={tmp_path}", "otlp_span.proto",
         "--encode=opentelemetry.proto.trace.v1.Span"],
        input=textpb.encode(), capture_output=True, timeout=30)
    assert out.returncode == 0, out.stderr.decode()

    from tempo_tpu.wire.model import SpanKind, StatusCode

    sp = otlp_pb.decode_span(out.stdout)
    assert sp.trace_id == b"fedcba9876543210"
    assert sp.span_id == b"abcd0123"
    assert sp.trace_state == "a=b"
    assert sp.name == "authoritative-otlp"
    assert sp.kind == SpanKind.PRODUCER
    assert sp.start_unix_nano == 1700000000000000005
    assert sp.end_unix_nano == 1700000000000000777
    assert sp.attrs == {"s": "x", "i": 42, "b": True}
    assert sp.status_code == StatusCode.ERROR and sp.status_message == "deadline"
