"""Wire-format interop: protoc independently parses what the hand-rolled
codecs emit.

The OTLP/OC codecs in wire/ are written against the public proto specs
with no protoc toolchain at runtime; these tests use the toolchain's
`protoc --decode_raw` as an INDEPENDENT parser to prove the emitted
bytes are well-formed protobuf with the documented field numbers --
the interop evidence that an off-the-shelf OTel SDK can talk to the
receivers (conformance with our own decoder alone would not catch a
field-numbering bug on both sides)."""

import shutil
import subprocess

import pytest

from tempo_tpu.util.testdata import make_trace
from tempo_tpu.wire import otlp_pb

protoc = shutil.which("protoc")
pytestmark = pytest.mark.skipif(protoc is None, reason="protoc not available")


def _decode_raw(data: bytes) -> str:
    out = subprocess.run([protoc, "--decode_raw"], input=data,
                         capture_output=True, timeout=30)
    assert out.returncode == 0, out.stderr.decode()
    return out.stdout.decode()


def test_otlp_trace_wire_parses():
    tid = bytes(range(16))
    tr = make_trace(3, trace_id=tid, n_spans=4)
    text = _decode_raw(otlp_pb.encode_trace(tr))
    # resource_spans = 1 { resource = 1 { attributes = 1 {...} },
    #                      scope_spans = 2 { spans = 2 {...} } }
    assert text.startswith("1 {")
    # the trace id bytes surface inside span field 1
    assert "1:" in text and "2 {" in text
    # span start/end are fixed64 field 7/8: protoc renders `7: 0x...`
    assert "7: 0x" in text and "8: 0x" in text


def test_otlp_export_request_roundtrip_fields():
    """Field-level equality: every span protoc sees carries the same
    kind (6) and status nesting (15) our decoder reads back."""
    tid = b"\x42" * 16
    tr = make_trace(9, trace_id=tid, n_spans=6)
    data = otlp_pb.encode_trace(tr)
    text = _decode_raw(data)
    n_spans = tr.span_count()
    # each span submessage renders one `5: "name"` (span.name, field 5)
    assert text.count('5: "') >= n_spans
    back = otlp_pb.decode_trace(data)
    assert back.span_count() == n_spans


def test_segment_splice_bytes_parse():
    """Segments produced by the raw-ingest byte splicer are themselves
    protoc-parseable TracesData."""
    from tempo_tpu.wire.model import Trace
    from tempo_tpu.wire.otlp_splice import split_by_trace
    from tempo_tpu.wire.segment import segment_payload

    t1 = make_trace(1, trace_id=b"\x01" * 16, n_spans=3)
    t2 = make_trace(2, trace_id=b"\x02" * 16, n_spans=2)
    mixed = Trace(t1.resource_spans + t2.resource_spans)
    out = split_by_trace(otlp_pb.encode_trace(mixed))
    if out is None:
        pytest.skip("native scanner unavailable")
    segs, n_spans = out
    assert n_spans == 5 and len(segs) == 2
    for tid, (_, _, seg) in segs.items():
        text = _decode_raw(segment_payload(seg))
        assert text.startswith("1 {"), tid.hex()
