"""Conformance: the bench's synthetic block is indistinguishable from a
builder-produced block to the read path (same column set, working find +
search), so bench numbers measure the real format."""

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from bench import synth_block  # noqa: E402

from tempo_tpu.backend import MemBackend
from tempo_tpu.block import build_block_from_traces
from tempo_tpu.block.reader import BackendBlock, open_block
from tempo_tpu.db.search import SearchRequest, search_block
from tempo_tpu.util.testdata import make_traces


def test_synth_block_matches_builder_columns():
    be = MemBackend()
    rng = np.random.default_rng(1)
    meta, ids = synth_block(be, "t", rng, 64, 4, n_res=8)
    synth_names = set(BackendBlock(be, meta).pack.names())

    be2 = MemBackend()
    m2 = build_block_from_traces(be2, "t", make_traces(8, seed=2))
    built_names = set(BackendBlock(be2, m2).pack.names())
    assert synth_names == built_names


def test_synth_block_find_and_search():
    be = MemBackend()
    rng = np.random.default_rng(3)
    meta, ids = synth_block(be, "t", rng, 128, 8, n_res=16)
    blk = open_block(be, "t", meta.block_id)
    # find every 10th id
    for i in range(0, 128, 10):
        t = blk.find_trace_by_id(ids[i].tobytes())
        assert t is not None and t.span_count() == 8
    assert blk.find_trace_by_id(b"\x00" * 16) is None
    # search on the dedicated service column
    resp = search_block(blk, SearchRequest(tags={"service.name": "svc-003"}, limit=1000))
    svc_col = blk.pack.read("res.service_id")
    res_idx = blk.pack.read("span.res_idx")
    sid_col = blk.pack.read("span.trace_sid")
    code = blk.dictionary.lookup("svc-003")
    expect = {ids[s].tobytes().hex()
              for s in np.unique(sid_col[svc_col[res_idx] == code])}
    assert {r.trace_id for r in resp.traces} == expect


def test_tel_close_workers_normalizes_device_time_share(monkeypatch):
    """Concurrent sections accumulate device seconds across Q threads
    while wall time doesn't: without the workers divisor the share reads
    ~Q (BENCH_r06 search_concurrent reported 3.85). With it, a section
    whose threads were device-busy the whole time reads <= ~1."""
    import time as _time

    from bench import _tel_close
    from tempo_tpu.util import kerneltel as kt

    mark = (0, 0.0, _time.perf_counter() - 0.1)  # section wall ~0.1s
    # 4 threads x ~0.09s device time each inside that 0.1s wall
    monkeypatch.setattr(kt.TEL, "totals", lambda: (0, 0.36))
    raw = _tel_close(mark)
    assert raw["device_time_share"] > 2.0  # the r06 artifact, reproduced
    share = _tel_close(mark, workers=4)["device_time_share"]
    assert 0.0 < share <= 1.05
    assert abs(share - raw["device_time_share"] / 4) < 0.05
