"""S3-compatible backend against an in-process fake S3 server, plus the
cache/hedging wrappers.

The fake server implements the REST subset the backend uses (PUT/GET
with Range/DELETE/ListObjectsV2 with delimiter+continuation) -- the
role minio plays in the reference's e2e suite (integration/e2e/backend).
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import pytest

from tempo_tpu.backend import DoesNotExist, open_backend
from tempo_tpu.backend.cache import CachedBackend, HedgedBackend
from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.backend.s3 import S3Backend
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.util.testdata import make_traces

TENANT = "t-s3"


class _FakeS3(BaseHTTPRequestHandler):
    store: dict[str, bytes] = {}
    lock = threading.Lock()
    secret = "sk"  # must match the client credentials in these tests

    def log_message(self, *a):
        pass

    def _check_auth(self) -> bool:
        """Recompute SigV4 from the RAW request with the shared secret
        (tests/test_backend_auth.verify_sigv4_request): a signer bug now
        fails every backend test instead of passing silently."""
        from test_backend_auth import verify_sigv4_request

        if verify_sigv4_request(self.command, self.path, dict(self.headers),
                                self.secret):
            return True
        self.send_response(403)
        self.send_header("Content-Length", "0")
        self.end_headers()
        return False

    def _key(self):
        # /bucket/key...
        path = unquote(urlparse(self.path).path)
        parts = path.lstrip("/").split("/", 1)
        return parts[1] if len(parts) > 1 else ""

    def do_PUT(self):
        if not self._check_auth():
            return
        ln = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(ln)
        # the signed payload hash must also MATCH the actual body, or a
        # signer hashing the wrong bytes would still pass (real S3:
        # XAmzContentSHA256Mismatch)
        import hashlib as _hashlib

        want = self.headers.get("x-amz-content-sha256", "")
        if want != _hashlib.sha256(body).hexdigest():
            self.send_response(403)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        copy_src = self.headers.get("x-amz-copy-source", "")
        if copy_src:
            # server-side CopyObject: /bucket/key -> this key
            src_key = unquote(copy_src).lstrip("/").split("/", 1)[1]
            with self.lock:
                data = self.store.get(src_key)
                if data is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.store[self._key()] = data
            resp = b"<CopyObjectResult><ETag>x</ETag></CopyObjectResult>"
            self.send_response(200)
            self.send_header("Content-Length", str(len(resp)))
            self.end_headers()
            self.wfile.write(resp)
            return
        with self.lock:
            self.store[self._key()] = body
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        if not self._check_auth():
            return
        with self.lock:
            self.store.pop(self._key(), None)
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        if not self._check_auth():
            return
        u = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        if q.get("list-type") == "2":
            return self._list(q)
        key = self._key()
        with self.lock:
            data = self.store.get(key)
        if data is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        rng = self.headers.get("Range")
        status = 200
        if rng and rng.startswith("bytes="):
            lo, hi = rng[6:].split("-")
            data = data[int(lo): int(hi) + 1]
            status = 206
        self.send_response(status)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _list(self, q):
        prefix = q.get("prefix", "")
        delim = q.get("delimiter", "")
        with self.lock:
            keys = sorted(k for k in self.store if k.startswith(prefix))
        contents, prefixes = [], []
        seen = set()
        for k in keys:
            rest = k[len(prefix):]
            if delim and delim in rest:
                p = prefix + rest.split(delim)[0] + delim
                if p not in seen:
                    seen.add(p)
                    prefixes.append(p)
            else:
                contents.append(k)
        body = ['<?xml version="1.0"?><ListBucketResult>']
        body.append("<IsTruncated>false</IsTruncated>")
        for k in contents:
            body.append(f"<Contents><Key>{k}</Key></Contents>")
        for p in prefixes:
            body.append(f"<CommonPrefixes><Prefix>{p}</Prefix></CommonPrefixes>")
        body.append("</ListBucketResult>")
        data = "".join(body).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/xml")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture(scope="module")
def s3_server():
    _FakeS3.store = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


@pytest.fixture()
def s3(s3_server):
    _FakeS3.store.clear()
    return S3Backend(s3_server, "bkt", access_key="ak", secret_key="sk", prefix="traces")


def test_s3_object_roundtrip(s3):
    s3.write(TENANT, "blk-1", "meta.json", b"{}")
    s3.write(TENANT, "blk-1", "data.vtpu", bytes(range(256)) * 4)
    assert s3.read(TENANT, "blk-1", "meta.json") == b"{}"
    assert s3.read_range(TENANT, "blk-1", "data.vtpu", 10, 5) == bytes(range(10, 15))
    assert s3.tenants() == [TENANT]
    assert s3.blocks(TENANT) == ["blk-1"]
    with pytest.raises(DoesNotExist):
        s3.read(TENANT, "blk-1", "nope")
    s3.mark_compacted(TENANT, "blk-1")
    assert s3.has_object(TENANT, "blk-1", "meta.compacted.json")
    assert not s3.has_object(TENANT, "blk-1", "meta.json")
    s3.delete_block(TENANT, "blk-1")
    assert s3.blocks(TENANT) == []


def test_s3_server_side_copy(s3):
    """copy_object issues a signed x-amz-copy-source PUT: bytes land
    under the destination without transiting the client, and a missing
    source surfaces as DoesNotExist."""
    payload = bytes(range(256)) * 8
    s3.write(TENANT, "blk-src", "data.vtpu", payload)
    s3.copy_object(TENANT, "blk-src", "data.vtpu", "blk-dst/p0")
    assert s3.read(TENANT, "blk-dst/p0", "data.vtpu") == payload
    with pytest.raises(DoesNotExist):
        s3.copy_object(TENANT, "blk-src", "missing", "blk-dst/p1")


def test_tempodb_over_s3(s3, tmp_path):
    """Full block write/find/search/compact cycle over the S3 REST path."""
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal")), backend=s3)
    traces1 = make_traces(15, seed=1, n_spans=4)
    traces2 = make_traces(15, seed=2, n_spans=4)
    db.write_block(TENANT, traces1)
    db.write_block(TENANT, traces2)
    for tid, t in traces1[:3] + traces2[:3]:
        got = db.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == t.span_count()
    from tempo_tpu.db.search import SearchRequest

    resp = db.search(TENANT, SearchRequest(tags={"service.name": "db"}, limit=100))
    assert resp.traces
    # a fresh reader over the same bucket discovers the blocks (poller path)
    db2 = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal2")), backend=s3)
    db2.poll_now()
    assert len(db2.blocklist.metas(TENANT)) == 2
    db.close()
    db2.close()


def test_open_backend_s3(s3_server):
    b = open_backend({"backend": "s3", "endpoint": s3_server, "bucket": "bkt",
                      "access_key": "ak", "secret_key": "sk"})
    b.write("t", "b1", "meta.json", b"x")
    assert b.read("t", "b1", "meta.json") == b"x"  # through the cache wrapper
    assert isinstance(b, CachedBackend)


def test_cached_backend_policy():
    mem = MemBackend()
    c = CachedBackend(mem)
    c.write("t", "b", "bloom-0", b"BLOOM")
    c.write("t", "b", "data.vtpu", b"D" * 100)
    assert c.read("t", "b", "bloom-0") == b"BLOOM"
    assert c.read("t", "b", "bloom-0") == b"BLOOM"
    assert c.hits == 1  # second bloom read cached
    # bulk object reads are not cached
    before = c.hits
    c.read("t", "b", "data.vtpu")
    c.read("t", "b", "data.vtpu")
    assert c.hits == before
    # small ranges cache, writes invalidate
    assert c.read_range("t", "b", "data.vtpu", 0, 10) == b"D" * 10
    assert c.read_range("t", "b", "data.vtpu", 0, 10) == b"D" * 10
    assert c.hits == before + 1
    c.write("t", "b", "data.vtpu", b"E" * 100)
    assert c.read_range("t", "b", "data.vtpu", 0, 10) == b"E" * 10


def test_hedged_backend_first_result_wins():
    import time

    class Slow(MemBackend):
        def __init__(self):
            super().__init__()
            self.calls = 0

        def read(self, tenant, block_id, name):
            self.calls += 1
            if self.calls == 1:
                time.sleep(0.4)  # slow primary
            return super().read(tenant, block_id, name)

    s = Slow()
    s.write("t", "b", "meta.json", b"M")
    h = HedgedBackend(s, hedge_after_s=0.05)
    t0 = time.monotonic()
    assert h.read("t", "b", "meta.json") == b"M"
    assert time.monotonic() - t0 < 0.35  # hedge answered before the slow leg
    assert h.hedged_requests == 1


def test_serverless_handler(s3_server, tmp_path):
    """Stateless one-shard search handler over the S3 backend."""
    from tempo_tpu.serverless import handler

    _FakeS3.store.clear()
    s3b = S3Backend(s3_server, "bkt", access_key="ak", secret_key="sk")
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal")), backend=s3b)
    traces = make_traces(20, seed=8, n_spans=4)
    meta = db.write_block(TENANT, traces)
    db.close()

    event = {
        "backend": {"backend": "s3", "endpoint": s3_server, "bucket": "bkt",
                    "access_key": "ak", "secret_key": "sk"},
        "tenant": TENANT,
        "block_id": meta.block_id,
        "groups": None,
        "search": {"tags": {"service.name": "db"}, "limit": 100},
    }
    out = handler(event)
    expect = {
        tid.hex() for tid, t in traces
        if any(r.service_name == "db" for r, _, _ in t.all_spans())
    }
    assert {t["traceID"] for t in out["traces"]} == expect
    assert out["inspectedSpans"] > 0  # response_to_dict wire form
