"""Continuous profiling plane (util/profiler + util/log +
util/runtimestats + the kerneltel/app wiring).

Covers the acceptance surface: sampler attribution (a busy tempo_tpu
component dominates its label and samples tag to the active query's
self-trace id), the profiling-off differential (bit-identical search
results, unchanged launch counts), slow-query auto-capture linking a
folded artifact into the slow-query log, folded-output parseability,
TimedLock/TimedRLock passthrough semantics, artifact-store bounds +
atomicity + path hygiene, the structured log shim, runtime health
gauges, strict OpenMetrics parse of every new family, and the e2e
loop: chaos slow-launch -> slow-query log entry carrying BOTH a
self-trace id and a profile artifact id -> `tempo-tpu-cli profile`
renders the artifact.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import types
import urllib.parse
import urllib.request

import pytest

from tempo_tpu.util import log as logmod
from tempo_tpu.util import profiler as profmod
from tempo_tpu.util.kerneltel import TEL
from tempo_tpu.util.profiler import (
    PROF,
    ArtifactStore,
    TimedLock,
    TimedRLock,
    timed_lock,
    timed_rlock,
)

TENANT = "prof-t"


@pytest.fixture(autouse=True)
def _fresh_profiler():
    PROF.stop()
    PROF.reset()
    TEL.reset()
    yield
    PROF.stop()
    PROF.reset()
    TEL.reset()


def _busy_thread(stop: threading.Event, trace=None):
    """Spin inside tempo_tpu code (util/testdata -> wire/model) so the
    sampler has a real component to attribute."""
    from tempo_tpu.util.testdata import make_traces

    def run():
        token = TEL.set_active_trace(trace) if trace is not None else None
        try:
            while not stop.is_set():
                make_traces(2, seed=3, n_spans=2)
        finally:
            if token is not None:
                TEL.reset_active_trace(token)

    t = threading.Thread(target=run, daemon=True, name="prof-busy")
    t.start()
    return t


# ----------------------------------------------------------- attribution


def test_sampler_attribution_component_and_query():
    """A busy tempo_tpu component dominates its sample label, and ring
    samples from the busy thread carry the parked trace's id."""
    fake = types.SimpleNamespace(trace_id=b"\xab" * 16)
    PROF.start(hz=250.0)
    stop = threading.Event()
    t = _busy_thread(stop, trace=fake)
    time.sleep(0.6)
    stop.set()
    t.join(timeout=5)
    snap = PROF.status_snapshot()
    PROF.stop()
    s = snap["sampler"]
    assert s["running"] and s["samples_total"] > 10
    assert s["top_stacks"], "no folded stacks aggregated"
    # the busy thread lives in util/testdata + wire/model: its
    # component labels accumulate samples (other tests' parked daemon
    # threads also sample into THEIR components, so the comparison
    # below is within this query's tagged samples, not process-wide)
    comps = s["components"]
    busy = sum(n for c, n in comps.items() if c in ("testdata", "wire"))
    assert busy > 0
    # query attribution: ring samples from the busy thread tag the
    # parked trace id (kerneltel set_active_trace -> thread registry),
    # and the busy component dominates within that query's samples
    want = fake.trace_id.hex()
    with PROF._lock:
        tagged = [r for r in PROF._ring if r[1] == want]
    assert tagged, "no ring samples attributed to the active query"
    in_busy = sum(1 for r in tagged if r[2] in ("testdata", "wire"))
    assert in_busy > 0.8 * len(tagged), (in_busy, len(tagged))


def test_folded_output_parses():
    PROF.start(hz=250.0)
    stop = threading.Event()
    t = _busy_thread(stop)
    time.sleep(0.4)
    stop.set()
    t.join(timeout=5)
    folded = PROF.folded()
    PROF.stop()
    assert folded.strip()
    for line in folded.splitlines():
        stack, _, count = line.rpartition(" ")
        assert int(count) >= 1
        frames = stack.split(";")
        assert len(frames) >= 2  # component root + at least one frame
        assert all(f for f in frames)
    # burst capture (the /debug/profile body) parses the same way
    stop2 = threading.Event()
    t2 = _busy_thread(stop2)
    out = PROF.sample_cpu(0.2, hz=300.0, fmt="folded")
    stop2.set()
    t2.join(timeout=5)
    assert out.strip()
    for line in out.splitlines():
        stack, _, count = line.rpartition(" ")
        assert int(count) >= 1 and ";" in stack
    text = PROF.sample_cpu(0.1, hz=200.0, fmt="text")
    assert "sampling profile" in text


# ------------------------------------------------ profiling-off differential


def test_profiling_off_differential_bit_identical(tmp_path):
    """Sampler on vs off: search results bit-identical, launch counts
    unchanged; TEMPO_PROFILE_HZ=0 makes ensure_sampler a strict no-op."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest

    from tempo_tpu.util.testdata import make_traces

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal"),
                               device_promote_touches=1),
                 backend=MemBackend())
    db.write_block(TENANT, make_traces(40, seed=9, n_spans=5))
    metas = db.blocklist.metas(TENANT)
    req = SearchRequest(query="{ duration > 1ms }", limit=50)

    def run():
        l0 = TEL.launch_count()
        resp = db.search_blocks(TENANT, metas, req)
        return ([ (t.trace_id, json.dumps(t.to_dict(), sort_keys=True))
                  for t in resp.traces ],
                TEL.launch_count() - l0)

    run()  # warm: staging + compiles out of the differential
    base, launches_off = run()
    assert base, "search found nothing; differential is vacuous"
    PROF.start(hz=200.0)
    try:
        on, launches_on = run()
    finally:
        PROF.stop()
    again, launches_off2 = run()
    assert on == base == again
    assert launches_on == launches_off == launches_off2
    db.close()

    # hz=0 kills the always-on sampler entirely
    import os

    old = os.environ.get(profmod.PROFILE_HZ_ENV)
    os.environ[profmod.PROFILE_HZ_ENV] = "0"
    try:
        assert PROF.ensure_sampler() is False
        assert not PROF.sampling
    finally:
        if old is None:
            os.environ.pop(profmod.PROFILE_HZ_ENV, None)
        else:
            os.environ[profmod.PROFILE_HZ_ENV] = old


# ------------------------------------------------- slow-query auto-capture


def test_slow_query_auto_capture_links_artifact(tmp_path, monkeypatch):
    monkeypatch.setenv("TEMPO_SLO_SEARCH_P99_S", "0.05")
    PROF.configure_artifacts(str(tmp_path / "profiles"))
    PROF.start(hz=250.0)
    stop = threading.Event()
    fake = types.SimpleNamespace(trace_id=b"\x17" * 16)
    t = _busy_thread(stop, trace=fake)
    time.sleep(0.5)
    stop.set()
    t.join(timeout=5)
    # a fast query never captures
    TEL.record_query("search", 0.001, fake.trace_id.hex(), "fast")
    fast = [q for q in TEL.slow_queries(20) if q["detail"] == "fast"][0]
    assert fast["profile_artifact_id"] == ""
    # a slow one (past the 0.05s class threshold) captures and links
    TEL.record_query("search", 0.4, fake.trace_id.hex(), "slow")
    slow = [q for q in TEL.slow_queries(20) if q["detail"] == "slow"][0]
    aid = slow["profile_artifact_id"]
    assert aid and slow["self_trace_id"] == fake.trace_id.hex()
    data = PROF.artifact_bytes(aid)
    assert data is not None
    text = data.decode()
    assert "slow-query profile" in text
    assert f"self_trace_id={fake.trace_id.hex()}" in text
    body = [ln for ln in text.splitlines()
            if ln and not ln.startswith("#")]
    assert body, "captured window held no samples"
    for line in body:
        stack, _, count = line.rpartition(" ")
        assert int(count) >= 1 and ";" in stack
    PROF.stop()
    # sampler off -> no capture regardless of latency
    TEL.record_query("search", 9.9, "", "off")
    off = [q for q in TEL.slow_queries(20) if q["detail"] == "off"][0]
    assert off["profile_artifact_id"] == ""


# --------------------------------------------------------- timed locks


def test_timed_lock_passthrough_and_semantics(monkeypatch):
    # unarmed: the factories return RAW threading locks (zero overhead)
    monkeypatch.delenv(profmod.LOCK_PROFILE_ENV, raising=False)
    assert not isinstance(timed_lock("x"), TimedLock)
    assert type(timed_lock("x")) is type(threading.Lock())
    # armed: wrappers with full lock semantics
    monkeypatch.setenv(profmod.LOCK_PROFILE_ENV, "1")
    lk = timed_lock("test_lock")
    assert isinstance(lk, TimedLock)
    with lk:
        assert lk.locked()
        # blocking=False from another thread fails cleanly
        got = []
        t = threading.Thread(
            target=lambda: got.append(lk.acquire(blocking=False)))
        t.start()
        t.join()
        assert got == [False]
    assert not lk.locked()
    # contended acquisition is measured (and only contended ones hit
    # the wait histogram)
    lk.acquire()
    release_at = threading.Event()

    def holder_release():
        release_at.wait(5)
        lk.release()

    t = threading.Thread(target=holder_release)
    t.start()
    waiter_done = threading.Event()

    def waiter():
        lk.acquire()
        lk.release()
        waiter_done.set()

    w = threading.Thread(target=waiter)
    w.start()
    time.sleep(0.05)
    release_at.set()
    assert waiter_done.wait(5)
    t.join()
    w.join()
    stats = profmod.lock_stats()["test_lock"]
    assert stats["acquisitions"] >= 3
    assert stats["contended"] >= 1
    assert stats["wait_max_s"] >= 0.02
    # RLock recursion: re-acquire by the owner is never contention
    rl = timed_rlock("test_rlock")
    assert isinstance(rl, TimedRLock)
    with rl:
        with rl:
            assert rl._is_owned()
    assert profmod.lock_stats()["test_rlock"]["contended"] == 0
    # Condition over a TimedLock (the frontend-queue shape)
    clk = timed_lock("test_cv_lock")
    cv = threading.Condition(clk)
    hits = []

    def consumer():
        with cv:
            while not hits:
                if not cv.wait(5):
                    return

    c = threading.Thread(target=consumer)
    c.start()
    time.sleep(0.02)
    with cv:
        hits.append(1)
        cv.notify_all()
    c.join(timeout=5)
    assert not c.is_alive()


# ------------------------------------------------------- artifact store


def test_artifact_store_bounds_and_atomicity(tmp_path):
    store = ArtifactStore(str(tmp_path / "art"), max_files=3)
    ids = []
    for i in range(6):
        ids.append(store.put("slowq", f"stack {i}\n".encode(),
                             suffix=".folded"))
        time.sleep(0.01)  # distinct mtimes for deterministic pruning
    listed = store.list()
    assert len(listed) <= 3
    # newest survive, oldest pruned
    assert {a["id"] for a in listed} <= set(ids[-4:])
    newest = ids[-1]
    assert store.get(newest) == b"stack 5\n"
    assert store.get(ids[0]) is None  # pruned
    # path hygiene: traversal-shaped ids never read outside the store
    assert store.get("../art/" + newest) is None
    assert store.get("..") is None
    assert store.get(".tmp-x") is None
    # no torn temp files left behind
    import os

    assert not [n for n in os.listdir(store.root)
                if n.startswith(".tmp-")]
    # a foreign DIRECTORY in the root (under the app, the storage
    # poller drops tenant-index dirs beside the artifacts) is neither
    # listed, readable, nor pruned
    os.makedirs(os.path.join(store.root, "__tenant__"), exist_ok=True)
    assert store.get("__tenant__") is None
    assert "__tenant__" not in {a["id"] for a in store.list()}
    store.put("slowq", b"x\n", suffix=".folded")  # prune pass runs
    assert os.path.isdir(os.path.join(store.root, "__tenant__"))


# ------------------------------------------------------------- log shim


def test_log_shim_structured_and_suppressed(capsys):
    lg = logmod.get_logger("unittest-comp")
    before = logmod.MESSAGES.get(
        'level="warning",component="unittest-comp"')
    lg.warning("thing %s failed", "alpha", attempt=1)
    for _ in range(4):  # same template inside the window: suppressed
        lg.warning("thing %s failed", "beta", attempt=2)
    err = capsys.readouterr().err
    lines = [json.loads(ln) for ln in err.splitlines()
             if ln.startswith("{")]
    ours = [r for r in lines if r.get("component") == "unittest-comp"]
    assert len(ours) == 1, "repeat suppression failed"
    rec = ours[0]
    assert rec["level"] == "warning" and rec["msg"] == "thing alpha failed"
    assert rec["attempt"] == 1 and "ts" in rec
    # every call counted, printed or not
    after = logmod.MESSAGES.get('level="warning",component="unittest-comp"')
    assert after - before == 5
    # ambient self-trace id lands on the line
    fake = types.SimpleNamespace(trace_id=b"\x42" * 16)
    token = TEL.set_active_trace(fake)
    try:
        lg.error("with trace")
    finally:
        TEL.reset_active_trace(token)
    traced = [json.loads(ln) for ln in capsys.readouterr().err.splitlines()
              if ln.startswith("{")]
    assert any(r.get("trace_id") == fake.trace_id.hex() for r in traced)


# ------------------------------------------------------- runtime gauges


def test_runtime_health_gauges():
    import gc

    from tempo_tpu.util import runtimestats

    runtimestats.install()
    gc.collect()
    lines = runtimestats.metrics_lines()
    text = "\n".join(lines)
    assert 'tempo_runtime_gc_collections_total{generation="2"}' in text
    assert "tempo_runtime_threads" in text
    assert "tempo_runtime_rss_bytes" in text
    # gauges carry live values
    assert runtimestats.THREADS.get() >= 1
    assert runtimestats.RSS.get() > 0


# ------------------------------------------------------ strict exposition


def test_new_families_strict_openmetrics(monkeypatch):
    from test_observability import parse_openmetrics_strict

    from tempo_tpu.util.metrics import render_openmetrics

    monkeypatch.setenv(profmod.LOCK_PROFILE_ENV, "1")
    # populate every new family
    PROF.start(hz=100.0)
    time.sleep(0.1)
    PROF.stop()
    lk = timed_lock("expo_lock")
    with lk:
        pass
    logmod.get_logger("expo").warning("expo message")
    text = render_openmetrics(TEL.metrics_lines(),
                              helps=TEL.help_entries()) + "# EOF\n"
    fams = parse_openmetrics_strict(text)
    assert fams.get("tempo_profile_samples") == "counter"
    assert fams.get("tempo_lock_acquisitions") == "counter"
    assert fams.get("tempo_log_messages") == "counter"
    assert fams.get("tempo_runtime_gc_collections") == "counter"
    assert fams.get("tempo_runtime_threads") == "gauge"
    assert fams.get("tempo_runtime_rss_bytes") == "gauge"
    # a contended wait makes the histogram family appear too
    lk2 = timed_lock("expo_lock2")
    lk2.acquire()
    t = threading.Thread(target=lambda: (lk2.acquire(), lk2.release()))
    t.start()
    time.sleep(0.05)
    lk2.release()
    t.join()
    text = render_openmetrics(TEL.metrics_lines(),
                              helps=TEL.help_entries()) + "# EOF\n"
    fams = parse_openmetrics_strict(text)
    assert fams.get("tempo_lock_wait_seconds") == "histogram"


# ------------------------------------------------------------------- e2e


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_slow_query_e2e_chaos_to_artifact(tmp_path, monkeypatch, capsys):
    """The acceptance loop: a chaos `slow-launch` rule makes a search
    slow; the slow-query log entry carries BOTH a self-trace id and a
    profile artifact id; the artifact downloads over HTTP and
    `tempo-tpu-cli profile artifact` renders it."""
    from tempo_tpu.chaos import plane
    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire import otlp_json

    monkeypatch.setenv("TEMPO_SLO_SEARCH_P99_S", "0.05")
    monkeypatch.setenv(profmod.PROFILE_HZ_ENV, "97")
    # the drill repeats one slow query until the profiler catches it
    # in-flight; a result-cache hit would answer in microseconds and
    # never cross the slow threshold again
    monkeypatch.setenv("TEMPO_RESULT_CACHE", "0")
    cfg = AppConfig(
        storage_path=str(tmp_path / "store"),
        http_port=_free_port(),
        compaction_cycle_s=9999,
        self_tracing_tenant="self",
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    base = f"http://127.0.0.1:{cfg.http_port}"
    try:
        assert PROF.sampling, "app start did not arm the sampler"
        for _, tr in make_traces(8, seed=21, n_spans=4):
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/traces", data=otlp_json.dumps(tr).encode(),
                headers={"Content-Type": "application/json"}), timeout=10)
        app.ingester.flush_all()
        app.db.poll_now()
        # warm the read path, then zero out the device round-trip cost
        # estimate so the router must pick the DEVICE engine (tiny test
        # blocks with cached host arrays otherwise always scan host and
        # a slow-LAUNCH rule would have nothing to slow), and pay the
        # device compile storm outside the chaos window
        from tempo_tpu.db import search as search_mod

        q = urllib.parse.quote('{ duration > 1ms }')
        for _ in range(3):
            urllib.request.urlopen(f"{base}/api/search?q={q}&limit=10",
                                   timeout=60)
        monkeypatch.setattr(search_mod, "_link_rtt_ms", lambda: -1.0)
        urllib.request.urlopen(f"{base}/api/search?q={q}&limit=10",
                               timeout=120)
        time.sleep(0.3)  # clear the capture stampede guard
        # chaos slow-launch: every device launch pays 120ms -> the
        # query crosses its SLO class p99 threshold deterministically
        plane.configure([{"site": "device.launch", "action": "latency",
                          "latency_s": 0.12}])
        urllib.request.urlopen(f"{base}/api/search?q={q}&limit=10",
                               timeout=60)
        plane.reset_for_tests()
        with urllib.request.urlopen(base + "/status/kernels",
                                    timeout=10) as r:
            status = json.loads(r.read())
        slow = [sq for sq in status["slow_queries"]
                if sq["op"] == "search" and sq["profile_artifact_id"]]
        assert slow, f"no captured slow query in {status['slow_queries']}"
        entry = slow[0]
        assert entry["self_trace_id"], "entry lost its self-trace id"
        aid = entry["profile_artifact_id"]
        # /status/profile shows the sampler + the artifact
        with urllib.request.urlopen(base + "/status/profile",
                                    timeout=10) as r:
            prof = json.loads(r.read())
        assert prof["sampler"]["running"]
        assert any(a["id"] == aid for a in prof["artifacts"])
        # the artifact downloads and is folded text
        with urllib.request.urlopen(
                f"{base}/debug/profile/artifact/{aid}", timeout=10) as r:
            art = r.read().decode()
        assert "slow-query profile" in art
        assert f"self_trace_id={entry['self_trace_id']}" in art
        # burst profile endpoints still serve both formats
        with urllib.request.urlopen(
                f"{base}/debug/profile?seconds=0.2&format=folded",
                timeout=30) as r:
            assert r.status == 200
        # the CLI renders the artifact (the dogfood loop's last hop)
        from tempo_tpu.cli.__main__ import main as cli_main

        capsys.readouterr()
        cli_main(["profile", "artifact", aid, "--target", base])
        out = capsys.readouterr().out
        assert "samples" in out and "slow-query profile" in out
        # and the lock table endpoint answers (no locks armed -> empty)
        cli_main(["profile", "lock", "--target", base])
        assert "lock" in capsys.readouterr().out.lower()
    finally:
        app.stop()
