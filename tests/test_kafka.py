"""Kafka receiver against an in-process fake broker (the fake-server
pattern of tests/test_backend_*): the broker speaks the Metadata /
ListOffsets / Fetch v0 subset the receiver's client uses, serving an
in-memory log; spans published as OTLP-proto messages must land in
storage and come back through find + search (reference contract:
modules/distributor/receiver/shim.go kafka receiver, topic otlp_spans)."""

import socketserver
import struct
import threading
import time

from tempo_tpu.services.kafka_receiver import (
    KafkaClient,
    Reader,
    enc_bytes,
    enc_str,
    parse_message_set,
)

_I16 = struct.Struct(">h")
_I32 = struct.Struct(">i")
_I64 = struct.Struct(">q")


class FakeBroker:
    """One-topic, one-partition in-memory Kafka broker (v0 apis)."""

    def __init__(self, topic: str):
        self.topic = topic
        self.log: list[bytes] = []
        self.fetches = 0

        broker = self

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        raw = self._read(4)
                        (ln,) = _I32.unpack(raw)
                        req = Reader(self._read(ln))
                        api = req.i16()
                        req.i16()  # version
                        corr = req.i32()
                        req.string()  # client id
                        body = broker._serve(api, req)
                        resp = _I32.pack(corr) + body
                        self.request.sendall(_I32.pack(len(resp)) + resp)
                except (ConnectionError, struct.error, OSError):
                    return

            def _read(self, n):
                out = b""
                while len(out) < n:
                    c = self.request.recv(n - len(out))
                    if not c:
                        raise ConnectionError
                    out += c
                return out

        self.server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _H)
        self.server.daemon_threads = True
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    @property
    def addr(self) -> str:
        h, p = self.server.server_address
        return f"{h}:{p}"

    def close(self):
        self.server.shutdown()

    def produce(self, value: bytes) -> None:
        self.log.append(value)

    def _message_set(self, start: int) -> bytes:
        out = b""
        for off in range(start, len(self.log)):
            v = self.log[off]
            body = b"\x00" * 4 + b"\x00\x00" + enc_bytes(None) + enc_bytes(v)
            out += _I64.pack(off) + _I32.pack(len(body)) + body
        return out

    def _serve(self, api: int, req: Reader) -> bytes:
        if api == 3:  # Metadata v0
            h, p = self.server.server_address
            return (
                _I32.pack(1) + _I32.pack(0) + enc_str(h) + _I32.pack(p)
                + _I32.pack(1) + _I16.pack(0) + enc_str(self.topic)
                + _I32.pack(1) + _I16.pack(0) + _I32.pack(0) + _I32.pack(0)
                + _I32.pack(0) + _I32.pack(0)
            )
        if api == 2:  # ListOffsets v0
            req.i32()  # replica
            req.i32()  # n topics (1)
            req.string()
            req.i32()  # n partitions
            req.i32()  # partition
            ts = req.i64()
            off = len(self.log) if ts == -1 else 0
            return (
                _I32.pack(1) + enc_str(self.topic) + _I32.pack(1)
                + _I32.pack(0) + _I16.pack(0) + _I32.pack(1) + _I64.pack(off)
            )
        if api == 1:  # Fetch v0
            self.fetches += 1
            req.i32()  # replica
            req.i32()  # max wait
            req.i32()  # min bytes
            req.i32()  # n topics
            req.string()
            req.i32()  # n partitions
            req.i32()  # partition
            offset = req.i64()
            if offset > len(self.log):  # fell off retention / bogus
                return (
                    _I32.pack(1) + enc_str(self.topic) + _I32.pack(1)
                    + _I32.pack(0) + _I16.pack(1) + _I64.pack(len(self.log))
                    + _I32.pack(0)
                )
            ms = self._message_set(int(offset))
            return (
                _I32.pack(1) + enc_str(self.topic) + _I32.pack(1)
                + _I32.pack(0) + _I16.pack(0) + _I64.pack(len(self.log))
                + _I32.pack(len(ms)) + ms
            )
        raise AssertionError(f"unexpected api {api}")


def _otlp_message(trace_id: bytes, name: str, svc: str) -> bytes:
    from tempo_tpu.wire import otlp_pb
    from tempo_tpu.wire.model import (
        Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace,
    )

    t = Trace(resource_spans=[ResourceSpans(
        resource=Resource(attrs={"service.name": svc}),
        scope_spans=[ScopeSpans(scope=Scope(), spans=[Span(
            trace_id=trace_id, span_id=trace_id[:8], name=name,
            start_unix_nano=1_700_000_001_000_000_000,
            end_unix_nano=1_700_000_001_200_000_000,
        )])])])
    return otlp_pb.encode_trace(t)


def test_kafka_client_wire_roundtrip():
    b = FakeBroker("otlp_spans")
    try:
        b.produce(b"one")
        b.produce(b"two")
        c = KafkaClient("127.0.0.1", int(b.addr.split(":")[1]))
        assert c.partitions("otlp_spans") == [0]
        assert c.list_offset("otlp_spans", 0, latest=False) == 0
        assert c.list_offset("otlp_spans", 0, latest=True) == 2
        got = c.fetch("otlp_spans", 0, 0)
        assert got == [(0, b"one"), (1, b"two")]
        assert c.fetch("otlp_spans", 0, 2) == []
        c.close()
    finally:
        b.close()


def test_message_set_partial_tail():
    body = b"\x00" * 4 + b"\x00\x00" + enc_bytes(None) + enc_bytes(b"full")
    ms = _I64.pack(0) + _I32.pack(len(body)) + body
    truncated = ms + _I64.pack(1) + _I32.pack(len(body)) + body[: len(body) // 2]
    assert parse_message_set(truncated) == [(0, b"full")]


def test_kafka_receiver_end_to_end(tmp_path):
    """Spans published through the broker land in a block and are
    findable + searchable through the app's query API."""
    from tempo_tpu.services.app import App, AppConfig, IngesterConfig

    broker = FakeBroker("otlp_spans")
    try:
        cfg = AppConfig(
            target="all", http_port=0, storage_path=str(tmp_path / "store"),
            kafka_brokers=broker.addr,
            ingester=IngesterConfig(max_trace_idle_s=0.05, max_block_age_s=0.05,
                                    flush_check_period_s=0.05),
        )
        app = App(cfg)
        app.start()
        app.kafka.poll_interval_s = 0.05

        tid1, tid2 = b"\x01" * 16, b"\x02" * 16
        broker.produce(_otlp_message(tid1, "op-a", "svc-kafka"))
        broker.produce(_otlp_message(tid2, "op-b", "svc-kafka"))

        deadline = time.time() + 10
        while app.kafka.messages < 2 and time.time() < deadline:
            time.sleep(0.05)
        assert app.kafka.messages == 2 and app.kafka.failures == 0

        tenant = app.tenant_of({})
        got = app.frontend.find_trace_by_id(tenant, tid1)
        assert got is not None and got.span_count() == 1
        from tempo_tpu.db.search import SearchRequest

        deadline = time.time() + 10
        hits = set()
        while time.time() < deadline:
            resp = app.frontend.search(
                tenant, SearchRequest(tags={"service.name": "svc-kafka"}, limit=10))
            hits = {t.trace_id for t in resp.traces}
            if len(hits) == 2:
                break
            time.sleep(0.1)
        assert hits == {tid1.hex(), tid2.hex()}

        # receiver starts at LATEST by default on a fresh topic: messages
        # produced before startup are skipped; consumed offsets advance
        assert app.kafka.offsets == {0: 2}
        app.stop()
    finally:
        broker.close()


def test_kafka_receiver_transient_vs_poison(tmp_path):
    """Transient push failures (429) rewind the offset for retry;
    undecodable messages are poison (skipped, offset advanced);
    OFFSET_OUT_OF_RANGE resets to the earliest retained offset."""
    from tempo_tpu.services.app import App, AppConfig, IngesterConfig
    from tempo_tpu.services.distributor import PushError
    from tempo_tpu.services.kafka_receiver import KafkaReceiver

    broker = FakeBroker("otlp_spans")
    try:
        cfg = AppConfig(target="all", http_port=0,
                        storage_path=str(tmp_path / "store"),
                        ingester=IngesterConfig())
        app = App(cfg)
        app.start()
        rx = KafkaReceiver(app, broker.addr, tenant=app.tenant_of({}),
                           start_latest=False)
        broker.produce(b"\x00garbage-not-otlp")          # poison
        broker.produce(_otlp_message(b"\x03" * 16, "x", "s"))
        rx.poll_once()
        assert rx.failures == 1 and rx.messages == 1 and rx.offsets == {0: 2}

        # transient: monkeypatch distributor to rate-limit once
        orig = app.distributor.push_raw
        calls = {"n": 0}

        def flaky(tenant, payload):
            calls["n"] += 1
            if calls["n"] == 1:
                raise PushError(429, "rate limited")
            return orig(tenant, payload)

        app.distributor.push_raw = flaky
        broker.produce(_otlp_message(b"\x04" * 16, "y", "s"))
        rx.poll_once()
        assert rx.offsets == {0: 2}, "transient failure must not advance"
        rx.poll_once()  # retry succeeds
        assert rx.offsets == {0: 3} and rx.messages == 2

        # offset out of range: pretend retention ate the log tail
        rx.offsets[0] = 99
        rx.poll_once()
        assert rx.offsets[0] == 0, "reset to earliest after OffsetOutOfRange"
        app.stop()
    finally:
        broker.close()
