"""Multi-process topology e2e: 2 ingesters + distributor + querier as
separate OS processes over a shared ring-KV directory and storage path.

The analog of the reference's TestMicroservicesWithKVStores
(integration/e2e/e2e_test.go:130) -- real process boundaries, HTTP
data plane, file-KV control plane.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from tempo_tpu.util.testdata import make_traces
from tempo_tpu.wire import otlp_json

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn(target, port, storage, kv, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        [sys.executable, "-m", "tempo_tpu.services.app",
         f"--target={target}", "--http.port", str(port),
         "--storage.path", storage, "--kv.dir", kv, *extra],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def _wait_ready(port, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/ready", timeout=1) as r:
                if r.status == 200:
                    return
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(0.3)
    raise TimeoutError(f"port {port} never became ready")


import contextlib


@contextlib.contextmanager
def _two_ingester_topology(tmp_path, rf=2):
    """2 ingesters + distributor(rf) + querier as real processes over a
    shared storage path + file ring-KV; yields (ports, procs-by-name)."""
    storage = str(tmp_path / "storage")
    kv = str(tmp_path / "kv")
    ports = {r: _free_port() for r in ("ing1", "ing2", "dist", "query")}
    procs = {}
    try:
        for name in ("ing1", "ing2"):
            procs[name] = _spawn("ingester", ports[name], storage, kv,
                                 ("--instance.id", name))
        _wait_ready(ports["ing1"])
        _wait_ready(ports["ing2"])
        procs["dist"] = _spawn("distributor", ports["dist"], storage, kv,
                               ("--replication.factor", str(rf)))
        procs["query"] = _spawn("querier", ports["query"], storage, kv)
        _wait_ready(ports["dist"])
        _wait_ready(ports["query"])
        yield ports, procs
    finally:
        for p in procs.values():
            p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_microservices_topology(tmp_path):
    with _two_ingester_topology(tmp_path, rf=2) as (ports, procs):

        traces = make_traces(10, seed=55, n_spans=4)
        for _, tr in traces:
            req = urllib.request.Request(
                f"http://127.0.0.1:{ports['dist']}/v1/traces",
                data=otlp_json.dumps(tr).encode(),
                headers={"Content-Type": "application/json"},
            )
            assert urllib.request.urlopen(req, timeout=10).status == 200

        # live read through the querier -> remote ingester find
        tid, tr = traces[0]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{ports['query']}/api/traces/{tid.hex()}", timeout=15
        ) as r:
            got = otlp_json.loads(r.read())
        assert got.span_count() == tr.span_count()

        # live search through the querier -> remote ingester search
        with urllib.request.urlopen(
            f"http://127.0.0.1:{ports['query']}/api/search?limit=100", timeout=15
        ) as r:
            hits = {t["traceID"] for t in json.loads(r.read())["traces"]}
        assert {tid.hex() for tid, _ in traces} <= hits

        # flush both ingesters -> blocks in shared storage -> backend read
        for name in ("ing1", "ing2"):
            urllib.request.urlopen(
                urllib.request.Request(f"http://127.0.0.1:{ports[name]}/flush", data=b""),
                timeout=15,
            )
        deadline = time.time() + 20
        got = None
        tid, tr = traces[1]
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{ports['query']}/api/traces/{tid.hex()}", timeout=15
                ) as r:
                    got = otlp_json.loads(r.read())
                break
            except urllib.error.HTTPError:
                time.sleep(1)
        assert got is not None and got.span_count() == tr.span_count()


@pytest.mark.slow
def test_frontend_remote_querier_pull(tmp_path):
    """1 dispatcher-only query-frontend + 2 standalone queriers pulling
    jobs over /internal/jobs: both queriers demonstrably execute search
    jobs (the reference's querier-worker attach model,
    modules/querier/worker/frontend_processor.go:57-80 +
    modules/frontend/v1/frontend.go:50-90)."""
    storage = str(tmp_path / "storage")
    kv = str(tmp_path / "kv")
    ports = {r: _free_port() for r in ("ing", "fe", "q1", "q2")}
    procs = []
    try:
        procs.append(_spawn("ingester", ports["ing"], storage, kv,
                            ("--instance.id", "ing-a")))
        _wait_ready(ports["ing"])

        # push + flush so the backend holds blocks to search
        from tempo_tpu.transport.client import HTTPIngesterClient
        from tempo_tpu.wire.segment import segment_for_write

        traces = make_traces(30, seed=21, n_spans=4)
        client = HTTPIngesterClient(f"http://127.0.0.1:{ports['ing']}")
        for i in range(0, 30, 10):  # three flushes -> three blocks
            batch = []
            for tid, tr in traces[i : i + 10]:
                lo, hi = tr.time_range_nanos()
                batch.append((tid, lo // 10**9, hi // 10**9 + 1,
                              segment_for_write(tr, lo // 10**9, hi // 10**9 + 1)))
            client.push_segments("single-tenant", batch)
            urllib.request.urlopen(
                urllib.request.Request(f"http://127.0.0.1:{ports['ing']}/flush", data=b""),
                timeout=20,
            )

        fe_addr = f"http://127.0.0.1:{ports['fe']}"
        procs.append(_spawn("query-frontend", ports["fe"], storage, kv))
        for q in ("q1", "q2"):
            procs.append(_spawn("querier", ports[q], storage, kv,
                                ("--querier.frontend-address", fe_addr)))
        _wait_ready(ports["fe"])
        _wait_ready(ports["q1"])
        _wait_ready(ports["q2"])

        # several searches + finds through the frontend: every job must
        # be executed by a REMOTE querier (the frontend has no workers)
        deadline = time.time() + 60
        hits = set()
        while time.time() < deadline and len(hits) < 30:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ports['fe']}/api/search?limit=100", timeout=30
            ) as r:
                hits = {t["traceID"] for t in json.loads(r.read())["traces"]}
            time.sleep(0.5)
        assert {tid.hex() for tid, _ in traces} <= hits

        tid, tr = traces[7]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{ports['fe']}/api/traces/{tid.hex()}", timeout=30
        ) as r:
            got = otlp_json.loads(r.read())
        assert got.span_count() == tr.span_count()

        # enough jobs that BOTH queriers must have pulled some
        for i in range(10):
            urllib.request.urlopen(
                f"http://127.0.0.1:{ports['fe']}/api/search?limit=100", timeout=30)
            urllib.request.urlopen(
                f"http://127.0.0.1:{ports['fe']}/api/traces/{traces[i][0].hex()}",
                timeout=30)

        def metric(port, name):
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                for line in r.read().decode().splitlines():
                    if line.startswith(name + " "):
                        return int(line.split()[1])
            return 0

        ex1 = metric(ports["q1"], "tempo_querier_worker_jobs_executed_total")
        ex2 = metric(ports["q2"], "tempo_querier_worker_jobs_executed_total")
        assert ex1 > 0 and ex2 > 0, (ex1, ex2)
        assert metric(ports["fe"], "tempo_frontend_jobs_remote_total") > 0
        assert metric(ports["fe"], "tempo_frontend_jobs_local_total") == 0
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_ingester_crash_restart_replays(tmp_path):
    """Kill an ingester before flush; its restart replays the WAL and the
    data stays queryable (the reference's ScalableSingleBinary restart
    scenario + WAL replay, e2e_test.go:314, ingester.go:326-400)."""
    storage = str(tmp_path / "storage")
    kv = str(tmp_path / "kv")
    p_ing = _free_port()
    p_q = _free_port()
    procs = []
    try:
        ing = _spawn("ingester", p_ing, storage, kv, ("--instance.id", "ing-x"))
        procs.append(ing)
        _wait_ready(p_ing)
        # push straight to the ingester via the internal API (distributor
        # path is covered by the other test; here the crash is the point)
        from tempo_tpu.transport.client import HTTPIngesterClient
        from tempo_tpu.wire.segment import segment_for_write

        traces = make_traces(8, seed=77, n_spans=3)
        client = HTTPIngesterClient(f"http://127.0.0.1:{p_ing}")
        batch = []
        for tid, tr in traces:
            lo, hi = tr.time_range_nanos()
            batch.append((tid, lo // 10**9, hi // 10**9 + 1,
                          segment_for_write(tr, lo // 10**9, hi // 10**9 + 1)))
        client.push_segments("single-tenant", batch)

        # crash hard (no flush), then restart with the same instance id
        ing.kill()
        ing.wait()
        ing2 = _spawn("ingester", p_ing, storage, kv, ("--instance.id", "ing-x"))
        procs.append(ing2)
        _wait_ready(p_ing)

        # replay turned the WAL into a backend block: a querier sees it
        q = _spawn("querier", p_q, storage, kv)
        procs.append(q)
        _wait_ready(p_q)
        deadline = time.time() + 30
        got = None
        tid, tr = traces[0]
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{p_q}/api/traces/{tid.hex()}", timeout=10
                ) as r:
                    got = otlp_json.loads(r.read())
                break
            except urllib.error.HTTPError:
                time.sleep(1)
        assert got is not None and got.span_count() == tr.span_count()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_gossip_topology(tmp_path):
    """Processes form the ring over GOSSIP (no shared KV dir): an
    ingester seeds, distributor + querier join by seed address — the
    memberlist topology (modules.go:288-316)."""
    storage = str(tmp_path / "storage")
    ports = {r: _free_port() for r in ("ing", "dist", "query")}
    gports = {r: _free_port() for r in ("ing", "dist", "query")}
    seed = f"127.0.0.1:{gports['ing']}"

    def spawn(target, name, extra=()):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        return subprocess.Popen(
            [sys.executable, "-m", "tempo_tpu.services.app",
             f"--target={target}", "--http.port", str(ports[name]),
             "--storage.path", storage,
             "--memberlist.bind", f"127.0.0.1:{gports[name]}", *extra],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )

    procs = []
    try:
        procs.append(spawn("ingester", "ing", ("--instance.id", "g-ing",)))
        _wait_ready(ports["ing"])
        procs.append(spawn("distributor", "dist", ("--memberlist.join", seed)))
        procs.append(spawn("querier", "query", ("--memberlist.join", seed)))
        _wait_ready(ports["dist"])
        _wait_ready(ports["query"])

        traces = make_traces(6, seed=33, n_spans=3)
        deadline = time.time() + 30
        pushed = False
        while time.time() < deadline and not pushed:
            try:  # distributor needs a gossip round to see the ingester
                for _, tr in traces:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{ports['dist']}/v1/traces",
                        data=otlp_json.dumps(tr).encode(),
                        headers={"Content-Type": "application/json"})
                    urllib.request.urlopen(req, timeout=10)
                pushed = True
            except urllib.error.HTTPError:
                time.sleep(1)
        assert pushed

        tid, tr = traces[0]
        got = None
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{ports['query']}/api/traces/{tid.hex()}",
                    timeout=15,
                ) as r:
                    got = otlp_json.loads(r.read())
                break
            except urllib.error.HTTPError:
                time.sleep(1)
        assert got is not None and got.span_count() == tr.span_count()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_remote_generator_blob_plane(tmp_path):
    """Standalone metrics-generator process: the distributor's tap ships
    otlp-proto BLOBS sliced from segments over /internal/genpush (zero
    decode on the distributor), shuffle-sharded via the generator ring;
    the generator aggregates them into span-metrics series."""
    storage = str(tmp_path / "storage")
    kv = str(tmp_path / "kv")
    os.makedirs(storage, exist_ok=True)
    ports = {t: _free_port() for t in ("ingester", "distributor", "generator")}
    procs = [
        _spawn("ingester", ports["ingester"], storage, kv),
        _spawn("metrics-generator", ports["generator"], storage, kv),
        _spawn("distributor", ports["distributor"], storage, kv),
    ]
    try:
        for p in ports.values():
            _wait_ready(p)
        from tempo_tpu.wire import otlp_pb

        traces = make_traces(8, seed=61, n_spans=3)
        base = f"http://127.0.0.1:{ports['distributor']}"
        for _, t in traces:
            req = urllib.request.Request(
                base + "/v1/traces", data=otlp_pb.encode_trace(t),
                headers={"Content-Type": "application/x-protobuf"})
            with urllib.request.urlopen(req, timeout=15) as r:
                assert r.status == 200
        # the tap is async + remote: poll the GENERATOR's metrics
        deadline = time.time() + 20
        total = 0
        while time.time() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{ports['generator']}/metrics",
                    timeout=10) as r:
                lines = r.read().decode().splitlines()
            total = sum(int(l.rsplit(" ", 1)[1]) for l in lines
                        if l.startswith("traces_spanmetrics_calls_total"))
            if total >= sum(t.span_count() for _, t in traces):
                break
            time.sleep(0.3)
        assert total == sum(t.span_count() for _, t in traces), total
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_rf2_survives_ingester_kill(tmp_path):
    """RF=2 eventual consistency (pkg/ring EventuallyConsistentStrategy,
    minSuccess=1): with one of two ingesters SIGKILLed -- and still
    listed healthy in the ring (no heartbeat timeout yet) -- writes
    keep succeeding on the surviving replica and every trace stays
    readable through the querier."""
    import signal

    with _two_ingester_topology(tmp_path, rf=2) as (ports, procs):

        def push(tr):
            req = urllib.request.Request(
                f"http://127.0.0.1:{ports['dist']}/v1/traces",
                data=otlp_json.dumps(tr).encode(),
                headers={"Content-Type": "application/json"})
            assert urllib.request.urlopen(req, timeout=15).status == 200

        before = make_traces(5, seed=71, n_spans=3)
        for _, tr in before:
            push(tr)

        # hard-kill one replica; its ring entry stays "healthy" until the
        # heartbeat staleness window, so the distributor still tries it
        procs["ing2"].send_signal(signal.SIGKILL)
        procs["ing2"].wait(timeout=10)

        after = make_traces(5, seed=72, n_spans=3)
        for _, tr in after:
            push(tr)  # minSuccess=1: the surviving replica is enough

        for tid, tr in before + after:
            got = None
            deadline = time.time() + 20
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{ports['query']}/api/traces/{tid.hex()}",
                            timeout=15) as r:
                        got = otlp_json.loads(r.read())
                    break
                except urllib.error.HTTPError:
                    time.sleep(0.5)
            assert got is not None and got.span_count() == tr.span_count(), tid.hex()
