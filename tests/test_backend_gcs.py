"""Native GCS backend against an in-process fake GCS JSON-API server.

The fake implements the subset the backend uses (media + resumable
uploads, alt=media reads with Range, delimiter listing with paging,
object delete, rewriteTo) -- the role fake-gcs-server plays in the
reference's e2e suite (integration/e2e/backend).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import pytest

from tempo_tpu.backend import DoesNotExist, open_backend
from tempo_tpu.backend.cache import CachedBackend
from tempo_tpu.backend.gcs import GCSBackend
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.util.testdata import make_traces

TENANT = "t-gcs"


class _FakeGCS(BaseHTTPRequestHandler):
    store: dict[str, bytes] = {}
    sessions: dict[str, dict] = {}  # session id -> {"name":, "data": bytearray}
    lock = threading.Lock()

    def log_message(self, *a):
        pass

    def _send(self, code, body=b"", headers=()):
        self.send_response(code)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _body(self):
        ln = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(ln) if ln else b""

    def do_POST(self):
        u = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        body = self._body()
        if q.get("uploadType") == "media":
            with self.lock:
                self.store[q["name"]] = body
            return self._send(200, b"{}")
        if q.get("uploadType") == "resumable":
            sid = f"sess-{len(self.sessions)}"
            with self.lock:
                self.sessions[sid] = {"name": q["name"], "data": bytearray()}
            host = self.headers.get("Host")
            return self._send(
                200, b"", [("Location", f"http://{host}/upload/session/{sid}")]
            )
        return self._send(400)

    def do_PUT(self):
        # resumable chunk
        u = urlparse(self.path)
        if not u.path.startswith("/upload/session/"):
            return self._send(400)
        sid = u.path.rsplit("/", 1)[1]
        body = self._body()
        cr = self.headers.get("Content-Range", "")
        with self.lock:
            sess = self.sessions.get(sid)
            if sess is None:
                return self._send(404)
            sess["data"].extend(body)
            total = cr.rsplit("/", 1)[1] if "/" in cr else "*"
            if total != "*":
                self.store[sess["name"]] = bytes(sess["data"])
                return self._send(200, b"{}")
        return self._send(308)

    def do_DELETE(self):
        u = urlparse(self.path)
        if u.path.startswith("/upload/session/"):
            with self.lock:
                self.sessions.pop(u.path.rsplit("/", 1)[1], None)
            return self._send(204)
        key = unquote(u.path.split("/o/", 1)[1]) if "/o/" in u.path else ""
        with self.lock:
            existed = self.store.pop(key, None)
        return self._send(204 if existed is not None else 404)

    def do_GET(self):
        u = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        if "/o/" in u.path:  # object read
            key = unquote(u.path.split("/o/", 1)[1])
            with self.lock:
                data = self.store.get(key)
            if data is None:
                return self._send(404)
            rng = self.headers.get("Range")
            if rng and rng.startswith("bytes="):
                lo, hi = rng[6:].split("-")
                return self._send(206, data[int(lo): int(hi) + 1])
            return self._send(200, data)
        # listing
        prefix = q.get("prefix", "")
        delim = q.get("delimiter", "")
        with self.lock:
            keys = sorted(k for k in self.store if k.startswith(prefix))
        prefixes, items = [], []
        seen = set()
        for k in keys:
            rest = k[len(prefix):]
            if delim and delim in rest:
                p = prefix + rest.split(delim)[0] + delim
                if p not in seen:
                    seen.add(p)
                    prefixes.append(p)
            else:
                items.append({"name": k})
        out = {"prefixes": prefixes, "items": items}
        return self._send(200, json.dumps(out).encode())


@pytest.fixture(scope="module")
def gcs_server():
    _FakeGCS.store = {}
    _FakeGCS.sessions = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeGCS)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


@pytest.fixture()
def gcs(gcs_server):
    _FakeGCS.store.clear()
    return GCSBackend("bkt", prefix="traces", endpoint=gcs_server, token="tok")


def test_gcs_object_roundtrip(gcs):
    gcs.write(TENANT, "blk-1", "meta.json", b"{}")
    gcs.write(TENANT, "blk-1", "data.vtpu", bytes(range(256)) * 4)
    assert gcs.read(TENANT, "blk-1", "meta.json") == b"{}"
    assert gcs.read_range(TENANT, "blk-1", "data.vtpu", 10, 5) == bytes(range(10, 15))
    assert gcs.tenants() == [TENANT]
    assert gcs.blocks(TENANT) == ["blk-1"]
    with pytest.raises(DoesNotExist):
        gcs.read(TENANT, "blk-1", "nope")
    gcs.mark_compacted(TENANT, "blk-1")
    assert gcs.has_object(TENANT, "blk-1", "meta.compacted.json")
    assert not gcs.has_object(TENANT, "blk-1", "meta.json")
    gcs.delete_block(TENANT, "blk-1")
    assert gcs.blocks(TENANT) == []


def test_gcs_resumable_append(gcs):
    """The streamed appender flushes 256KiB-aligned chunks through a
    resumable session and finalizes with the exact total."""
    app = gcs.open_append(TENANT, "blk-2", "data.vtpu")
    blob = bytes(range(256)) * 2048  # 512 KiB
    app.append(blob)
    app.append(b"tail")
    app.close()
    assert app.bytes_written == len(blob) + 4
    assert gcs.read(TENANT, "blk-2", "data.vtpu") == blob + b"tail"
    # ranged read across a chunk boundary
    assert gcs.read_range(TENANT, "blk-2", "data.vtpu", len(blob) - 2, 4) == blob[-2:] + b"ta"
    # abort writes nothing
    app2 = gcs.open_append(TENANT, "blk-3", "data.vtpu")
    app2.append(b"junk")
    app2.abort()
    assert not gcs.has_object(TENANT, "blk-3", "data.vtpu")


def test_tempodb_over_gcs(gcs, tmp_path):
    """Full block write/find/search cycle over the GCS JSON-API path."""
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal")), backend=gcs)
    traces1 = make_traces(15, seed=1, n_spans=4)
    traces2 = make_traces(15, seed=2, n_spans=4)
    db.write_block(TENANT, traces1)
    db.write_block(TENANT, traces2)
    for tid, t in traces1[:3] + traces2[:3]:
        got = db.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == t.span_count()
    from tempo_tpu.db.search import SearchRequest

    resp = db.search(TENANT, SearchRequest(tags={"service.name": "db"}, limit=100))
    assert resp.traces
    db2 = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal2")), backend=gcs)
    db2.poll_now()
    assert len(db2.blocklist.metas(TENANT)) == 2
    db.close()
    db2.close()


def test_open_backend_gcs(gcs_server):
    b = open_backend({"backend": "gcs", "endpoint": gcs_server, "bucket": "bkt",
                      "token": "tok"})
    b.write("t", "b1", "meta.json", b"x")
    assert b.read("t", "b1", "meta.json") == b"x"
    assert isinstance(b, CachedBackend)
    # HMAC keys route to the S3-interoperability endpoint instead
    from tempo_tpu.backend.s3 import S3Backend

    b2 = open_backend({"backend": "gcs", "bucket": "bkt", "access_key": "a",
                       "secret_key": "s", "cache": False})
    assert isinstance(b2, S3Backend)
