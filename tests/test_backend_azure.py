"""Azure Blob backend against an in-process fake Azurite-style server
(PUT/GET-range/DELETE blob, List Blobs XML with BlobPrefix delimiter)."""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import pytest

from tempo_tpu.backend import DoesNotExist
from tempo_tpu.backend.azure import AzureBackend
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.util.testdata import make_traces

TENANT = "t-az"


class _FakeAzurite(BaseHTTPRequestHandler):
    store: dict[str, bytes] = {}
    lock = threading.Lock()

    def log_message(self, *a):
        pass

    def _blob(self):
        # /account/container/blob...
        parts = unquote(urlparse(self.path).path).lstrip("/").split("/", 2)
        return parts[2] if len(parts) > 2 else ""

    def do_PUT(self):
        ln = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(ln)
        with self.lock:
            self.store[self._blob()] = body
        self.send_response(201)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        with self.lock:
            existed = self.store.pop(self._blob(), None) is not None
        self.send_response(202 if existed else 404)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        u = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        if q.get("comp") == "list":
            return self._list(q)
        with self.lock:
            data = self.store.get(self._blob())
        if data is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        rng = self.headers.get("x-ms-range") or self.headers.get("Range")
        status = 200
        if rng and rng.startswith("bytes="):
            lo, hi = rng[6:].split("-")
            data = data[int(lo): int(hi) + 1]
            status = 206
        self.send_response(status)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _list(self, q):
        prefix = q.get("prefix", "")
        delim = q.get("delimiter", "")
        with self.lock:
            keys = sorted(k for k in self.store if k.startswith(prefix))
        blobs, prefixes, seen = [], [], set()
        for k in keys:
            rest = k[len(prefix):]
            if delim and delim in rest:
                p = prefix + rest.split(delim)[0] + delim
                if p not in seen:
                    seen.add(p)
                    prefixes.append(p)
            else:
                blobs.append(k)
        xml = ["<?xml version='1.0'?><EnumerationResults><Blobs>"]
        for k in blobs:
            xml.append(f"<Blob><Name>{k}</Name></Blob>")
        for p in prefixes:
            xml.append(f"<BlobPrefix><Name>{p}</Name></BlobPrefix>")
        xml.append("</Blobs><NextMarker/></EnumerationResults>")
        data = "".join(xml).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture(scope="module")
def az_server():
    _FakeAzurite.store = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeAzurite)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}/devaccount"
    srv.shutdown()


@pytest.fixture()
def az(az_server):
    _FakeAzurite.store.clear()
    import base64

    return AzureBackend("devaccount", "traces", key=base64.b64encode(b"k" * 32).decode(),
                        endpoint=az_server)


def test_azure_object_roundtrip(az):
    az.write(TENANT, "blk-1", "meta.json", b"{}")
    az.write(TENANT, "blk-1", "data.vtpu", bytes(range(256)))
    assert az.read(TENANT, "blk-1", "meta.json") == b"{}"
    assert az.read_range(TENANT, "blk-1", "data.vtpu", 5, 4) == bytes(range(5, 9))
    assert az.tenants() == [TENANT]
    assert az.blocks(TENANT) == ["blk-1"]
    with pytest.raises(DoesNotExist):
        az.read(TENANT, "blk-1", "missing")
    az.mark_compacted(TENANT, "blk-1")
    assert az.has_object(TENANT, "blk-1", "meta.compacted.json")
    az.delete_block(TENANT, "blk-1")
    assert az.blocks(TENANT) == []


def test_tempodb_over_azure(az, tmp_path):
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal")), backend=az)
    traces = make_traces(12, seed=6, n_spans=4)
    db.write_block(TENANT, traces)
    for tid, t in traces[:4]:
        got = db.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == t.span_count()
    db.close()
