"""Kernel telemetry end-to-end: compile/cache-hit counters keyed by
(op, shape bucket), routing-reason counters on forced fallbacks,
/status/kernels + strict-OpenMetrics /metrics over the single-binary
app, self-trace spans carrying kernel attrs, the SelfTracer flush ack,
and the new Gauge instrument."""

import json
import re
import socket
import threading
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from tempo_tpu.util.kerneltel import TEL
from tempo_tpu.util.metrics import Gauge, render_openmetrics


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    TEL.reset()
    yield


# ------------------------------------------------- compile vs cache hit


def _sorted_ids(n: int) -> np.ndarray:
    ids = np.zeros((n, 4), np.int32)
    ids[:, 3] = np.arange(n, dtype=np.int32)
    return ids


def _kernel_row(op: str, bucket):
    for row in TEL.snapshot()["kernels"]:
        if row["op"] == op and row["bucket"] == str(bucket):
            return row
    return None


def test_compile_counter_once_per_op_bucket():
    """First launch of an (op, shape-bucket) signature is a compile;
    repeats are cache hits; a NOVEL bucket compiles exactly once more."""
    from tempo_tpu.ops.find import lookup_ids

    ids = _sorted_ids(100)  # bucket 1024
    queries = ids[:3]
    assert (lookup_ids(ids, queries) == [0, 1, 2]).all()
    row = _kernel_row("find", 1024)
    assert row is not None
    assert row["compiles"] == 1 and row["cache_hits"] == 0
    assert row["last_compile_unix"] > 0

    lookup_ids(ids, queries)  # same buckets: hit, no new compile
    row = _kernel_row("find", 1024)
    assert row["compiles"] == 1 and row["cache_hits"] == 1

    # forced recompile: novel shape bucket (2000 rows -> 2048)
    ids2 = _sorted_ids(2000)
    lookup_ids(ids2, ids2[:3])
    row2 = _kernel_row("find", 2048)
    assert row2 is not None and row2["compiles"] == 1
    assert TEL.snapshot()["jit_cache"]["entries"] == 2
    # device-time histogram observed per call
    assert row["calls"] >= 1 and row["device_seconds"] >= 0.0
    assert any('op="find"' in ln for ln in TEL.device_time.text())


def test_filter_kernel_compile_and_staging_telemetry(tmp_path):
    """The search filter kernel records compiles, staging records
    transfer bytes + padding waste, and search_block(mode=...) records
    forced routing reasons."""
    from tempo_tpu.db.search import SearchRequest, search_block
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
    from tempo_tpu.util.testdata import make_traces

    db = TempoDB(TempoDBConfig(
        backend={"backend": "local", "path": str(tmp_path / "store")},
        wal_path=str(tmp_path / "wal")))
    meta = db.write_block("t1", make_traces(16, seed=5, n_spans=4))
    blk = db.open_block(meta)
    req = SearchRequest(tags={"service.name": "db"}, limit=10)

    search_block(blk, req, mode="device")
    snap = TEL.snapshot()
    assert any(k["op"] == "filter" and k["compiles"] >= 1 for k in snap["kernels"])
    st = snap["staging"]
    assert st["transfer_bytes_total"] > 0
    assert st["rows_padded_total"] >= st["rows_real_total"] > 0
    assert st["padding_waste_ratio"] >= 1.0
    assert ("search_block", "device", "forced") in TEL.routing_counts()

    # second identical query: staged cache + jit cache both hit
    search_block(blk, req, mode="device")
    snap2 = TEL.snapshot()
    frow = [k for k in snap2["kernels"] if k["op"] == "filter"]
    assert sum(k["cache_hits"] for k in frow) >= 1
    assert snap2["staging"]["cache_hits"] >= 1

    # forced host fallback is a routing fact too
    search_block(blk, req, mode="host")
    assert ("search_block", "host", "forced") in TEL.routing_counts()
    db.close()


def test_routing_reason_cold_block(tmp_path):
    """Auto mode on a block with no pinned/staged device columns routes
    host with reason cold_block."""
    from tempo_tpu.db.search import SearchRequest, search_block
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
    from tempo_tpu.util.testdata import make_traces

    db = TempoDB(TempoDBConfig(
        backend={"backend": "local", "path": str(tmp_path / "store")},
        wal_path=str(tmp_path / "wal"), device_search=False))
    meta = db.write_block("t1", make_traces(8, seed=6, n_spans=3))
    blk = db.open_block(meta)  # device_pinned False (device_search off)
    search_block(blk, SearchRequest(tags={"service.name": "db"}), mode="auto")
    assert ("search_block", "host", "cold_block") in TEL.routing_counts()
    db.close()


def test_metrics_engine_routing_reasons(tmp_path):
    """The metrics executor labels exact-engine fallbacks with the
    reason (forced here) and device/host engines by temperature."""
    from tempo_tpu.db.metrics_exec import (
        MetricsResponse, align_params, metrics_block, parse_metrics_query,
    )
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
    from tempo_tpu.util.testdata import make_traces

    db = TempoDB(TempoDBConfig(
        backend={"backend": "local", "path": str(tmp_path / "store")},
        wal_path=str(tmp_path / "wal")))
    meta = db.write_block("t1", make_traces(8, seed=7, n_spans=3))
    blk = db.open_block(meta)
    base_s = meta.start_time_unix_nano // 1_000_000_000
    req = align_params("{ true } | rate()", base_s, base_s + 60, 10)
    q = parse_metrics_query(req.query)

    resp = MetricsResponse(fn="rate", start_ms=req.start_ms,
                           step_ms=req.step_ms, n_buckets=req.n_buckets)
    metrics_block(blk, q, req, resp, mode="exact")
    assert ("metrics", "exact", "forced") in TEL.routing_counts()

    metrics_block(blk, q, req, resp, mode="device")
    rc = TEL.routing_counts()
    assert ("metrics", "device", "forced") in rc
    assert any(k["op"] == "timeseries" and k["compiles"] >= 1
               for k in TEL.snapshot()["kernels"])
    db.close()


# --------------------------------------------------- self-trace attrs


def test_selftrace_block_spans_carry_kernel_attrs():
    """A self-traced query's flame view shows which block ran on which
    engine and whether it recompiled: per-block child spans carry
    engine/bucket/compile attrs (acceptance: forced recompile + forced
    host fallback both visible end-to-end)."""
    import tempfile

    from tempo_tpu.db.search import SearchRequest, search_block
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
    from tempo_tpu.services.selftrace import SelfTracer
    from tempo_tpu.util.testdata import make_traces

    with tempfile.TemporaryDirectory() as tmp:
        db = TempoDB(TempoDBConfig(
            backend={"backend": "local", "path": tmp + "/store"},
            wal_path=tmp + "/wal"))
        meta = db.write_block("t1", make_traces(8, seed=9, n_spans=3))
        blk = db.open_block(meta)
        shipped = []
        st = SelfTracer(lambda tenant, rss: shipped.extend(rss))
        req = SearchRequest(min_duration_ms=1, limit=5)  # never prunes

        with st.trace("frontend.search") as t:
            token = TEL.set_active_trace(t)
            try:
                search_block(blk, req, mode="device")  # forced recompile path
                search_block(blk, req, mode="host")  # forced host fallback
            finally:
                TEL.reset_active_trace(token)
        st.flush()
        spans = [sp for rs in shipped for ss in rs.scope_spans for sp in ss.spans]
        block_spans = [sp for sp in spans if sp.name.startswith("block:")]
        assert len(block_spans) == 2
        by_engine = {sp.attrs["engine"]: sp.attrs for sp in block_spans}
        assert by_engine["device"]["compile"] is True
        assert by_engine["device"]["bucket"] >= 1024
        assert by_engine["host"]["compile"] is False
        # and the routing counters saw both forced decisions
        rc = TEL.routing_counts()
        assert ("search_block", "device", "forced") in rc
        assert ("search_block", "host", "forced") in rc
        db.close()


# ------------------------------------------------ SelfTracer flush ack


def test_selftracer_flush_waits_for_push():
    """flush() must wait for the shipper's push to COMPLETE, not just
    for the queue to drain (the old emptiness poll returned while the
    last push was mid-flight and spans_emitted unread)."""
    from tempo_tpu.services.selftrace import SelfTracer

    release = threading.Event()
    pushed = []

    def slow_push(tenant, rss):
        release.wait(5.0)
        pushed.append(rss)

    st = SelfTracer(slow_push)
    with st.trace("op"):
        pass
    # shipper is now blocked inside push; queue is already empty
    time.sleep(0.05)
    release.set()
    st.flush(timeout_s=5.0)
    assert pushed and st.spans_emitted == 1


# ------------------------------------------------------- instruments


def test_gauge_instrument():
    g = Gauge("tempo_test_gauge", help="h")
    g.set(3)
    g.inc()
    g.dec(0.5)
    assert g.get() == 3.5
    assert g.text() == ["tempo_test_gauge 3.5"]  # no empty {}
    g.set(1, labels='tenant="a"')
    assert 'tempo_test_gauge{tenant="a"} 1' in g.text()


def test_render_openmetrics_families():
    text = render_openmetrics([
        "foo_total 3",
        'bar_bucket{le="1"} 1',
        'bar_bucket{le="+Inf"} 2',
        "bar_sum{} 1.5",  # empty braces must be stripped
        "bar_count 2",
        "baz 7",
        "foo_total 3",  # duplicate dropped
    ], helps={"foo": "a counter"})
    assert "# TYPE foo counter" in text
    assert "# HELP foo a counter" in text
    assert "# TYPE bar histogram" in text
    assert "# TYPE baz gauge" in text
    assert "bar_sum 1.5" in text and "{}" not in text
    assert text.count("foo_total 3") == 1


# ----------------------------------------- strict OpenMetrics parser

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? (-?(?:[0-9.eE+-]+|NaN|[+-]?Inf))"
    r"(?: # \{[^{}]*\} [0-9.eE+-]+)?$")  # optional exemplar


def parse_openmetrics_strict(text: str) -> dict:
    """Validating parser per the OpenMetrics text format: EOF marker,
    TYPE before samples, suffix rules per type, no empty label sets,
    family samples contiguous, no duplicate sample lines."""
    assert text.endswith("# EOF\n"), "missing EOF marker"
    body = text[: -len("# EOF\n")]
    families: dict[str, str] = {}
    current = None
    seen_lines = set()
    n_samples = 0
    for ln in body.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP "):
            continue
        if ln.startswith("# TYPE "):
            _, _, fam, typ = ln.split(" ")
            assert fam not in families, f"family {fam} declared twice"
            assert typ in ("counter", "gauge", "histogram"), typ
            families[fam] = typ
            current = fam
            continue
        assert not ln.startswith("#"), f"unknown comment line {ln!r}"
        m = _SAMPLE_RE.match(ln)
        assert m, f"unparseable sample line {ln!r}"
        name, labels = m.group(1), m.group(2)
        assert labels != "{}", f"empty label set in {ln!r}"
        assert ln not in seen_lines, f"duplicate sample {ln!r}"
        seen_lines.add(ln)
        assert current is not None, f"sample {ln!r} before any TYPE"
        typ = families[current]
        if typ == "counter":
            assert name == current + "_total", (name, current)
        elif typ == "histogram":
            assert name in (current + "_bucket", current + "_sum",
                            current + "_count"), (name, current)
            if name.endswith("_bucket"):
                assert 'le="' in (labels or ""), f"bucket without le: {ln!r}"
        else:
            assert name == current, (name, current)
        n_samples += 1
    assert n_samples > 0
    return families


# ------------------------------------------------- HTTP end-to-end


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_status_kernels_and_strict_metrics(tmp_path):
    """After a search + metrics query, /status/kernels returns per-op
    compile counts, cache hits, device-time totals and routing-reason
    counters, and /metrics passes a strict OpenMetrics parse."""
    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire import otlp_json

    cfg = AppConfig(
        storage_path=str(tmp_path / "store"),
        http_port=_free_port(),
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    base = f"http://127.0.0.1:{cfg.http_port}"
    try:
        for _, tr in make_traces(6, seed=11, n_spans=4):
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/traces", data=otlp_json.dumps(tr).encode(),
                headers={"Content-Type": "application/json"}), timeout=10)
        app.ingester.flush_all()
        app.db.poll_now()

        # backend search + metrics range query through the frontend
        q = urllib.parse.quote('{ resource.service.name = "db" }')
        urllib.request.urlopen(f"{base}/api/search?q={q}&limit=10", timeout=15)
        mq = urllib.parse.quote("{ true } | rate()")
        urllib.request.urlopen(
            f"{base}/api/metrics/query_range?q={mq}&start=1&end=3600&step=60",
            timeout=15)
        # one forced-device per-block search so the kernel table has a
        # compiled filter entry even where auto-routing prefers host
        from tempo_tpu.db.search import SearchRequest, search_block

        meta = app.db.blocklist.metas("single-tenant")[0]
        search_block(app.db.open_block(meta),
                     SearchRequest(min_duration_ms=1), mode="device")

        with urllib.request.urlopen(base + "/status/kernels", timeout=10) as r:
            status = json.loads(r.read())
        assert status["jit_cache"]["entries"] >= 1
        assert any(k["op"] == "filter" and k["compiles"] >= 1
                   for k in status["kernels"])
        assert status["routing"], "no routing decisions recorded"
        assert {"layer", "engine", "reason", "count"} <= set(status["routing"][0])
        assert status["staging"]["transfer_bytes_total"] > 0
        assert "hottest" in status["staged_cache"]
        # slow-query log carries ops + durations (self-trace id empty
        # when self-tracing is off)
        assert any(sq["op"] in ("search", "metrics")
                   for sq in status["slow_queries"])

        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        fams = parse_openmetrics_strict(text)
        assert fams.get("tempo_kernel_compiles") == "counter"
        assert fams.get("tempo_kernel_device_seconds") == "histogram"
        assert fams.get("tempo_engine_routing") == "counter"
        assert fams.get("tempo_kernel_jit_cache_entries") == "gauge"
        assert fams.get("tempo_blocklist_length") == "gauge"
        assert fams.get("tempo_ingester_wal_bytes") == "gauge"
        assert fams.get("tempo_frontend_query_duration_seconds") == "histogram"
    finally:
        app.stop()


def test_self_traced_http_search_has_block_spans(tmp_path):
    """With self-tracing on and blocks in the backend, a user search
    yields a self trace whose job runs carry per-block kernel child
    spans -- and the slow-query log records the self-trace id."""
    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire import otlp_json

    cfg = AppConfig(
        storage_path=str(tmp_path / "store"),
        http_port=_free_port(),
        multitenancy=True,
        self_tracing_tenant="self",
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    base = f"http://127.0.0.1:{cfg.http_port}"
    try:
        for _, tr in make_traces(5, seed=13, n_spans=3):
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/traces", data=otlp_json.dumps(tr).encode(),
                headers={"Content-Type": "application/json",
                         "X-Scope-OrgID": "t1"}), timeout=10)
        app.ingester.flush_all()
        app.db.poll_now()
        urllib.request.urlopen(urllib.request.Request(
            base + "/api/search?limit=10",
            headers={"X-Scope-OrgID": "t1"}), timeout=15)
        app.frontend.self_tracer.flush()

        sq = [q for q in TEL.slow_queries(20) if q["op"] == "search"
              and q["self_trace_id"]]
        assert sq, "slow-query log missing self-trace id"
        tid = sq[0]["self_trace_id"]
        with urllib.request.urlopen(urllib.request.Request(
                base + f"/api/traces/{tid}",
                headers={"X-Scope-OrgID": "self"}), timeout=15) as r:
            tr = otlp_json.loads(r.read())
        names = [sp.name for _, _, sp in tr.all_spans()]
        blocks = [sp for _, _, sp in tr.all_spans()
                  if sp.name.startswith("block:")]
        assert "frontend.search" in names
        assert blocks, f"no per-block kernel spans in {names}"
        assert all("engine" in sp.attrs and "compile" in sp.attrs
                   for sp in blocks)
    finally:
        app.stop()
