"""Known-answer + tamper tests for the hand-rolled backend auth
(VERDICT r3 item 9): the signing code is exactly the code most likely
to break against a real endpoint, and the in-process fakes used to
accept anything. Now:

* SigV4 key derivation checks against the AWS-documented derived-key
  vector (docs.aws.amazon.com "Example: derived signing key");
* the canonical request / string-to-sign layouts check against
  hand-transcribed spec literals;
* a server-side verifier (reused by the fake S3) recomputes the
  signature from the RAW request with the shared secret -- a corrupted
  string-to-sign must fail it.
"""

import hashlib
import hmac
import urllib.parse

from tempo_tpu.backend.azure import AzureBackend
from tempo_tpu.backend.s3 import SigV4


def test_sigv4_derived_key_vector():
    """AWS documentation vector ("Example: derived signing key"):
    20150830/us-east-1/iam with the documented example secret must
    produce the documented kSigning hex -- an ABSOLUTE check of the
    HMAC chain against AWS, not against our own code."""
    s = SigV4("AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
              "us-east-1", service="iam")
    assert s.signing_key("20150830").hex() == (
        "c4afb1cc5771d871763a393e44b703571b55cc28424d1a5e86da6ed3c154a4b9"
    )


def test_sigv4_canonical_layout():
    """The canonical request and string-to-sign must follow the spec
    byte-for-byte: sorted+encoded query, lowercase sorted headers each
    ending in \\n, signed-headers list, payload hash; string-to-sign =
    algorithm, date, scope, hash(canonical)."""
    import datetime

    s = SigV4("AK", "SK", "us-east-1")
    now = datetime.datetime(2015, 8, 30, 12, 36, 0, tzinfo=datetime.timezone.utc)
    payload_sha = hashlib.sha256(b"").hexdigest()
    url = "https://examplebucket.s3.amazonaws.com/key%20name?b=2&a=1&a%20x="
    hdrs = s.sign("GET", url, payload_sha, now=now)

    canonical = "\n".join([
        "GET",
        "/key%20name",
        "a=1&a%20x=&b=2",  # sorted, strict percent-encoding, blank kept
        "host:examplebucket.s3.amazonaws.com\n"
        f"x-amz-content-sha256:{payload_sha}\n"
        "x-amz-date:20150830T123600Z\n",
        "host;x-amz-content-sha256;x-amz-date",
        payload_sha,
    ])
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256",
        "20150830T123600Z",
        "20150830/us-east-1/s3/aws4_request",
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])
    expect = hmac.new(s.signing_key("20150830"), to_sign.encode(),
                      hashlib.sha256).hexdigest()
    assert hdrs["Authorization"].endswith(f"Signature={expect}")
    assert "SignedHeaders=host;x-amz-content-sha256;x-amz-date" in hdrs["Authorization"]
    assert hdrs["x-amz-date"] == "20150830T123600Z"


def verify_sigv4_request(method: str, path_qs: str, headers: dict,
                         secret_key: str) -> bool:
    """Server-side SigV4 verification from a RAW request (independent
    reconstruction: parses Authorization for scope + signed headers,
    rebuilds the canonical request from what was actually sent). Used
    by the fake S3 server so a signer/sender mismatch fails tests."""
    auth = headers.get("Authorization", "")
    if not auth.startswith("AWS4-HMAC-SHA256 "):
        return False
    fields = dict(p.strip().split("=", 1) for p in
                  auth[len("AWS4-HMAC-SHA256 "):].split(","))
    scope = fields["Credential"].split("/", 1)[1]  # date/region/service/aws4_request
    datestamp, region, service, _ = scope.split("/")
    signed = fields["SignedHeaders"].split(";")
    u = urllib.parse.urlsplit(path_qs)
    lower = {k.lower(): v for k, v in headers.items()}
    canonical_query = "&".join(
        f"{k}={v}" for k, v in sorted(
            (urllib.parse.quote(k, safe=""), urllib.parse.quote(v, safe=""))
            for k, v in urllib.parse.parse_qsl(u.query, keep_blank_values=True)
        )
    )
    canonical = "\n".join([
        method, u.path or "/", canonical_query,
        "".join(f"{h}:{lower[h]}\n" for h in signed),
        ";".join(signed),
        lower.get("x-amz-content-sha256", ""),
    ])
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", lower["x-amz-date"], scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])

    def _h(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _h(("AWS4" + secret_key).encode(), datestamp)
    k = _h(k, region)
    k = _h(k, service)
    k = _h(k, "aws4_request")
    expect = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    return hmac.compare_digest(expect, fields["Signature"])


def test_sigv4_server_side_verify_and_tamper():
    s = SigV4("AK", "wrong-or-right", "us-east-1")
    url = "https://h.example/bkt/obj%20x?versions=&prefix=a%2Fb"
    sha = hashlib.sha256(b"body").hexdigest()
    hdrs = s.sign("PUT", url, sha)
    u = urllib.parse.urlsplit(url)
    req_headers = {"Host": u.netloc, **hdrs}
    path_qs = u.path + ("?" + u.query if u.query else "")
    assert verify_sigv4_request("PUT", path_qs, req_headers, "wrong-or-right")
    # tampered string-to-sign: ANY canonical ingredient change must fail
    assert not verify_sigv4_request("GET", path_qs, req_headers, "wrong-or-right")
    assert not verify_sigv4_request("PUT", u.path + "?prefix=a%2Fc", req_headers,
                                    "wrong-or-right")
    assert not verify_sigv4_request("PUT", path_qs, req_headers, "other-secret")
    bad = dict(req_headers)
    bad["x-amz-content-sha256"] = hashlib.sha256(b"evil").hexdigest()
    assert not verify_sigv4_request("PUT", path_qs, bad, "wrong-or-right")


def test_azure_shared_key_layout_and_tamper():
    """SharedKey string-to-sign layout per the Azure spec: VERB + 12
    header slots + canonicalized x-ms-* headers + canonicalized
    resource; corrupting any slot changes the MAC."""
    import base64

    key = base64.b64encode(b"0123456789abcdef0123456789abcdef").decode()
    be = AzureBackend.__new__(AzureBackend)
    be.account = "acct"
    be.key = base64.b64decode(key)

    url = "https://acct.blob.core.windows.net/container/blob%20name?comp=list&restype=container"
    hdrs = {"x-ms-version": "2021-08-06",
            "x-ms-date": "Sun, 30 Aug 2015 12:36:00 GMT"}
    auth = be._sign("PUT", url, hdrs, "42", "application/octet-stream")
    assert auth.startswith("SharedKey acct:")

    # 2015-04-05 scheme: VERB, Content-Encoding, Content-Language,
    # Content-Length, Content-MD5, Content-Type, Date (empty: x-ms-date
    # wins), If-Modified-Since, If-Match, If-None-Match,
    # If-Unmodified-Since, Range; then canonicalized x-ms-* headers
    # (lexicographic, one per line) and the canonicalized resource
    # (/account/path + sorted decoded query as name:value lines)
    to_sign = "\n".join([
        "PUT", "", "", "42", "", "application/octet-stream",
        "", "", "", "", "", "",
    ]) + "\n" + (
        "x-ms-date:Sun, 30 Aug 2015 12:36:00 GMT\n"
        "x-ms-version:2021-08-06\n"
    ) + "/acct/container/blob%20name\ncomp:list\nrestype:container"
    import hmac as _hmac

    expect = base64.b64encode(
        _hmac.new(be.key, to_sign.encode(), hashlib.sha256).digest()).decode()
    assert auth == f"SharedKey acct:{expect}", (
        "SharedKey string-to-sign drifted from the spec layout"
    )
    # tamper: different verb / length -> different MAC
    assert be._sign("GET", url, hdrs, "42", "application/octet-stream") != auth
    assert be._sign("PUT", url, hdrs, "43", "application/octet-stream") != auth


def test_fake_s3_rejects_bad_signature(tmp_path):
    """End to end: the verifying fake S3 403s a client signing with the
    wrong secret (the 'deliberately corrupted string-to-sign fails'
    acceptance check), while the right secret round-trips."""
    import threading
    from http.server import ThreadingHTTPServer

    from test_backend_s3 import _FakeS3

    import tempo_tpu.backend.s3 as s3mod

    _FakeS3.store = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}"
    try:
        good = s3mod.S3Backend(url, "bkt", access_key="ak", secret_key="sk")
        good.write("t", "b1", "meta.json", b"ok")
        assert good.read("t", "b1", "meta.json") == b"ok"

        bad = s3mod.S3Backend(url, "bkt", access_key="ak", secret_key="WRONG")
    
        import pytest as _pytest

        with _pytest.raises(Exception):
            bad.write("t", "b2", "meta.json", b"x")
        # and nothing landed
        assert not any(k.endswith("b2/meta.json") for k in _FakeS3.store)
    finally:
        srv.shutdown()
