"""Device kernel tests: lookup, predicate filter, bloom ops.

Run on the 8-virtual-device CPU platform (conftest); results are checked
against numpy oracles over the same block columns."""

import numpy as np
import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.block import build_block_from_traces, open_block
from tempo_tpu.block import schema as S
from tempo_tpu.block.bloom import ShardedBloom
from tempo_tpu.ops import bloom_ops
from tempo_tpu.ops.filter import Cond, Operands, eval_block, required_columns
from tempo_tpu.ops.find import lookup_ids
from tempo_tpu.ops.stage import stage_block
from tempo_tpu.util.testdata import make_traces

TENANT = "t"


@pytest.fixture(scope="module")
def block():
    backend = MemBackend()
    traces = make_traces(120, seed=5, n_spans=10)
    meta = build_block_from_traces(backend, TENANT, traces, row_group_spans=256)
    return open_block(backend, TENANT, meta.block_id), traces


def test_lookup_ids(block):
    blk, traces = block
    codes = blk.trace_index["trace.id_codes"]
    # every present id found at the right sid
    queries = np.asarray([S.trace_id_to_codes(tid) for tid, _ in traces], dtype=np.int32)
    sids = lookup_ids(codes, queries)
    np.testing.assert_array_equal(sids, np.arange(len(traces)))
    # misses return -1
    miss = np.asarray(
        [S.trace_id_to_codes(b"\x00" * 16), S.trace_id_to_codes(b"\xff" * 16)], dtype=np.int32
    )
    np.testing.assert_array_equal(lookup_ids(codes, miss), [-1, -1])


def test_lookup_extreme_ids():
    # ids around the signed/unsigned transform boundary
    ids = sorted([b"\x00" * 16, b"\x7f" + b"\xff" * 15, b"\x80" + b"\x00" * 15, b"\xff" * 16])
    codes = np.asarray([S.trace_id_to_codes(t) for t in ids], dtype=np.int32)
    sids = lookup_ids(codes, codes)
    np.testing.assert_array_equal(sids, np.arange(4))


def _oracle_span_mask(blk, pred):
    """numpy oracle: spans matching pred(dict of host arrays) -> bool (n_spans,)"""
    cols = blk.pack.read_all()
    return pred(cols)


def test_filter_service_eq(block):
    blk, traces = block
    d = blk.dictionary
    svc = "db"
    code = d.lookup(svc)
    assert code >= 0
    conds = (Cond(target="res", col="res.service_id", op="eq"),)
    ops = Operands.build([(0, code, 0, 0.0, 0.0)])
    staged = stage_block(blk, required_columns(conds))
    span_mask, trace_mask, counts = eval_block(
        conds, "and", staged.cols, ops,
        staged.n_spans, staged.n_traces, staged.n_spans_b, staged.n_res_b, staged.n_traces_b,
    )
    span_mask = np.asarray(span_mask)[: staged.n_spans]
    oracle = _oracle_span_mask(
        blk, lambda c: c["res.service_id"][c["span.res_idx"]] == code
    )
    np.testing.assert_array_equal(span_mask, oracle)
    # trace mask agrees with any-span aggregation
    tm = np.asarray(trace_mask)[: staged.n_traces]
    sid = blk.pack.read("span.trace_sid")
    oracle_tm = np.zeros(staged.n_traces, dtype=bool)
    np.maximum.at(oracle_tm, sid, oracle)
    np.testing.assert_array_equal(tm, oracle_tm)
    assert np.asarray(counts)[: staged.n_traces].sum() == oracle.sum()


def test_filter_attr_and_duration(block):
    blk, _ = block
    d = blk.dictionary
    method_code = d.lookup("GET")
    key_code = d.lookup("http.method")
    assert method_code >= 0 and key_code >= 0
    dur_thresh_us = 500_000  # 500ms
    conds = (
        Cond(target="sattr", col="str", op="eq"),
        Cond(target="span", col="span.dur_us", op="ge"),
    )
    ops = Operands.build([
        (key_code, method_code, 0, 0.0, 0.0),
        (0, dur_thresh_us, 0, 0.0, 0.0),
    ])
    staged = stage_block(blk, required_columns(conds))
    span_mask, trace_mask, _ = eval_block(
        conds, "and", staged.cols, ops,
        staged.n_spans, staged.n_traces, staged.n_spans_b, staged.n_res_b, staged.n_traces_b,
    )
    span_mask = np.asarray(span_mask)[: staged.n_spans]

    def oracle(c):
        hit = np.zeros(staged.n_spans, dtype=bool)
        rows = (c["sattr.key_id"] == key_code) & (c["sattr.vtype"] == 0) & (c["sattr.str_id"] == method_code)
        np.maximum.at(hit, c["sattr.span"], rows)
        return hit & (c["span.dur_us"] >= dur_thresh_us)

    np.testing.assert_array_equal(span_mask, _oracle_span_mask(blk, oracle))
    assert span_mask.sum() > 0  # query actually selects something


def test_filter_int_attr(block):
    blk, _ = block
    d = blk.dictionary
    key_code = d.lookup("http.status_code")
    conds = (Cond(target="sattr", col="int", op="eq"),)
    ops = Operands.build([(key_code, 500, 0, 0.0, 0.0)])
    staged = stage_block(blk, required_columns(conds))
    span_mask, _, _ = eval_block(
        conds, "and", staged.cols, ops,
        staged.n_spans, staged.n_traces, staged.n_spans_b, staged.n_res_b, staged.n_traces_b,
    )
    span_mask = np.asarray(span_mask)[: staged.n_spans]

    def oracle(c):
        hit = np.zeros(staged.n_spans, dtype=bool)
        rows = (c["sattr.key_id"] == key_code) & (c["sattr.vtype"] == 1) & (c["sattr.int32"] == 500)
        np.maximum.at(hit, c["sattr.span"], rows)
        return hit

    np.testing.assert_array_equal(span_mask, _oracle_span_mask(blk, oracle))
    assert span_mask.sum() > 0


def test_filter_group_range(block):
    """Staging a row-group subrange gives the same hits as slicing the full mask."""
    blk, _ = block
    d = blk.dictionary
    code = d.lookup("db.query")
    conds = (Cond(target="span", col="span.name_id", op="eq"),)
    ops = Operands.build([(0, code, 0, 0.0, 0.0)])

    full = stage_block(blk, required_columns(conds))
    fm, _, _ = eval_block(conds, "and", full.cols, ops, full.n_spans, full.n_traces,
                          full.n_spans_b, full.n_res_b, full.n_traces_b)
    fm = np.asarray(fm)[: full.n_spans]

    part = stage_block(blk, required_columns(conds), groups=[1])
    pm, _, _ = eval_block(conds, "and", part.cols, ops, part.n_spans, part.n_traces,
                          part.n_spans_b, part.n_res_b, part.n_traces_b)
    pm = np.asarray(pm)[: part.n_spans]
    np.testing.assert_array_equal(pm, fm[part.span_base : part.span_base + part.n_spans])


def test_bloom_union_and_batch_test():
    b1 = ShardedBloom(4, 1 << 13)
    b2 = ShardedBloom(4, 1 << 13)
    ids1 = [bytes([1, i]) + b"\x00" * 14 for i in range(50)]
    ids2 = [bytes([2, i]) + b"\x00" * 14 for i in range(50)]
    b1.add_many(ids1)
    b2.add_many(ids2)
    u = bloom_ops.union_blooms([b1, b2])
    assert all(u.test(t) for t in ids1 + ids2)
    hits = bloom_ops.batch_test(u.words, u.shard_bits, u.n_shards, ids1 + ids2)
    assert hits.all()
    misses = bloom_ops.batch_test(
        u.words, u.shard_bits, u.n_shards, [bytes([9, i]) + b"\x01" * 14 for i in range(100)]
    )
    assert misses.sum() < 10
    with pytest.raises(ValueError):
        bloom_ops.union_blooms([b1, ShardedBloom(2, 1 << 13)])
