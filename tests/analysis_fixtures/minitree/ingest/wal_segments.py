"""Seeded violations in the ingest-plane lock shapes (PR-16
device-native ingest): the columnar feature cache's install/evict
lock, the WAL head's segment append path, and the feature-checkpoint
condition variable -- the lock pairs ingest/columnar.py and
services/ingester.py use, so the concurrency rules provably cover the
write path's new state. Every EXPECT marker is asserted by
tests/test_analysis.py against the exact line it sits on."""

import threading

_cache_lock = threading.Lock()
_features: dict[int, tuple] = {}  # id(segment) -> SegFeatures
_head_lock = threading.Lock()
_pending: list[tuple[int, int]] = []  # (window_idx, trace_idx)
_checkpoint_cv = threading.Condition()
_windows = 0


def install(seg_id, feat):
    # sanctioned: cache mutation under its dedicated lock
    with _cache_lock:
        _features[seg_id] = feat
        return len(_features)


def install_racy(seg_id, feat):
    _features[seg_id] = feat  # EXPECT: global-mutation-unlocked


def append_window_racy(n_traces):
    global _windows
    _windows = _windows + 1  # EXPECT: global-mutation-unlocked
    for i in range(n_traces):
        _pending.append((_windows, i))  # EXPECT: global-mutation-unlocked


def checkpoint_features():
    # sanctioned order: checkpoint cv outer, head lock inner (the
    # sweeper drains pending features, then touches the append file)
    with _checkpoint_cv:
        drained = list(_pending)
        with _head_lock:
            _checkpoint_cv.notify_all()
        return drained


def append_then_checkpoint_racy():
    with _head_lock:
        with _checkpoint_cv:  # EXPECT: lock-order
            _pending.clear()


def pending_depth_unsafe():
    _checkpoint_cv.acquire()  # EXPECT: lock-bare-acquire
    n = len(_pending)
    _checkpoint_cv.release()
    return n


def pending_depth_safe():
    _checkpoint_cv.acquire()
    try:
        return len(_pending)
    finally:
        _checkpoint_cv.release()


def evict_half():
    with _cache_lock:
        for k in list(_features)[: len(_features) // 2]:
            _features.pop(k, None)
    return len(_features)
