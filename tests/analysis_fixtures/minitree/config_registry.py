"""Seeded config-registry fixture: one healthy knob, one undocumented
knob, one dead knob (see ../README.md for the doc side)."""

KNOBS = {
    "TEMPO_FIX_DOCUMENTED": (
        "bool", "1", "read by services/env_knobs.py and documented"),
    "TEMPO_FIX_UNDOCUMENTED": (  # EXPECT: env-doc-drift
        "int", "4", "read, registered, but absent from every doc"),
    "TEMPO_FIX_DEAD_KNOB": (  # EXPECT: env-dead
        "str", "", "documented but nothing reads it"),
}
