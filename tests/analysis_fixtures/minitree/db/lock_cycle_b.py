"""Seeded cross-module lock cycle, B side: takes LOCK_B then calls
back into A while holding it (the finding anchors on the A side's
minimal edge)."""

import threading

from .lock_cycle_a import touch_a

LOCK_B = threading.Lock()


def helper_b() -> None:
    with LOCK_B:
        pass


def path_ba() -> None:
    with LOCK_B:
        touch_a()
