"""Fixture executor: imports one registered kernel and one orphan."""

from ..ops.hostk import search_host
from ..ops.kern import make_kern
from ..ops.kern import orphan_kernel, search_kernel  # EXPECT: twin-missing


def run(x):
    return search_kernel(x), orphan_kernel(x), search_host(x)


def run_compile_storm(x):
    # executor-side value-keyed factory call: the cross-module pass
    # must catch what the per-module pass cannot see
    fn = make_kern(int(x.max()))  # EXPECT: jit-value-key
    return fn(x)
