"""Seeded cross-module lock cycle, A side: takes LOCK_A then calls
into B while holding it. Nothing lexical in either module inverts --
only the interprocedural lock graph sees the cycle."""

import threading

from .lock_cycle_b import helper_b

LOCK_A = threading.Lock()


def path_ab() -> None:
    with LOCK_A:
        helper_b()  # EXPECT: lock-order-global


def touch_a() -> None:
    with LOCK_A:
        pass
