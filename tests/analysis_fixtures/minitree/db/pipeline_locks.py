"""Seeded violations in the compaction-pipeline lock shape: an
admission condition guarding in-flight job/byte registries plus a
separate stats lock -- the lock pairs db/compact_pipeline.py uses, so
the concurrency rules provably cover this module shape."""

import threading

_admission_lock = threading.Condition()
_stats_lock = threading.Lock()
_inflight: dict[str, int] = {}
_stage_seconds: dict[str, float] = {}


def admit(job_id, est):
    _inflight[job_id] = est  # EXPECT: global-mutation-unlocked


def release(job_id):
    with _admission_lock:
        _inflight.pop(job_id, None)


def record_stage_ab(stage, dt):
    with _admission_lock:
        with _stats_lock:
            _stage_seconds[stage] = _stage_seconds.get(stage, 0.0) + dt


def snapshot_ba():
    with _stats_lock:
        with _admission_lock:  # EXPECT: lock-order
            return dict(_inflight), dict(_stage_seconds)


def drain_unsafe():
    _admission_lock.acquire()  # EXPECT: lock-bare-acquire
    n = len(_inflight)
    _admission_lock.release()
    return n


def drain_safe():
    _admission_lock.acquire()
    try:
        _inflight.clear()
    finally:
        _admission_lock.release()
