"""Seeded healthy chaos seam: claims and actually names its site."""


def poke(plane) -> object:
    return plane.tap("fix.tapped", key="poke")
