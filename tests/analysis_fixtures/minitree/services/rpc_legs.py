"""Seeded resilience fixture: deadline and guarding contracts on
remote legs. Claimed with an empty seam tuple in chaos/plane.py (fault
source, not a seam) so only the deadline/guard rules fire here."""

import urllib.request


def no_deadline(url: str) -> bytes:
    return urllib.request.urlopen(url).read()  # EXPECT: rpc-no-deadline


def with_deadline(url: str) -> bytes:
    return urllib.request.urlopen(url, timeout=5.0).read()


def push_blind(client, blob: bytes) -> int:
    return client.push_segments(blob)  # EXPECT: rpc-unguarded


def push_caught(client, blob: bytes) -> int:
    try:
        return client.push_segments(blob)
    except OSError:
        return 0
