"""Seeded violations in the cache-affinity scheduler's lock shapes: a
worker-membership registry refreshed on every poll, the request queue's
condition variable, and the per-tenant QoS admission lock -- the lock
pairs services/frontend.py and services/overrides.py use, so the
concurrency rules provably cover the affinity scheduling module shape."""

import threading

_members: dict[str, float] = {}  # worker id -> last poll (monotonic)
_queue_cv = threading.Condition()
_qos_lock = threading.Lock()
_inflight: dict[str, int] = {}  # tenant -> queries in flight


def register(worker, now):
    # sanctioned: membership refresh under the queue condition
    with _queue_cv:
        _members[worker] = now
        _queue_cv.notify_all()


def register_racy(worker, now):
    _members[worker] = now  # EXPECT: global-mutation-unlocked


def admit(tenant):
    with _qos_lock:
        _inflight[tenant] = _inflight.get(tenant, 0) + 1


def claim_then_admit(tenant):
    # sanctioned order: queue cv outer, QoS lock inner
    with _queue_cv:
        with _qos_lock:
            _inflight[tenant] = _inflight.get(tenant, 0) + 1


def admit_then_claim_racy(tenant):
    with _qos_lock:
        with _queue_cv:  # EXPECT: lock-order
            _members.pop(tenant, None)


def steal_scan_unsafe():
    _queue_cv.acquire()  # EXPECT: lock-bare-acquire
    n = len(_members)
    _queue_cv.release()
    return n


def steal_scan_safe():
    _queue_cv.acquire()
    try:
        _members.clear()
    finally:
        _queue_cv.release()
