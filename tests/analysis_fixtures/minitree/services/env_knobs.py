"""Seeded env-read fixture: registered reads plus one rogue knob."""

import os


def documented() -> bool:
    return os.environ.get("TEMPO_FIX_DOCUMENTED", "1") != "0"


def undocumented() -> int:
    return int(os.environ.get("TEMPO_FIX_UNDOCUMENTED", "4"))


def rogue() -> str:
    return os.environ.get("TEMPO_FIX_ROGUE", "")  # EXPECT: env-unregistered
