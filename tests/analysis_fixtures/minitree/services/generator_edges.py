"""Seeded violations over the metrics-generator shapes: the coded
edge store (pending client/server halves) and the series registry,
whose module-level maps are exactly the state the concurrency passes
must keep honest under the streaming tap's worker thread."""

import threading

_edge_lock = threading.Lock()
_series_lock = threading.Lock()
_pending_edges: dict[int, tuple] = {}
_series: dict[int, int] = {}
_EXPIRED = 0


def open_edge(key, svc):
    _pending_edges[key] = (svc, None)  # EXPECT: global-mutation-unlocked


def expire_edges(cutoff):
    global _EXPIRED
    _EXPIRED = cutoff  # EXPECT: global-mutation-unlocked


def open_edge_guarded(key, svc):
    with _edge_lock:
        _pending_edges[key] = (svc, None)


def _drain_pending_locked():
    # *_locked convention: the caller holds _edge_lock
    _pending_edges.clear()


def fold_then_pair(sid, key):
    with _series_lock:
        with _edge_lock:
            _series[sid] = _series.get(sid, 0) + 1
            return _pending_edges.get(key)


def pair_then_fold(sid, key):
    with _edge_lock:
        with _series_lock:  # EXPECT: lock-order
            _series[sid] = _series.get(sid, 0) + 1
            return _pending_edges.get(key)


def shed_series_unsafe(sid):
    _series_lock.acquire()  # EXPECT: lock-bare-acquire
    n = _series.get(sid, 0)
    _series_lock.release()
    return n


def shed_series_safe(sid):
    # sanctioned non-with form: the try body holds the lock, so the
    # registry mutation inside must NOT fire the global rule
    _series_lock.acquire()
    try:
        _series[sid] = 0
        return len(_series)
    finally:
        _series_lock.release()
