"""Seeded violations in the self-trace timeline spine's lock shapes:
the tracer's in-flight counter + processed-ack condition variable, a
trace's span-list lock, and the ambient-span contextvar token
discipline -- the lock pairs services/selftrace.py uses, so the
concurrency rules provably cover the span/contextvar module shape."""

import contextvars
import threading

_ambient_span = contextvars.ContextVar("fixture_span", default=None)
_done_cv = threading.Condition()
_span_lock = threading.Lock()
_spans: list[tuple] = []
_inflight: dict[str, int] = {}


def push_span(span_id):
    # sanctioned: contextvar token discipline is not a container mutation
    token = _ambient_span.set(span_id)
    return token


def record(name, t0, t1):
    # sanctioned: span append under the span lock
    with _span_lock:
        _spans.append((name, t0, t1))


def record_racy(name, t0, t1):
    _spans.append((name, t0, t1))  # EXPECT: global-mutation-unlocked


def enqueue(trace_id):
    # sanctioned order: processed-ack cv outer, span lock inner
    with _done_cv:
        with _span_lock:
            _inflight[trace_id] = _inflight.get(trace_id, 0) + 1
        _done_cv.notify_all()


def flush_racy(trace_id):
    with _span_lock:
        with _done_cv:  # EXPECT: lock-order
            _inflight.pop(trace_id, None)


def drain_unsafe():
    _done_cv.acquire()  # EXPECT: lock-bare-acquire
    n = len(_spans)
    _done_cv.release()
    return n


def drain_safe():
    _done_cv.acquire()
    try:
        _spans.clear()
    finally:
        _done_cv.release()
