"""Seeded seam gap: remote side effect in scope, module claims no
seam in chaos/plane.py SEAM_MODULES."""

import urllib.request


def fetch(url: str) -> bytes:
    return urllib.request.urlopen(url, timeout=2.0).read()  # EXPECT: chaos-seam-gap
