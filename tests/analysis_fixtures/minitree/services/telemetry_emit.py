"""Seeded telemetry fixture: one family the ops files know, one orphan,
and both sides of the label-escaping contract."""

from util.metrics import Counter, Gauge

PUSHES = Counter("tempo_fix_pushes_total")
ORPHAN_DEPTH = Gauge("tempo_fix_orphan_depth")  # EXPECT: metric-orphan


def _esc(v: str) -> str:
    return v.replace('"', '\\"')


def render_bad(tenant: str) -> list[str]:
    return [f'tempo_fix_pushes_total{{tenant="{tenant}"}} 1']  # EXPECT: metric-label-cardinality


def render_ok(tenant: str) -> list[str]:
    t = _esc(tenant)
    return [f'tempo_fix_pushes_total{{tenant="{t}"}} 1',
            f'tempo_fix_pushes_total{{tenant="{_esc(tenant)}"}} 1']
