"""Seeded chaos-seam registry: one healthy claim, one unclaimed site,
one claim on a missing module, one claim the module never names."""

SITES = {
    "fix.tapped": "healthy: claimed by services/tapped.py which names it",
    "fix.orphan_site": "declared but no module claims it",  # EXPECT: chaos-seam-gap
}

SEAM_MODULES = {  # EXPECT: chaos-seam-gap
    "services/tapped.py": ("fix.tapped",),
    "services/ghost.py": ("fix.ghost",),
    "services/env_knobs.py": ("fix.unnamed",),
    "services/rpc_legs.py": (),
}
