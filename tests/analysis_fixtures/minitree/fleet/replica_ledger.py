"""Seeded fleet-serving concurrency violations (the PR-18 replication
shapes). Every EXPECT marker is asserted by tests/test_analysis.py: the
per-replica write ledger and the poller shard-map cache are process-wide
registries shared by the distributor's push threads and the blocklist
poll loop -- exactly the shapes the live tree (fleet/replication.py,
fleet/poller_shard.py) must keep lock-guarded."""

import threading

_write_ledger = {"quorum": 0, "partial": 0, "failed": 0}
_shard_cache = {}
_ledger_lock = threading.Lock()
_shard_lock = threading.Lock()


def record_outcome_nolock(outcome):
    _write_ledger[outcome] = _write_ledger[outcome] + 1  # EXPECT: global-mutation-unlocked
    return _write_ledger[outcome]


def cache_owner_nolock(tenant, owner):
    br = _shard_cache.get(tenant)
    if br is None:
        _shard_cache[tenant] = br = owner  # EXPECT: global-mutation-unlocked
    return br


def reset_tenant(tenant):
    # establishes the module-wide order: ledger OUTER, shard INNER
    with _ledger_lock:
        with _shard_lock:
            _shard_cache.pop(tenant, None)


def rebalance(tenant, owner):
    with _shard_lock:
        with _ledger_lock:  # EXPECT: lock-order
            _shard_cache[tenant] = owner


def quorum_floor():
    _ledger_lock.acquire()  # EXPECT: lock-bare-acquire
    n = _write_ledger["quorum"]
    _ledger_lock.release()
    return n
