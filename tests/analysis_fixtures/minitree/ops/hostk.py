"""Numpy twin for the twin-registry fixtures."""

import numpy as np


def search_host(x):
    return np.cumsum(x)
