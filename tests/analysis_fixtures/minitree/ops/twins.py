"""Twin registry for the fixture minitree: one good entry, one stale."""

DEVICE_HOST_TWINS = {
    "ops.kern.search_kernel": "ops.hostk.search_host",
    "ops.kern.gone_kernel": "ops.hostk.search_host",  # EXPECT: twin-unresolvable
}

DEVICE_ONLY = {
    "ops.kern.make_kern": "compile factory, not an eval entry point",
}
