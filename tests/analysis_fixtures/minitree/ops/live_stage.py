"""Seeded violations in the live-head staging lock/epoch shape: the
stager's tail lock guarding slot/row mutation and the generation
(epoch) counter, plus the staging-lag pending-push stamp lock -- the
lock pairs ops/livestage.py and db/live_engine.py use, so the
concurrency rules provably cover the live-stage module shape."""

import threading

_tails: dict[bytes, int] = {}  # trace id -> slot
_tail_lock = threading.RLock()
_pending_lock = threading.Lock()
_pending_push: dict[bytes, float] = {}
_generation = 0


def refresh(tid, slot):
    # sanctioned: slot assignment and the epoch bump share the tail lock,
    # so a snapshot can never observe a half-applied generation
    global _generation
    with _tail_lock:
        _tails[tid] = slot
        _generation += 1
        return _generation


def refresh_racy(tid, slot):
    global _generation
    _tails[tid] = slot  # EXPECT: global-mutation-unlocked
    _generation += 1  # EXPECT: global-mutation-unlocked
    return _generation


def note_push(tid, now):
    with _pending_lock:
        _pending_push.setdefault(tid, now)


def retire_tail_then_pending(tid):
    # sanctioned order: tail lock outer, pending-stamp lock inner
    with _tail_lock:
        with _pending_lock:
            _pending_push.pop(tid, None)
            _tails.pop(tid, None)


def stamp_pending_then_tail(tid, now):
    with _pending_lock:
        with _tail_lock:  # EXPECT: lock-order
            _tails.setdefault(tid, len(_tails))
            _pending_push[tid] = now


def generation_peek_unsafe():
    _tail_lock.acquire()  # EXPECT: lock-bare-acquire
    g = _generation
    _tail_lock.release()
    return g


def generation_peek_safe():
    _tail_lock.acquire()
    try:
        return _generation
    finally:
        _tail_lock.release()
