"""Seeded batched-mesh launch-key violations: the Q-bucket of a
mesh multiquery factory keys the compiled shard_map program exactly
like an axis bucket, so deriving it from DATA (a live occupancy count
off an array) compiles one program per occupancy -- a compile storm
the jit-value-key pass must keep catching on the new module shape.
Every EXPECT marker is asserted by tests/test_analysis.py. This file
is never imported."""

from functools import lru_cache

import jax
import jax.numpy as jnp


@lru_cache(maxsize=32)
def make_mesh_multiquery(shape, q_b: int, n_spans_b: int):
    @jax.jit
    def run(span_mat, progs):
        return jnp.cumsum(span_mat, axis=1)[:q_b]

    return run


def launch_window(shape, span_mat, progs, occupancy_rows):
    # q_b must be the padded power-of-two window bucket, never a value
    # read back off a device array
    fn = make_mesh_multiquery(shape, int(occupancy_rows.max()), 1024)  # EXPECT: jit-value-key
    return fn(span_mat, progs)


def launch_window_ok(shape, span_mat, progs, q_b: int):
    fn = make_mesh_multiquery(shape, q_b, span_mat.shape[1])
    return fn(span_mat, progs)
