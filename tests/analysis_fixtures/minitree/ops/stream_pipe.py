"""Seeded violations in the cold-read stream-pipeline lock shape: a
lazily-built shared stage executor, a byte-budget admission gate
(Condition) with a per-pipeline ordering turnstile, and a stats
registry -- the lock pairs ops/stream.py uses, so the concurrency
rules provably cover this module shape."""

import threading

_pool = None
_pool_lock = threading.Lock()
_gate_cv = threading.Condition()
_turn_cv = threading.Condition()
_inflight: dict[int, int] = {}
_stage_seconds: dict[str, float] = {}


def executor():
    # sanctioned: the singleton rebind happens under its lock
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = object()
        return _pool


def executor_racy():
    global _pool
    if _pool is None:
        _pool = object()  # EXPECT: global-mutation-unlocked
    return _pool


def admit(unit_id, est):
    with _gate_cv:
        _inflight[unit_id] = est
        _gate_cv.notify_all()


def admit_racy(unit_id, est):
    _inflight[unit_id] = est  # EXPECT: global-mutation-unlocked


def record_stage_gate_then_turn(stage, dt):
    with _gate_cv:
        with _turn_cv:
            _stage_seconds[stage] = _stage_seconds.get(stage, 0.0) + dt


def snapshot_turn_then_gate():
    with _turn_cv:
        with _gate_cv:  # EXPECT: lock-order
            return dict(_inflight), dict(_stage_seconds)


def gate_wait_unsafe():
    _gate_cv.acquire()  # EXPECT: lock-bare-acquire
    n = len(_inflight)
    _gate_cv.release()
    return n


def gate_wait_safe():
    _gate_cv.acquire()
    try:
        _inflight.clear()
    finally:
        _gate_cv.release()
