"""Seeded kernel-contract violations. Every EXPECT marker is asserted
by tests/test_analysis.py to produce exactly that finding on exactly
that line -- and nothing else. This file is never imported."""

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def sync_kernel(x):
    total = x.sum()
    host = float(x)  # EXPECT: jit-host-sync
    v = total.item()  # EXPECT: jit-host-sync
    arr = np.asarray(x)  # EXPECT: jit-host-sync
    y = np.where(x > 0, 1, 0)  # EXPECT: jit-numpy
    return jnp.sum(x) + host + v + arr.shape[0] + y


@partial(jax.jit, static_argnames=("n_steps",))
def timed_kernel(x, n_steps):
    y = x * n_steps
    y.block_until_ready()  # EXPECT: jit-host-sync
    return y


@jax.jit
def ok_kernel(x):
    # dtype constructors and static shape math are legitimate in-trace
    n = np.int32(x.shape[0])
    return jnp.cumsum(x.astype(jnp.float32)) + n


def make_kernel(n):  # EXPECT: jit-uncached-factory
    def body(x):
        return jnp.sum(x) * n

    return jax.jit(body)


@lru_cache(maxsize=8)
def make_loop_kernels(count: int):
    kernels = []
    for scale in range(count):

        @jax.jit
        def body(x):
            return x * scale  # EXPECT: jit-nonstatic-capture

        kernels.append(body)
    return kernels


@lru_cache(maxsize=8)
def compiled_scale(k: int):
    @jax.jit
    def body(x):
        return x * k  # enclosing-factory param: static by construction

    return body


def run_scaled(x):
    fn = compiled_scale(int(x.max()))  # EXPECT: jit-value-key
    return fn(x)


def run_scaled_ok(x):
    # shape-derived key: the sanctioned pattern (ops/device.bucket)
    fn = compiled_scale(int(x.shape[0]))
    return fn(x)


@partial(jax.jit, static_argnames=("n_steps",))
def stepped_kernel(x, n_steps):
    for _ in range(n_steps):
        x = x * 2
    return x


def run_stepped(x):
    # static_argnames passed by KEYWORD key compiles just like
    # positional static args
    return stepped_kernel(x, n_steps=x.max())  # EXPECT: jit-value-key


def run_stepped_ok(x):
    return stepped_kernel(x, n_steps=x.shape[0].bit_length())


@lru_cache(maxsize=8)
def make_branch_kernel(flag: bool):
    # bound once per call across disjoint branches: static for the
    # closure, must NOT fire the capture rule
    if flag:
        scale2 = 1
    else:
        scale2 = 2

    @jax.jit
    def body(x):
        return x * scale2

    return body


def entry_with_cached_factory(x, n):
    # outer wrapper around a properly cached factory: must NOT fire
    # jit-uncached-factory (the cached def owns the jit creation)
    @lru_cache(maxsize=4)
    def factory(k: int):
        @jax.jit
        def body(v):
            return v * k

        return body

    return factory(n)(x)


def _wrapped_impl(x):
    v = x.sum().item()  # EXPECT: jit-host-sync
    return x * v


# module-level jit wrapping (no decorator) is a jit region too
wrapped_kernel = jax.jit(_wrapped_impl)
