"""Device kernels for the twin-registry fixtures."""

from functools import lru_cache

import jax
import jax.numpy as jnp


@jax.jit
def search_kernel(x):
    return jnp.cumsum(x)


@jax.jit
def orphan_kernel(x):
    return x * 2


@lru_cache(maxsize=8)
def make_kern(n: int):
    @jax.jit
    def body(x):
        return x * n

    return body
