"""Seeded circuit-breaker / retry-budget concurrency violations (the
PR-14 resilience shapes). Every EXPECT marker is asserted by
tests/test_analysis.py: the breaker registry and the process-wide
retry-budget counter are exactly the kind of shared state the live
tree (util/breaker.py) must keep lock-guarded."""

import threading

_breakers = {}
_budget = {"total": 8, "used": 0}
_registry_lock = threading.Lock()
_state_lock = threading.Lock()


def get_breaker_nolock(name):
    br = _breakers.get(name)
    if br is None:
        _breakers[name] = br = object()  # EXPECT: global-mutation-unlocked
    return br


def take_budget_nolock():
    _budget["used"] = _budget["used"] + 1  # EXPECT: global-mutation-unlocked
    return _budget["used"] <= _budget["total"]


def trip(name):
    # establishes the module-wide order: registry OUTER, state INNER
    with _registry_lock:
        with _state_lock:
            _breakers[name] = "open"


def half_open(name):
    with _state_lock:
        with _registry_lock:  # EXPECT: lock-order
            _breakers[name] = "half_open"


def probe_quota():
    _state_lock.acquire()  # EXPECT: lock-bare-acquire
    n = _budget["total"]
    _state_lock.release()
    return n
