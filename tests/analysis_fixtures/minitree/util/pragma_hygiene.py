"""Seeded pragma-hygiene fixture: a reasonless suppression that works,
and a reasoned suppression that suppresses nothing."""

_flags = {}


def set_flag(k) -> None:
    _flags[k] = True  # tempo: ignore[global-mutation-unlocked] # EXPECT: pragma-no-reason


def read_flag(k):
    return _flags.get(k)  # tempo: ignore[global-mutation-unlocked] reads mutate nothing # EXPECT: pragma-unused
