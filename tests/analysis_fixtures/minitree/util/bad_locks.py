"""Seeded concurrency violations (and their sanctioned counterparts)."""

import threading

_lock = threading.Lock()
_other_lock = threading.Lock()
_registry: dict[str, int] = {}
_BUDGET = 100


def record(name):
    _registry[name] = _registry.get(name, 0) + 1  # EXPECT: global-mutation-unlocked


def forget(name):
    _registry.pop(name, None)  # EXPECT: global-mutation-unlocked


def set_budget(n):
    global _BUDGET
    _BUDGET = n  # EXPECT: global-mutation-unlocked


def set_budget_intentional(n):
    global _BUDGET
    # tempo: ignore[global-mutation-unlocked] benign config rebind, test fixture
    _BUDGET = n


def record_guarded(name):
    with _lock:
        _registry[name] = 1


def _trim_locked():
    # *_locked convention: the caller holds the lock
    _registry.clear()


def nested_ab():
    with _lock:
        with _other_lock:
            return dict(_registry)


def nested_ba():
    with _other_lock:
        with _lock:  # EXPECT: lock-order
            return len(_registry)


def grab_unsafe():
    _lock.acquire()  # EXPECT: lock-bare-acquire
    n = len(_registry)
    _lock.release()
    return n


def grab_safe():
    # the sanctioned non-with form: the try body (and handlers) hold
    # the lock, so the mutation inside must NOT fire the global rule
    _lock.acquire()
    try:
        _registry["grab"] = 1
        return len(_registry)
    finally:
        _lock.release()


class _Blockish:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


staged_block = _Blockish()


def block_is_not_a_lock():
    # 'block' contains 'lock' as a substring but is NOT a lock: this
    # mutation must still fire, and the with must not join lock-order
    with staged_block:
        _registry["b"] = 1  # EXPECT: global-mutation-unlocked


def deferred_callback(register):
    with _lock:
        # the closure runs AFTER the with-block exits: lexical nesting
        # under the lock must not count as holding it
        def cb(k):
            _registry[k] = 1  # EXPECT: global-mutation-unlocked

        register(cb)
