"""Seeded violations in the continuous-profiling shapes: the sampler's
ring buffer + thread-tag registry (module containers under the sampler
lock) and the TimedLock wrapper's stats table (stats mutex inside the
wrapped lock) -- the lock pairs util/profiler.py uses, so the
concurrency rules provably cover the profiling plane. Also proves the
TimedLock/TimedRLock token teach-in: a `with`-held wrapper attribute
named like the wrapper class still counts as a lock."""

import threading
from collections import deque

_sampler_lock = threading.Lock()
_ring: deque = deque()
_thread_tags: dict[int, str] = {}
_stats_mutex = threading.Lock()
_wait_stats: dict[str, list] = {}


def push_sample(row):
    with _sampler_lock:
        _ring.append(row)
        while len(_ring) > 4096:
            _ring.popleft()


def push_sample_racy(row):
    _ring.append(row)  # EXPECT: global-mutation-unlocked


def tag_thread(tid, tag):
    with _sampler_lock:
        if tag:
            _thread_tags[tid] = tag
        else:
            _thread_tags.pop(tid, None)


def tag_thread_racy(tid, tag):
    _thread_tags[tid] = tag  # EXPECT: global-mutation-unlocked


class TimedLockish:
    """The wrapper shape: a wrapped inner lock plus a module stats
    table guarded by its own mutex."""

    def __init__(self, name):
        self.name = name
        self.inner_timedlock = threading.Lock()

    def note_wait(self):
        # sanctioned order: wrapped lock outer, stats mutex inner
        with self.inner_timedlock:
            with _stats_mutex:
                _wait_stats.setdefault(self.name, [0, 0.0])

    def stats_then_inner_racy(self):
        with _stats_mutex:
            with self.inner_timedlock:  # EXPECT: lock-order
                _wait_stats.pop(self.name, None)

    def probe_racy(self):
        self.inner_timedlock.acquire()  # EXPECT: lock-bare-acquire
        n = len(_wait_stats)
        self.inner_timedlock.release()
        return n

    def probe_safe(self):
        self.inner_timedlock.acquire()
        try:
            _wait_stats.clear()
        finally:
            self.inner_timedlock.release()
