"""Seeded violations in the cost-plane lock shapes: the CostLedger's
module singleton (configure/ledger rebind under a dedicated lock), the
cost model's capture condition variable, and the HBM watermark update --
the lock pairs util/costledger.py and util/costmodel.py use, so the
concurrency rules provably cover the measured-crossover store and the
device-memory ledger."""

import threading

_singleton_lock = threading.Lock()
_singleton = None
_capture_cv = threading.Condition()
_programs: dict[str, dict] = {}  # (op,bucket) -> analysis row
_hbm_peak = 0


def configure(path):
    # sanctioned: singleton repoint under its lock
    global _singleton
    with _singleton_lock:
        _singleton = {"path": path}
        return _singleton


def configure_racy(path):
    global _singleton
    _singleton = {"path": path}  # EXPECT: global-mutation-unlocked


def record_capture(key, row):
    # sanctioned order: capture cv outer, singleton lock inner (the
    # worker publishes a row, then touches the ledger artifact)
    with _capture_cv:
        _programs[key] = row
        with _singleton_lock:
            _capture_cv.notify_all()


def publish_then_capture_racy(key):
    with _singleton_lock:
        with _capture_cv:  # EXPECT: lock-order
            _programs.pop(key, None)


def watermark_scan_unsafe():
    _capture_cv.acquire()  # EXPECT: lock-bare-acquire
    n = len(_programs)
    _capture_cv.release()
    return n


def watermark_scan_safe():
    _capture_cv.acquire()
    try:
        _programs.clear()
    finally:
        _capture_cv.release()


def note_peak(total):
    global _hbm_peak
    with _capture_cv:
        if total > _hbm_peak:
            _hbm_peak = total
    return _hbm_peak
