"""Race/stress harness: N-thread hammers with invariant checks over the
shared mutable structures (VERDICT r3 item 10 -- the repo's analog of
the reference running every test under `go test -race`; round 3's
shared-zstd-context corruption proved the class of bug is real).

Each test runs a bounded burst (thousands of ops across 8 threads),
asserting structural invariants the whole way and re-raising any worker
exception; CPython's GIL doesn't serialize the C-extension sections
(zstd, numpy, native lib), which is exactly where the round-3 race
lived."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.util.testdata import make_traces

TENANT = "t-race"
N_THREADS = 8


def _hammer(fns, seconds=1.5):
    """Run callables round-robin across N_THREADS for a time budget,
    re-raising the first worker exception."""
    stop = time.monotonic() + seconds
    errors: list[BaseException] = []

    def run(i):
        k = 0
        try:
            while time.monotonic() < stop and not errors:
                fns[(i + k) % len(fns)]()
                k += 1
        except BaseException as e:  # pragma: no cover - failure path
            errors.append(e)
        return k

    with ThreadPoolExecutor(max_workers=N_THREADS) as ex:
        done = list(ex.map(run, range(N_THREADS)))
    if errors:
        raise errors[0]
    assert sum(done) > 100  # the hammer actually hammered


def test_blocklist_concurrent_update_read(tmp_path):
    """Pollers, ingesters (add), compactors (remove) and readers share
    the blocklist; list invariants must hold at every observation."""
    from tempo_tpu.block.meta import BlockMeta
    from tempo_tpu.db.blocklist import Blocklist

    bl = Blocklist()
    base = [BlockMeta.new(TENANT) for _ in range(50)]
    bl.update(TENANT, add=base)
    lock = threading.Lock()
    live_ids = {m.block_id: m for m in base}

    def reader():
        metas = bl.metas(TENANT)
        ids = [m.block_id for m in metas]
        assert len(ids) == len(set(ids)), "duplicate metas observed"

    def adder():
        m = BlockMeta.new(TENANT)
        with lock:
            live_ids[m.block_id] = m
        bl.update(TENANT, add=[m])

    def remover():
        with lock:
            if len(live_ids) <= 10:
                return
            bid, m = next(iter(live_ids.items()))
            del live_ids[bid]
        bl.update(TENANT, remove=[bid])

    def repoller():
        with lock:
            snapshot = list(live_ids.values())
        bl.apply_poll_results({TENANT: snapshot}, {TENANT: []})

    _hammer([reader, adder, remover, repoller, reader])
    # convergence: one final poll must reconcile exactly to live state
    with lock:
        snapshot = list(live_ids.values())
    bl.apply_poll_results({TENANT: snapshot}, {TENANT: []})
    assert {m.block_id for m in bl.metas(TENANT)} == set(
        m.block_id for m in snapshot
    )


def test_columnpack_cache_concurrent_readers(tmp_path):
    """The column ARRAY cache + chunk cache (round-4 code) under
    concurrent full reads, group reads and cache-pressure eviction:
    every read must return exactly the written bytes."""
    from tempo_tpu.block import build_block_from_traces
    from tempo_tpu.block.reader import BackendBlock

    be = MemBackend()
    meta = build_block_from_traces(be, TENANT, make_traces(300, seed=7, n_spans=12))
    blk = BackendBlock(be, meta)
    pack = blk.pack
    pack.CHUNK_CACHE_BYTES = 64 << 10  # force constant eviction churn
    names = [n for n in pack.names() if pack.has(n)]
    want = {n: pack.read(n).copy() for n in names}
    span_groups = list(range(pack.axes["span"].n_groups))

    def full_reader():
        n = names[np.random.randint(len(names))]
        got = pack.read(n)
        assert np.array_equal(got, want[n]), f"corrupt read of {n}"

    def group_reader():
        if not span_groups:
            return
        col = "span.name_id"
        g = int(np.random.randint(len(span_groups)))
        got = pack.read_groups(col, [g])
        off = pack.axes["span"].offsets
        assert np.array_equal(got, want[col][off[g]:off[g + 1]])

    def read_all_reader():
        out = pack.read_all()
        assert np.array_equal(out["trace.span_off"], want["trace.span_off"])

    _hammer([full_reader, group_reader, full_reader, read_all_reader])


def test_ring_kv_concurrent_membership():
    """Heartbeats, joins, leaves and readers hammer one ring KV; the
    token map must always reflect a consistent instance set (no ghost
    instances, tokens sorted/unique per observation)."""
    from tempo_tpu.ring.ring import InMemoryKV, Lifecycler, Ring

    kv = InMemoryKV()
    ring = Ring(kv, "r", replication_factor=2)
    cyclers = [Lifecycler(kv, "r", f"inst-{i}", addr=f"http://h{i}") for i in range(4)]
    for c in cyclers:
        c.join()
    extra_lock = threading.Lock()
    extra: list = []
    counter = [100]

    def heartbeat():
        cyclers[int(np.random.randint(len(cyclers)))].heartbeat()

    def join_leave():
        from tempo_tpu.ring.ring import Lifecycler as L

        with extra_lock:
            counter[0] += 1
            name = f"ghost-{counter[0]}"
        lc = L(kv, "r", name, addr="http://ghost")
        lc.heartbeat()
        lc.leave()

    def reader():
        descs = ring.healthy_instances()
        ids = [d.instance_id for d in descs]
        assert len(ids) == len(set(ids))
        if descs:
            rs = ring.get(12345)
            assert rs.instances and all(d.instance_id for d in rs.instances)
            assert len({d.instance_id for d in rs.instances}) == len(rs.instances)

    def shard_reader():
        descs = ring.healthy_instances()
        if descs:
            rs = ring.shuffle_shard(TENANT, 2)
            assert len({d.instance_id for d in rs}) == len(rs)

    _hammer([heartbeat, join_leave, reader, shard_reader])
    # all ghosts left: only the 4 long-lived instances remain healthy
    alive = {d.instance_id for d in ring.healthy_instances()}
    assert alive == {f"inst-{i}" for i in range(4)}


def test_gossip_store_concurrent_merge():
    """Concurrent local updates + remote-state merges on one gossip
    store must never resurrect removed instances or lose newer
    heartbeats (transport/gossip.py merge rules)."""
    from tempo_tpu.ring.ring import InstanceDesc, InstanceState
    from tempo_tpu.transport.gossip import GossipKV

    kv = GossipKV("127.0.0.1:0", seeds=[])
    try:
        t0 = time.time()

        def writer():
            i = int(np.random.randint(8))
            kv.update("ring", InstanceDesc(
                instance_id=f"w-{i}", addr="http://x", state=InstanceState.ACTIVE,
                tokens=[1, 2, 3], heartbeat_ts=time.time()))

        def merger():
            # a peer snapshot carrying older heartbeats must not clobber
            state = kv._snapshot()
            time.sleep(0.001)
            kv._merge(state)

        def remover_rejoiner():
            kv.remove("ring", "flapper")
            kv.update("ring", InstanceDesc(
                instance_id="flapper", addr="http://f",
                state=InstanceState.ACTIVE, tokens=[9], heartbeat_ts=time.time()))

        def reader():
            insts = kv.get_all("ring")
            for d in insts.values():
                assert d.heartbeat_ts >= t0 - 1

        _hammer([writer, merger, remover_rejoiner, reader], seconds=1.2)
        # no removed-but-present ghosts; recent writers all present
        insts = kv.get_all("ring")
        for i in range(8):
            assert f"w-{i}" in insts
    finally:
        kv.close()


def test_search_during_block_swap(tmp_path):
    """Concurrent searches while rewrite-block swaps the block out from
    under them (the CLI's documented exposure window): every search
    either sees the old or the new block, never an error or a torn
    result."""
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.cli.__main__ import main as cli
    from tempo_tpu.db.search import SearchRequest

    store = str(tmp_path / "store")
    db = TempoDB(
        TempoDBConfig(backend={"backend": "local", "path": store},
                      wal_path=str(tmp_path / "wal")),
        backend=LocalBackend(store),
    )
    traces = make_traces(80, seed=11, n_spans=6)
    db.write_block(TENANT, traces)
    db.poll_now()
    want = len(db.search(TENANT, SearchRequest(limit=1000)).traces)
    stop = threading.Event()
    errors: list = []

    def searcher():
        while not stop.is_set():
            try:
                db.poll_now()
                got = len(db.search(TENANT, SearchRequest(limit=1000)).traces)
                assert got == want, f"torn result: {got} != {want}"
            except Exception as e:
                errors.append(e)
                return

    threads = [threading.Thread(target=searcher) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for codec in ("gzip", "zstd", "zstd"):
            live = [m for m in db.blocklist.metas(TENANT)
                    if not m.compacted_at_unix]  # grace keeps old ones listed
            cli(["--backend.path", store, "rewrite-block", TENANT,
                 live[0].block_id, "--codec", codec])
            db.poll_now()
    finally:
        stop.set()
        for t in threads:
            t.join()
    if errors:
        raise errors[0]
