"""Jaeger thrift-binary ingest: hand-built Batch payloads (an
independent thrift binary ENCODER lives here, so the product decoder is
checked against the spec, not against itself) pushed over the collector
endpoint and read back as OTLP."""

import struct
import urllib.request

from tempo_tpu.wire.jaeger_thrift import decode_batch
from tempo_tpu.wire.model import SpanKind, StatusCode

_BOOL, _DOUBLE, _I32, _I64, _STRING, _STRUCT, _LIST = 2, 4, 8, 10, 11, 12, 15


def _fld(fid, ttype, payload):
    return bytes([ttype]) + struct.pack(">h", fid) + payload


def _s(v: str) -> bytes:
    b = v.encode()
    return struct.pack(">i", len(b)) + b


def _lst(ttype, items):
    return bytes([ttype]) + struct.pack(">i", len(items)) + b"".join(items)


def _tag(key, **kw):
    out = _fld(1, _STRING, _s(key))
    if "s" in kw:
        out += _fld(2, _I32, struct.pack(">i", 0)) + _fld(3, _STRING, _s(kw["s"]))
    elif "d" in kw:
        out += _fld(2, _I32, struct.pack(">i", 1)) + _fld(4, _DOUBLE, struct.pack(">d", kw["d"]))
    elif "b" in kw:
        out += _fld(2, _I32, struct.pack(">i", 2)) + _fld(5, _BOOL, bytes([int(kw["b"])]))
    elif "i" in kw:
        out += _fld(2, _I32, struct.pack(">i", 3)) + _fld(6, _I64, struct.pack(">q", kw["i"]))
    return out + b"\x00"


def _ref(ref_type, tid_hi, tid_lo, sid):
    out = _fld(1, _I32, struct.pack(">i", ref_type))
    out += _fld(2, _I64, struct.pack(">q", tid_lo))
    out += _fld(3, _I64, struct.pack(">q", tid_hi))
    out += _fld(4, _I64, struct.pack(">q", sid))
    return out + b"\x00"


def _log(ts_us, fields):
    out = _fld(1, _I64, struct.pack(">q", ts_us))
    out += _fld(2, _LIST, _lst(_STRUCT, list(fields)))
    return out + b"\x00"


def _span(tid_hi, tid_lo, sid, parent, name, start_us, dur_us, tags=(), refs=(), logs=()):
    out = _fld(1, _I64, struct.pack(">q", tid_lo))
    out += _fld(2, _I64, struct.pack(">q", tid_hi))
    out += _fld(3, _I64, struct.pack(">q", sid))
    out += _fld(4, _I64, struct.pack(">q", parent))
    out += _fld(5, _STRING, _s(name))
    out += _fld(7, _I32, struct.pack(">i", 1))
    out += _fld(8, _I64, struct.pack(">q", start_us))
    out += _fld(9, _I64, struct.pack(">q", dur_us))
    if refs:
        out += _fld(6, _LIST, _lst(_STRUCT, list(refs)))
    if tags:
        out += _fld(10, _LIST, _lst(_STRUCT, list(tags)))
    if logs:
        out += _fld(11, _LIST, _lst(_STRUCT, list(logs)))
    return out + b"\x00"


def _batch(service, spans, proc_tags=()):
    proc = _fld(1, _STRING, _s(service))
    if proc_tags:
        proc += _fld(2, _LIST, _lst(_STRUCT, list(proc_tags)))
    proc += b"\x00"
    return _fld(1, _STRUCT, proc) + _fld(2, _LIST, _lst(_STRUCT, spans)) + b"\x00"


def test_decode_batch():
    spans = [
        _span(0x1122, 0x3344, 0xAA, 0, "root", 1_700_000_000_000_000, 2_000,
              tags=[_tag("span.kind", s="server"), _tag("http.status_code", i=500),
                    _tag("error", b=True), _tag("ratio", d=0.5)]),
        _span(0x1122, 0x3344, 0xBB, 0xAA, "child", 1_700_000_000_001_000, 500),
    ]
    rs = decode_batch(_batch("shop", spans, proc_tags=[_tag("host", s="h1")]))
    assert rs.resource.attrs["service.name"] == "shop"
    assert rs.resource.attrs["host"] == "h1"
    sp = rs.scope_spans[0].spans
    assert len(sp) == 2
    root, child = sp
    assert root.trace_id.hex() == f"{0x1122:016x}{0x3344:016x}"
    assert root.span_id.hex() == f"{0xAA:016x}"
    assert root.name == "root" and root.kind == SpanKind.SERVER
    assert root.status_code == StatusCode.ERROR
    assert root.attrs["http.status_code"] == 500
    assert root.attrs["ratio"] == 0.5
    assert root.start_unix_nano == 1_700_000_000_000_000_000
    assert root.end_unix_nano - root.start_unix_nano == 2_000_000
    assert child.parent_span_id.hex() == f"{0xAA:016x}"
    assert "span.kind" not in root.attrs  # consumed into kind


def test_decode_logs_and_refs():
    """Jaeger logs map to events, FOLLOWS_FROM refs to links, CHILD_OF
    to the parent id (the standard Jaeger->OTLP translation)."""
    sp_bytes = _span(0x1, 0x2, 0x3, 0, "s", 1_000_000, 10,
                     refs=[_ref(0, 0x1, 0x2, 0x77), _ref(1, 0x9, 0x8, 0x66)],
                     logs=[_log(1_000_005, [_tag("event", s="boom")])])
    rs = decode_batch(_batch("svc", [sp_bytes]))
    (sp,) = rs.scope_spans[0].spans
    assert sp.parent_span_id.hex() == f"{0x77:016x}"  # CHILD_OF
    (ln,) = sp.links
    assert ln.span_id.hex() == f"{0x66:016x}"
    assert ln.trace_id.hex() == f"{0x9:016x}{0x8:016x}"
    (ev,) = sp.events
    assert ev.time_unix_nano == 1_000_005_000
    assert ev.attrs["event"] == "boom"


def test_jaeger_http_e2e(tmp_path):
    """POST thrift to the collector endpoint of -target=all; read the
    trace back by id over the OTLP query API."""
    import socket

    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.wire import otlp_json

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    cfg = AppConfig(storage_path=str(tmp_path / "store"), http_port=port,
                    compaction_cycle_s=9999,
                    ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                            flush_check_period_s=9999))
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    try:
        payload = _batch("pay", [
            _span(0x77, 0x88, 0x1, 0, "charge", 1_700_000_000_000_000, 1_000,
                  tags=[_tag("span.kind", s="client")]),
        ])
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/traces", data=payload,
            headers={"Content-Type": "application/vnd.apache.thrift.binary"})
        assert urllib.request.urlopen(req, timeout=10).status == 202
        tid_hex = f"{0x77:016x}{0x88:016x}"
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/api/traces/{tid_hex}",
                                    timeout=10) as r:
            got = otlp_json.loads(r.read())
        (res, _, sp), = list(got.all_spans())
        assert res.service_name == "pay" and sp.name == "charge"
        assert sp.kind == SpanKind.CLIENT
    finally:
        app.stop()


# ------------------------------------------------ gRPC PostSpans ingest


def _pb_kv(key, value):
    """Independent api_v2 KeyValue encoder (hand-built against
    model.proto, NOT the product encoder, so the decoder is checked
    against the spec)."""
    from tempo_tpu.wire import pbwire as w

    m = bytearray()
    w.write_string_field(m, 1, key)
    if isinstance(value, bool):
        w.write_varint_field(m, 2, 1)
        w.write_varint_field(m, 4, 1 if value else 0)
    elif isinstance(value, int):
        w.write_varint_field(m, 2, 2)
        w.write_varint_field(m, 5, value)
    elif isinstance(value, float):
        w.write_varint_field(m, 2, 3)
        w.write_double_field(m, 6, value)
    else:
        w.write_string_field(m, 3, str(value))
    return bytes(m)


def _pb_ts(field, buf, unix_nano):
    from tempo_tpu.wire import pbwire as w

    t = bytearray()
    w.write_varint_field(t, 1, unix_nano // 10**9)
    w.write_varint_field(t, 2, unix_nano % 10**9)
    w.write_message_field(buf, field, bytes(t))


def _post_spans_request(trace_id: bytes, n_spans: int, service: str) -> bytes:
    from tempo_tpu.wire import pbwire as w

    base = 1_700_000_000 * 10**9
    spans = []
    for i in range(n_spans):
        m = bytearray()
        w.write_bytes_field(m, 1, trace_id)
        w.write_bytes_field(m, 2, (i + 1).to_bytes(8, "big"))
        w.write_string_field(m, 3, f"op-{i}")
        if i > 0:  # CHILD_OF reference -> parent span
            ref = bytearray()
            w.write_bytes_field(ref, 1, trace_id)
            w.write_bytes_field(ref, 2, (1).to_bytes(8, "big"))
            w.write_message_field(m, 4, bytes(ref))
        _pb_ts(6, m, base + i * 1000)
        dur = bytearray()
        w.write_varint_field(dur, 2, 5_000_000)  # 5 ms
        w.write_message_field(m, 7, bytes(dur))
        w.write_message_field(m, 8, _pb_kv("span.kind", "server"))
        w.write_message_field(m, 8, _pb_kv("http.status_code", 200))
        spans.append(bytes(m))
    batch = bytearray()
    for s in spans:
        w.write_message_field(batch, 1, s)
    proc = bytearray()
    w.write_string_field(proc, 1, service)
    w.write_message_field(proc, 2, _pb_kv("jaeger.version", "go-2.30"))
    w.write_message_field(batch, 2, bytes(proc))
    req = bytearray()
    w.write_message_field(req, 1, bytes(batch))
    return bytes(req)


def test_jaeger_grpc_post_spans_e2e(tmp_path):
    """Push a Batch through the real gRPC collector endpoint
    (jaeger.api_v2.CollectorService/PostSpans) and read it back through
    the querier as OTLP, with references mapped to parent ids and the
    process to resource attrs."""
    import json

    import grpc

    from tempo_tpu.services.app import App, AppConfig, IngesterConfig

    cfg = AppConfig(
        target="all", http_port=0, jaeger_grpc_port=-1,
        storage_path=str(tmp_path / "store"),
        ingester=IngesterConfig(max_trace_idle_s=9999, max_block_age_s=9999,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    srv = app.serve_http(background=True)
    try:
        http_port = srv.server_address[1]
        tid = bytes(range(16))
        payload = _post_spans_request(tid, 3, "jaeger-svc")
        ch = grpc.insecure_channel(f"127.0.0.1:{cfg.jaeger_grpc_port}")
        resp = ch.unary_unary("/jaeger.api_v2.CollectorService/PostSpans")(payload)
        assert resp == b""
        got = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{http_port}/api/traces/{tid.hex()}", timeout=10).read())
        spans = [sp for rs in got["resourceSpans"]
                 for ss in rs["scopeSpans"] for sp in ss["spans"]]
        assert len(spans) == 3
        by_name = {sp["name"]: sp for sp in spans}
        assert by_name["op-1"]["parentSpanId"] == (1).to_bytes(8, "big").hex()
        res_attrs = {a["key"]: a["value"] for rs in got["resourceSpans"]
                     for a in rs["resource"]["attributes"]}
        assert res_attrs["service.name"]["stringValue"] == "jaeger-svc"
        assert res_attrs["jaeger.version"]["stringValue"] == "go-2.30"
        # malformed payload -> INVALID_ARGUMENT, server stays up
        import pytest as _pytest

        with _pytest.raises(grpc.RpcError) as ei:
            ch.unary_unary("/jaeger.api_v2.CollectorService/PostSpans")(b"\xff\xff\xff")
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        r2 = ch.unary_unary("/jaeger.api_v2.CollectorService/PostSpans")(payload)
        assert r2 == b""
    finally:
        srv.shutdown()
        app.stop()


# ------------------------------------------------ agent UDP (emitBatch)


def _cz(v: int) -> bytes:
    """Independent compact-protocol zigzag varint encoder."""
    u = (v << 1) ^ (v >> 63) if v < 0 else v << 1
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _cv(u: int) -> bytes:
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _cfield(prev_fid: int, fid: int, ctype: int) -> bytes:
    delta = fid - prev_fid
    if 0 < delta <= 15:
        return bytes([(delta << 4) | ctype])
    return bytes([ctype]) + _cz(fid)


def _cstr(s) -> bytes:
    b = s if isinstance(s, bytes) else s.encode()
    return _cv(len(b)) + b


def _compact_emit_batch(trace_id: bytes, n_spans: int, service: str) -> bytes:
    """Hand-built compact-protocol emitBatch datagram (agent.thrift),
    independent of the product decoder."""
    tid_hi = int.from_bytes(trace_id[:8], "big", signed=True)
    tid_lo = int.from_bytes(trace_id[8:], "big", signed=True)

    def tag(key, sval):  # string tag
        t = _cfield(0, 1, 8) + _cstr(key)      # key
        t += _cfield(1, 2, 5) + _cz(0)         # vType STRING
        t += _cfield(2, 3, 8) + _cstr(sval)    # vStr
        return t + b"\x00"

    def span(i):
        m = _cfield(0, 1, 6) + _cz(tid_lo)          # traceIdLow
        m += _cfield(1, 2, 6) + _cz(tid_hi)         # traceIdHigh
        m += _cfield(2, 3, 6) + _cz(i + 1)          # spanId
        m += _cfield(3, 4, 6) + _cz(1 if i else 0)  # parentSpanId
        m += _cfield(4, 5, 8) + _cstr(f"udp-op-{i}")
        m += _cfield(5, 7, 5) + _cz(1)              # flags (skips fid 6)
        m += _cfield(7, 8, 6) + _cz(1_700_000_000_000_000 + i)  # startTime us
        m += _cfield(8, 9, 6) + _cz(5_000)          # duration us
        m += _cfield(9, 10, 9) + bytes([(1 << 4) | 12]) + tag("k", "v")  # tags list
        return m + b"\x00"

    process = _cfield(0, 1, 8) + _cstr(service) + b"\x00"
    batch = _cfield(0, 1, 12) + process
    spans = b"".join(span(i) for i in range(n_spans))
    hdr = bytes([(n_spans << 4) | 12]) if n_spans < 15 else bytes([0xFC]) + _cv(n_spans)
    batch += _cfield(1, 2, 9) + hdr + spans + b"\x00"
    args = _cfield(0, 1, 12) + batch + b"\x00"
    # message: protocol id, (type ONEWAY=4)<<5 | version 1, seqid, name
    return bytes([0x82, (4 << 5) | 1]) + _cv(0) + _cstr("emitBatch") + args


def _binary_emit_batch(trace_id: bytes, n_spans: int, service: str) -> bytes:
    """Strict-binary framed emitBatch using the binary struct helpers."""
    tid_hi = trace_id[:8]
    tid_lo = trace_id[8:]

    def span(i):
        out = _fld(1, _I64, tid_lo)
        out += _fld(2, _I64, tid_hi)
        out += _fld(3, _I64, struct.pack(">q", i + 1))
        out += _fld(4, _I64, struct.pack(">q", 1 if i else 0))
        out += _fld(5, _STRING, _s(f"bin-op-{i}"))
        out += _fld(7, _I32, struct.pack(">i", 1))
        out += _fld(8, _I64, struct.pack(">q", 1_700_000_100_000_000 + i))
        out += _fld(9, _I64, struct.pack(">q", 7_000))
        return out + b"\x00"

    process = _fld(1, _STRING, _s(service)) + b"\x00"
    batch = _fld(1, _STRUCT, process)
    batch += _fld(2, _LIST, _lst(_STRUCT, [span(i) for i in range(n_spans)]))
    batch += b"\x00"
    args = _fld(1, _STRUCT, batch) + b"\x00"
    name = b"emitBatch"
    # strict binary: version 0x80010000 | type ONEWAY(4), name, seqid
    return (struct.pack(">I", 0x80010000 | 4) + struct.pack(">i", len(name))
            + name + struct.pack(">i", 0) + args)


def test_jaeger_agent_udp_both_protocols(tmp_path):
    """Client-SDK UDP datagrams (compact on 6831-role port, strict
    binary on its +1) land through the distributor and read back."""
    import json
    import socket
    import time

    from tempo_tpu.services.app import App, AppConfig, IngesterConfig

    cfg = AppConfig(
        target="all", http_port=0, jaeger_agent_port=-1,
        storage_path=str(tmp_path / "store"),
        ingester=IngesterConfig(max_trace_idle_s=9999, max_block_age_s=9999,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    srv = app.serve_http(background=True)
    try:
        http_port = srv.server_address[1]
        recv = app.jaeger_agent
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        tid_c = bytes(range(16))
        tid_b = bytes(range(16, 32))
        s.sendto(_compact_emit_batch(tid_c, 3, "udp-compact-svc"),
                 ("127.0.0.1", recv.compact_port))
        s.sendto(_binary_emit_batch(tid_b, 2, "udp-binary-svc"),
                 ("127.0.0.1", recv.binary_port))
        s.sendto(b"\x82\x21\x00\x09emitBatch garbage", ("127.0.0.1", recv.compact_port))

        deadline = time.time() + 10
        got_c = got_b = None
        while time.time() < deadline and (got_c is None or got_b is None):
            for tid, slot in ((tid_c, "c"), (tid_b, "b")):
                try:
                    r = json.loads(urllib.request.urlopen(
                        f"http://127.0.0.1:{http_port}/api/traces/{tid.hex()}",
                        timeout=5).read())
                except Exception:
                    continue
                if slot == "c":
                    got_c = r
                else:
                    got_b = r
            time.sleep(0.1)
        assert got_c is not None and got_b is not None
        n_c = sum(len(ss["spans"]) for rs in got_c["resourceSpans"]
                  for ss in rs["scopeSpans"])
        n_b = sum(len(ss["spans"]) for rs in got_b["resourceSpans"]
                  for ss in rs["scopeSpans"])
        assert n_c == 3 and n_b == 2
        svc_c = {a["key"]: a["value"].get("stringValue")
                 for rs in got_c["resourceSpans"]
                 for a in rs["resource"]["attributes"]}
        assert svc_c["service.name"] == "udp-compact-svc"
        assert recv.failures >= 1  # the garbage datagram counted, nothing died
    finally:
        srv.shutdown()
        app.stop()
