"""Gossip ring KV: multi-host membership convergence without shared
storage (reference: memberlist anti-entropy sync)."""

import time

from tempo_tpu.ring.ring import Lifecycler, Ring
from tempo_tpu.transport.gossip import GossipKV


def _converge(check, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if check():
            return True
        time.sleep(0.1)
    return False


def test_gossip_convergence_and_tombstones():
    kvs = []
    try:
        n1 = GossipKV("127.0.0.1:0", interval_s=0.2)
        n2 = GossipKV("127.0.0.1:0", seeds=[n1.addr], interval_s=0.2)
        n3 = GossipKV("127.0.0.1:0", seeds=[n1.addr], interval_s=0.2)
        kvs = [n1, n2, n3]

        # one instance joins on each node; every node must see all three
        for i, kv in enumerate(kvs):
            Lifecycler(kv, "ring", f"inst-{i}", addr=f"http://h{i}").join()
        assert _converge(lambda: all(len(kv.get_all("ring")) == 3 for kv in kvs)), \
            [sorted(kv.get_all("ring")) for kv in kvs]

        # n3 discovered n2 transitively through the shared seed
        ids = {sorted(kv.get_all("ring"))[1] for kv in kvs}
        assert ids == {"inst-1"}

        # removal tombstones propagate (and beat the stale descriptor)
        n2.remove("ring", "inst-1")
        assert _converge(lambda: all(len(kv.get_all("ring")) == 2 for kv in kvs)), \
            [sorted(kv.get_all("ring")) for kv in kvs]

        # rings over gossip KVs behave like any other KV
        ring = Ring(n3, "ring")
        assert {d.instance_id for d in ring.healthy_instances()} == {"inst-0", "inst-2"}
    finally:
        for kv in kvs:
            kv.close()


def test_gossip_heartbeats_win_by_recency():
    n1 = GossipKV("127.0.0.1:0", interval_s=0.2)
    n2 = GossipKV("127.0.0.1:0", seeds=[n1.addr], interval_s=0.2)
    try:
        lc = Lifecycler(n1, "r", "a", addr="http://a")
        lc.join()
        assert _converge(lambda: "a" in n2.get_all("r"))
        ts1 = n2.get_all("r")["a"].heartbeat_ts
        time.sleep(0.3)
        lc.desc.heartbeat_ts = time.time()
        n1.update("r", lc.desc)
        assert _converge(lambda: n2.get_all("r")["a"].heartbeat_ts > ts1)
    finally:
        n1.close()
        n2.close()
