"""TraceQL metrics engine: parser/validate vectors, step alignment and
by() grouping against a hand-computed fixture, device-vs-host engine
equality, frontend time-sharding, and an HTTP round trip through
/api/metrics/query_range on the single-binary app."""

import json
import socket
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from tempo_tpu.db.metrics_exec import (
    MetricsRequest,
    MetricsResponse,
    align_params,
    metrics_block,
    metrics_query_range_blocks,
    parse_metrics_query,
    response_from_dict,
    response_to_dict,
    series_values,
    to_prometheus,
)
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.traceql.ast import MetricsQuery, ParseError
from tempo_tpu.traceql.parser import parse
from tempo_tpu.wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

BASE_NS = 1_700_000_000_000_000_000
BASE_S = BASE_NS // 1_000_000_000


# ------------------------------------------------------- parser vectors

PARSE_OK = [
    '{ span.foo = "bar" } | rate()',
    '{ span.foo = "bar" } | rate() by(resource.service.name)',
    '{ true } | count_over_time() by(name, status)',
    '{ duration > 10ms } | min_over_time(duration)',
    '{ true } | max_over_time(span.http.status_code) by(kind)',
    '{ true } | avg_over_time(duration) by(.foo, resource.service.name)',
    '{ true } | sum_over_time(.weight)',
    '{ .a = 1 } | count() = 1 | rate()',  # scalar stage ahead of metrics
]

PARSE_FAIL = [
    'rate()',  # no spanset ahead
    '{ true } | rate() | { true }',  # not terminal
    '{ true } | rate(duration)',  # rate takes no argument
    '{ true } | count_over_time(name)',
    '{ true } | avg_over_time()',  # needs an argument
    '{ true } | sum_over_time(name)',  # non-numeric argument
    '{ true } | avg_over_time(3)',  # must reference span data
    '{ true } | rate() by()',  # empty by
    '{ true } | rate() by(3)',  # by must reference span data
    '{ true } && ({ true } | rate())',  # metrics pipelines do not combine
]


def test_parse_metrics_vectors():
    for src in PARSE_OK:
        q = parse(src)
        assert isinstance(q, MetricsQuery), src
        assert q.agg.fn in ("rate", "count_over_time", "min_over_time",
                            "max_over_time", "avg_over_time", "sum_over_time")
    for src in PARSE_FAIL:
        with pytest.raises(ParseError):
            parse(src)


def test_metrics_stage_shapes():
    q = parse('{ true } | avg_over_time(duration) by(name, resource.service.name)')
    assert q.agg.fn == "avg_over_time"
    assert q.agg.field is not None
    assert len(q.agg.by) == 2
    q2 = parse('{ true } | rate()')
    assert q2.agg.field is None and q2.agg.by == ()


def test_metrics_rejected_on_search_paths():
    """Metrics stages are only valid on the metrics endpoints: the
    search planner refuses them, and parse_metrics_query refuses the
    inverse (a plain spanset on the metrics endpoint)."""
    from tempo_tpu.block.dictionary import Dictionary
    from tempo_tpu.traceql.plan import plan_search_request

    d = Dictionary(["bar", "foo"])
    with pytest.raises(ParseError):
        plan_search_request(d, {}, query='{ .foo = "bar" } | rate()')
    with pytest.raises(ParseError):
        parse_metrics_query('{ .foo = "bar" }')
    # a plain search on the same dictionary still plans fine
    plan_search_request(d, {}, query='{ .foo = "bar" }')


def test_align_params():
    req = align_params("{ true } | rate()", 103, 158, 10)
    assert req.start_ms == 100_000 and req.end_ms == 160_000
    assert req.step_ms == 10_000 and req.n_buckets == 6
    with pytest.raises(ValueError):
        align_params("{ true } | rate()", 0, 10_000_000, 1)  # too many buckets


# ------------------------------------------------------ fixture blocks


def _trace(tid_byte: int, svc: str, spans):
    """spans: list of (name, start_off_s, dur_s, attrs)."""
    tid = bytes([0] * 15 + [tid_byte])
    t = Trace()
    rs = ResourceSpans(resource=Resource(attrs={"service.name": svc}))
    ss = ScopeSpans(scope=Scope(name="test", version="1"))
    for name, off_s, dur_s, attrs in spans:
        start = BASE_NS + int(off_s * 1e9)
        ss.spans.append(Span(
            trace_id=tid,
            span_id=bytes([tid_byte] * 7 + [len(ss.spans)]),
            name=name,
            kind=2,
            start_unix_nano=start,
            end_unix_nano=start + int(dur_s * 1e9),
            attrs=dict(attrs),
        ))
    rs.scope_spans.append(ss)
    t.resource_spans.append(rs)
    return tid, t


@pytest.fixture(scope="module")
def fixture_db(tmp_path_factory):
    """Two blocks with hand-placed span start times:

    svc 'a' (span.foo = "bar"): offsets 1, 11, 12, 35 s  -> [1, 2, 0, 1]
    svc 'b' (span.foo = "bar"): offsets 5, 25 s          -> [1, 0, 1, 0]
    svc 'a' (foo = "other"):    offset 2 s               -> filtered out
    svc 'a' (foo = "bar"):      offset 45 s              -> out of range
    over start=BASE_S, end=BASE_S+40, step=10s (4 buckets).
    """
    root = tmp_path_factory.mktemp("metrics-db")
    db = TempoDB(TempoDBConfig(
        backend={"backend": "local", "path": str(root / "store")},
        wal_path=str(root / "wal"),
    ))
    batch1 = [
        _trace(1, "a", [("op1", 1, 0.5, {"foo": "bar", "w": 2.0}),
                        ("op2", 11, 1.5, {"foo": "bar", "w": 4.0})]),
        _trace(2, "b", [("op1", 5, 2.0, {"foo": "bar", "w": 10.0})]),
    ]
    batch2 = [
        _trace(3, "a", [("op1", 12, 2.5, {"foo": "bar", "w": 6.0}),
                        ("op3", 2, 1.0, {"foo": "other"}),
                        ("op1", 45, 1.0, {"foo": "bar", "w": 99.0})]),
        _trace(4, "a", [("op2", 35, 3.0, {"foo": "bar", "w": 8.0})]),
        _trace(5, "b", [("op2", 25, 4.0, {"foo": "bar", "w": 20.0})]),
    ]
    batch1.sort(key=lambda p: p[0])
    batch2.sort(key=lambda p: p[0])
    m1 = db.write_block("t", batch1)
    m2 = db.write_block("t", batch2)
    yield db, [m1, m2]
    db.close()


RATE_Q = '{ span.foo = "bar" } | rate() by(resource.service.name)'


def _req(query, step_s=10, start=BASE_S, end=BASE_S + 40):
    return align_params(query, start, end, step_s)


def test_rate_by_hand_computed(fixture_db):
    db, metas = fixture_db
    req = _req(RATE_Q)
    blocks = [db.open_block(m) for m in metas]
    resp = metrics_query_range_blocks(blocks, req)
    assert resp.label_names == ("resource.service.name",)
    assert set(resp.series) == {("a",), ("b",)}
    assert resp.series[("a",)]["count"].tolist() == [1, 2, 0, 1]
    assert resp.series[("b",)]["count"].tolist() == [1, 0, 1, 0]
    vals = series_values(resp, resp.series[("a",)])
    assert vals.tolist() == [0.1, 0.2, 0.0, 0.1]  # count / 10 s step
    prom = to_prometheus(resp)
    assert prom["status"] == "success"
    assert prom["data"]["resultType"] == "matrix"
    a = next(r for r in prom["data"]["result"]
             if r["metric"] == {"resource.service.name": "a"})
    assert a["values"][0] == [float(BASE_S), "0.1"]


def test_value_folds_hand_computed(fixture_db):
    db, metas = fixture_db
    blocks = [db.open_block(m) for m in metas]
    # avg of span attr w per bucket across both services
    resp = metrics_query_range_blocks(
        blocks, _req('{ span.foo = "bar" } | avg_over_time(.w)'))
    vals = series_values(resp, resp.series[()])
    # bucket 0: w=2,10 -> 6; bucket 1: w=4,6 -> 5; bucket 2: w=20; bucket 3: w=8
    assert vals.tolist() == [6.0, 5.0, 20.0, 8.0]
    # min/max over duration in seconds
    resp2 = metrics_query_range_blocks(
        blocks, _req('{ span.foo = "bar" } | max_over_time(duration)'))
    vals2 = series_values(resp2, resp2.series[()])
    assert vals2.tolist() == [2.0, 2.5, 4.0, 3.0]
    resp3 = metrics_query_range_blocks(
        blocks, _req('{ span.foo = "bar" } | min_over_time(duration) by(resource.service.name)'))
    assert np.allclose(series_values(resp3, resp3.series[("a",)]),
                       [0.5, 1.5, np.nan, 3.0], equal_nan=True)


def test_step_realignment_independent_of_request_jitter(fixture_db):
    """The grid depends only on step, not the request instant: shifting
    start/end inside one step changes nothing but edge buckets."""
    db, metas = fixture_db
    blocks = [db.open_block(m) for m in metas]
    r1 = metrics_query_range_blocks(blocks, _req(RATE_Q, start=BASE_S + 3, end=BASE_S + 37))
    # floors to BASE_S, ceils to BASE_S+40: identical to the aligned axis
    assert r1.start_ms == BASE_S * 1000 and r1.n_buckets == 4
    assert r1.series[("a",)]["count"].tolist() == [1, 2, 0, 1]


ENGINE_QUERIES = [
    RATE_Q,
    '{ span.foo = "bar" } | count_over_time() by(name)',
    '{ true } | rate() by(kind)',
    '{ true } | avg_over_time(duration) by(resource.service.name)',
    '{ true } | sum_over_time(.w)',
    '{ span.foo = "bar" } | max_over_time(.w) by(resource.service.name)',
    # float-valued by(): every engine must route exact (a columnar drop
    # would disagree with the exact engine's float labels)
    '{ span.foo = "bar" } | rate() by(.w)',
]


def test_device_host_exact_engine_equality(fixture_db):
    """The three engines must agree series-for-series on the same block
    set (counts exactly; float folds to f32 tolerance)."""
    db, metas = fixture_db
    blocks = [db.open_block(m) for m in metas]
    for query in ENGINE_QUERIES:
        q = parse_metrics_query(query)
        req = _req(query)
        out = {}
        for mode in ("host", "device", "exact"):
            resp = MetricsResponse(fn=q.agg.fn, start_ms=req.start_ms,
                                   step_ms=req.step_ms, n_buckets=req.n_buckets)
            for b in blocks:
                metrics_block(b, q, req, resp, mode=mode)
            out[mode] = resp
        keys = set(out["host"].series)
        for mode in ("device", "exact"):
            assert set(out[mode].series) == keys, (query, mode)
            for k in keys:
                for f, arr in out["host"].series[k].items():
                    assert np.allclose(arr, out[mode].series[k][f],
                                       rtol=1e-5, equal_nan=True), (query, mode, k, f)


def test_exact_fallback_on_lossy_and_pipeline(fixture_db):
    """needs_verify plans (float compares) and pipelines with
    intermediate stages route through the exact engine and still
    produce correct, mergeable series."""
    db, metas = fixture_db
    blocks = [db.open_block(m) for m in metas]
    resp = metrics_query_range_blocks(blocks, _req('{ .w > 5.0 } | rate()'))
    # w in {6, 8, 10, 20, 99(out of range)} -> buckets [1, 1, 1, 1]
    assert resp.series[()]["count"].tolist() == [1, 1, 1, 1]
    resp2 = metrics_query_range_blocks(
        blocks, _req('{ span.foo = "bar" } | count() = 1 | rate()'))
    # traces with exactly one matching span: trace2 (5s), trace4 (35s),
    # trace5 (25s), trace3 counts 12s+45s=2 spans -> excluded
    assert resp2.series[()]["count"].tolist() == [1, 0, 1, 1]


def test_prometheus_value_precision():
    from tempo_tpu.db.metrics_exec import _fmt_value

    assert _fmt_value(0.1) == "0.1"
    assert _fmt_value(1234567.0) == "1234567"  # no %g 6-digit truncation
    assert float(_fmt_value(1 / 3)) == 1 / 3  # round-trips exactly


def test_wire_roundtrip(fixture_db):
    db, metas = fixture_db
    blocks = [db.open_block(m) for m in metas]
    resp = metrics_query_range_blocks(blocks, _req(RATE_Q))
    back = response_from_dict(response_to_dict(resp))
    assert set(back.series) == set(resp.series)
    for k in resp.series:
        for f, arr in resp.series[k].items():
            assert (back.series[k][f] == arr).all()


def test_mesh_path_matches_per_block(fixture_db):
    """The stacked shard_map fold (psum combine, globalized group keys)
    equals the per-block engines on the virtual 8-device mesh."""
    db, metas = fixture_db
    blocks = [db.open_block(m) for m in metas]
    req = _req(RATE_Q)
    plain = metrics_query_range_blocks(blocks, req)
    meshed = metrics_query_range_blocks(blocks, req, mesh=db.mesh)
    assert set(meshed.series) == set(plain.series)
    for k in plain.series:
        assert (meshed.series[k]["count"] == plain.series[k]["count"]).all()


def test_frontend_shards_and_merges(fixture_db):
    """The frontend splits the range into >= 2 step-aligned jobs and the
    merged output equals the unsharded result."""
    from tempo_tpu.services.frontend import Frontend
    from tempo_tpu.services.querier import Querier

    db, metas = fixture_db
    fe = Frontend(Querier(db, None, lambda a: None), n_workers=2)
    fe.METRICS_BUCKETS_PER_JOB = 2  # force several shards at 4 buckets
    try:
        req = _req(RATE_Q)
        sharded = fe.metrics_query_range("t", req)
        direct = db.metrics_query_range("t", req)
        assert fe.stats_jobs_local >= 2
        assert set(sharded.series) == set(direct.series)
        for k in direct.series:
            assert (sharded.series[k]["count"] == direct.series[k]["count"]).all()
    finally:
        fe.stop()


# ----------------------------------------------------------- HTTP e2e


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_http_query_range_round_trip(tmp_path):
    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.wire import otlp_json

    cfg = AppConfig(
        storage_path=str(tmp_path), http_port=_free_port(),
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    base = f"http://127.0.0.1:{cfg.http_port}"
    try:
        for tid_b, svc, spans in [
            (1, "web", [("h", 1, 0.5, {"foo": "bar"}), ("h", 11, 0.5, {"foo": "bar"})]),
            (2, "db", [("q", 5, 0.5, {"foo": "bar"})]),
        ]:
            _, tr = _trace(tid_b, svc, spans)
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/traces", data=otlp_json.dumps(tr).encode(),
                headers={"Content-Type": "application/json"})).read()
        app.ingester.flush_all()
        app.db.poll_now()

        qs = urllib.parse.urlencode({
            "q": RATE_Q, "start": BASE_S, "end": BASE_S + 20, "step": 10})
        out = json.loads(urllib.request.urlopen(
            f"{base}/api/metrics/query_range?{qs}").read())
        assert out["status"] == "success"
        assert out["data"]["resultType"] == "matrix"
        by_label = {r["metric"]["resource.service.name"]: r["values"]
                    for r in out["data"]["result"]}
        assert by_label["web"] == [[float(BASE_S), "0.1"],
                                   [float(BASE_S + 10), "0.1"]]
        assert by_label["db"][0] == [float(BASE_S), "0.1"]

        # non-metrics query on the metrics endpoint -> 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                base + "/api/metrics/query_range?q="
                + urllib.parse.quote("{ true }"))
        assert ei.value.code == 400
        # metrics query on the search endpoint -> 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                base + "/api/search?q="
                + urllib.parse.quote("{ true } | rate()"))
        assert ei.value.code == 400
    finally:
        app.stop()
