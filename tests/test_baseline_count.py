"""Tier-1 pass-count regression guard (CI half).

Two floors in TIER1_BASELINE.json keep the suite honest:

  * test_defs_floor -- asserted HERE, statically: the number of
    `def test_*` functions across tests/ must never shrink below the
    committed floor. A test file accidentally deleted, renamed out of
    collection, or emptied by a refactor fails THIS test inside the
    very run that lost the coverage -- a green run can no longer mean
    "fewer tests ran".
  * dots_passed_floor -- asserted by scripts/verify_tier1.sh, which
    runs the ROADMAP tier-1 command and compares its DOTS_PASSED
    against the floor (a test obviously can't count the passes of the
    run it is part of).

Raise the floors when adding tests; lowering them is a reviewed act.
"""

import json
import re
from pathlib import Path

TESTS = Path(__file__).resolve().parent
REPO = TESTS.parent

_DEF_RE = re.compile(r"^\s*def (test_\w+)\(", re.MULTILINE)


def _baseline() -> dict:
    return json.loads((REPO / "TIER1_BASELINE.json").read_text())


def test_baseline_file_is_valid():
    b = _baseline()
    assert isinstance(b["dots_passed_floor"], int)
    assert isinstance(b["test_defs_floor"], int)
    # the dots floor tracks the committed tier-1 state; it only ratchets
    assert b["dots_passed_floor"] >= 506


def test_test_function_count_never_shrinks():
    defs = []
    for p in sorted(TESTS.glob("test_*.py")):
        defs.extend((p.name, name) for name in _DEF_RE.findall(p.read_text()))
    # distinct (file, name): a duplicated name in one file shadows its
    # twin at collection time and silently halves that file's coverage
    assert len(set(defs)) == len(defs), "duplicate test names shadow tests"
    floor = _baseline()["test_defs_floor"]
    assert len(defs) >= floor, (
        f"tests/ defines {len(defs)} test functions, below the committed "
        f"floor {floor} (TIER1_BASELINE.json): a test file was lost or "
        f"emptied. If removal is intentional, lower the floor explicitly.")


def test_verify_script_exists_and_references_floor():
    script = (REPO / "scripts" / "verify_tier1.sh").read_text()
    assert "TIER1_BASELINE.json" in script
    assert "DOTS_PASSED" in script
