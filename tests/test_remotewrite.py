"""Generator remote-write: snappy(protobuf WriteRequest) verified by an
INDEPENDENT decoder in the test (snappy block format + prompb reader),
so the hand-rolled encoders are checked against the specs."""

import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

from tempo_tpu.services.generator import MetricsGenerator
from tempo_tpu.services.overrides import Overrides
from tempo_tpu.services.remotewrite import (
    RemoteWriter,
    encode_write_request,
    parse_exposition,
    snappy_block_encode,
)
from tempo_tpu.util.testdata import make_traces
from tempo_tpu.wire import pbwire as w


def snappy_decode(data: bytes) -> bytes:
    """Spec decoder: varint length + literal/copy tags (tests only)."""
    n, pos = w.read_varint(data, 0)
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        t = tag & 3
        if t == 0:  # literal
            ln = tag >> 2
            if ln < 60:
                ln += 1
            else:
                nbytes = ln - 59
                ln = int.from_bytes(data[pos : pos + nbytes], "little") + 1
                pos += nbytes
            out += data[pos : pos + ln]
            pos += ln
        else:  # copy
            if t == 1:
                ln = ((tag >> 2) & 7) + 4
                off = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif t == 2:
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[pos : pos + 2], "little")
                pos += 2
            else:
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[pos : pos + 4], "little")
                pos += 4
            for _ in range(ln):
                out.append(out[-off])
    assert len(out) == n
    return bytes(out)


def decode_write_request(data: bytes):
    series = []
    pos = 0
    while pos < len(data):
        key, pos = w.read_varint(data, pos)
        assert key >> 3 == 1 and key & 7 == 2
        ln, pos = w.read_varint(data, pos)
        ts_msg = data[pos : pos + ln]
        pos += ln
        labels, samples = {}, []
        p = 0
        while p < len(ts_msg):
            k, p = w.read_varint(ts_msg, p)
            ln2, p = w.read_varint(ts_msg, p)
            body = ts_msg[p : p + ln2]
            p += ln2
            if k >> 3 == 1:  # label
                q = 0
                name = value = ""
                while q < len(body):
                    lk, q = w.read_varint(body, q)
                    lln, q = w.read_varint(body, q)
                    s = body[q : q + lln].decode()
                    q += lln
                    if lk >> 3 == 1:
                        name = s
                    else:
                        value = s
                labels[name] = value
            else:  # sample
                import struct
                val = struct.unpack("<d", body[1:9])[0]
                samples.append(val)
        series.append((labels, samples))
    return series


def test_snappy_block_roundtrip():
    for blob in (b"", b"x", b"hello" * 100, bytes(range(256)) * 700):
        assert snappy_decode(snappy_block_encode(blob)) == blob


def test_remote_write_e2e():
    received = []

    class H(BaseHTTPRequestHandler):
        def do_POST(self):
            ln = int(self.headers["Content-Length"])
            body = self.rfile.read(ln)
            assert self.headers["Content-Encoding"] == "snappy"
            received.append(decode_write_request(snappy_decode(body)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        gen = MetricsGenerator(Overrides())
        traces = make_traces(12, seed=9, n_spans=4)
        gen.push("t1", [t for _, t in traces])
        rw = RemoteWriter(gen, f"http://127.0.0.1:{srv.server_address[1]}/api/v1/push")
        assert rw.push_once()
        assert rw.pushes == 1
        (series,) = received
        names = {lab["__name__"] for lab, _ in series}
        assert "traces_spanmetrics_calls_total" in names
        assert "traces_spanmetrics_latency_bucket" in names
        # counts survive the trip
        total = sum(s[0] for lab, s in series
                    if lab["__name__"] == "traces_spanmetrics_calls_total")
        assert total == sum(t.span_count() for _, t in traces)
        # bucket labels include le
        assert any("le" in lab for lab, _ in series)
    finally:
        srv.shutdown()


def test_exemplars_in_exposition():
    gen = MetricsGenerator(Overrides())
    traces = make_traces(5, seed=4, n_spans=3)
    gen.push("t1", [t for _, t in traces])
    text = "\n".join(gen.metrics_text())
    assert '# {trace_id="' in text  # OpenMetrics exemplar attached
    # exemplars don't break remote-write parsing
    series = parse_exposition(text.splitlines())
    assert any(lab["__name__"] == "traces_spanmetrics_latency_bucket"
               for lab, _ in series)


def test_parse_exposition_hostile_labels():
    """Label values with braces, spaces and ' # ' parse correctly; the
    exemplar suffix is dropped without truncating series."""
    lines = [
        'm_total{span_name="GET # users",svc="a}b"} 3',
        'bucket{le="0.5",span_name="x y"} 7 # {trace_id="ab"} 0.2',
        "plain_total 9",
        "# EOF",
    ]
    series = parse_exposition(lines)
    assert (dict(series[0][0]), series[0][1]) == (
        {"__name__": "m_total", "span_name": "GET # users", "svc": "a}b"}, 3.0)
    assert series[1][0]["le"] == "0.5" and series[1][1] == 7.0
    assert series[2] == ({"__name__": "plain_total"}, 9.0)
    assert len(series) == 3
