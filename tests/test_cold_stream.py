"""Cold-read streaming pipeline (ops/stream + colio plan_fetch).

The load-bearing guarantees, each with its own test:
  * differential: pipelined cold search (TEMPO_STREAM_PREFETCH_DEPTH >
    0, HostPrefetch running fetch/decompress ahead) returns
    bit-identical results and ordering to the serial path (depth 0);
  * the staged-upload pipeline (stream_staged) yields identical device
    arrays pipelined vs serial, strictly in unit order;
  * cancellation: a mid-stream error cancels in-flight units, leaks no
    futures and returns every admitted byte to the gate; the executor
    stays healthy for the next run;
  * byte budget: many tiny units under a small TEMPO_STREAM_MEM_BUDGET
    all complete, in order, with the admission high-water bounded;
  * compaction passthrough: an output inheriting one whole input block
    copies its compressed objects verbatim (byte-equal data object, no
    recompress) and stays logically identical to a full rewrite.
"""

from __future__ import annotations

import shutil
import time

import numpy as np
import pytest

import tempo_tpu.ops.stream as stream
from tempo_tpu.backend.local import LocalBackend
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.db.search import SearchRequest
from tempo_tpu.util.kerneltel import TEL
from tempo_tpu.util.testdata import make_traces

TENANT = "t1"


def _mk_backend(tmp_path, n_blocks=4, n_traces=40, seed0=20):
    backend = LocalBackend(str(tmp_path / "store"))
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal0")), backend=backend)
    for b in range(n_blocks):
        db.write_block(TENANT, make_traces(n_traces, seed=seed0 + b, n_spans=6))
    db.close()
    return backend


def _cold_blocks(backend, tmp_path, tag="x"):
    """Fresh BackendBlock readers (empty caches) over every block."""
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / f"wal_{tag}")),
                 backend=backend)
    db.poll_now()
    metas = db.blocklist.metas(TENANT)
    blocks = [db.open_block(m) for m in metas]
    return db, blocks


# ---------------------------------------------------------- differential
def test_cold_search_pipelined_matches_serial(tmp_path, monkeypatch):
    """The whole cold path through TempoDB.search: pipelined (prefetch
    running ahead of the engines) vs serial (depth 0) must be
    bit-identical in results AND ordering, query by query."""
    backend = _mk_backend(tmp_path)
    reqs = [
        SearchRequest(tags={"service.name": "db"}, limit=100),
        SearchRequest(min_duration_ms=1, limit=1000),
        SearchRequest(tags={"http.method": "GET"}, limit=30),
    ]

    def run_cold(depth: int, tag: str):
        monkeypatch.setenv("TEMPO_STREAM_PREFETCH_DEPTH", str(depth))
        out = []
        for qi, req in enumerate(reqs):
            # fresh TempoDB per query: every byte comes off disk
            db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / f"w{tag}{qi}")),
                         backend=backend)
            db.poll_now()
            resp = db.search(TENANT, req)
            out.append((
                [(r.trace_id, r.start_time_unix_nano, r.duration_ms,
                  r.root_service_name, r.root_trace_name) for r in resp.traces],
                resp.inspected_spans,
            ))
            db.close()
        return out

    serial = run_cold(0, "s")
    piped = run_cold(3, "p")
    assert piped == serial
    assert any(traces for traces, _ in serial), "queries must match something"


def test_stream_staged_pipelined_matches_serial(tmp_path):
    """stream_staged over row-group chunk units: the pipeline reorders
    WORK, never data -- staged device arrays and yield order identical
    to the inline serial path."""
    backend = _mk_backend(tmp_path, n_blocks=1, n_traces=120)

    def staged_cols(depth: int, tag: str):
        db, (blk,) = _cold_blocks(backend, tmp_path, tag)
        needed = sorted(n for n in blk.pack.names()
                        if n.startswith(("span.", "trace.")))[:6]
        span_ax = blk.pack.axes["span"]
        groups = list(range(span_ax.n_groups)) or [0]
        units = [stream.StreamUnit(blk, needed, [g], upload=True)
                 for g in groups]
        out = []
        for u, staged in stream.stream_staged(units, depth=depth):
            assert staged is not None
            out.append((u.groups,
                        {k: np.asarray(v) for k, v in staged.cols.items()}))
        db.close()
        return out

    serial = staged_cols(0, "a")
    piped = staged_cols(3, "b")
    assert len(serial) == len(piped) >= 1
    for (gs, cs), (gp, cp) in zip(serial, piped):
        assert gs == gp
        assert sorted(cs) == sorted(cp)
        for k in cs:
            assert np.array_equal(cs[k], cp[k]), k


# ---------------------------------------------------------- cancellation
def test_midstream_error_cancels_and_drains(tmp_path, monkeypatch):
    """A unit that dies mid-pipeline surfaces its error to the consumer,
    cancels everything in flight, returns every admitted byte to the
    gate and leaves the shared executor healthy."""
    backend = _mk_backend(tmp_path, n_blocks=6, n_traces=20)
    db, blocks = _cold_blocks(backend, tmp_path, "err")
    names = [n for n in blocks[0].pack.names() if n.startswith("span.")][:4]

    boom = blocks[2].pack
    monkeypatch.setattr(
        boom, "fetch_ranges",
        lambda cf: (_ for _ in ()).throw(OSError("injected: fetch died")))

    units = [stream.StreamUnit(b, list(names), None, upload=False)
             for b in blocks]
    it = stream.stream_staged(units, depth=3)
    got = []
    with pytest.raises(OSError, match="injected"):
        for u, res in it:
            got.append(u)
    assert len(got) == 2  # units 0 and 1 yielded before the error
    # the generator's finally drained every future and released the gate
    assert stream._GATE.inflight_bytes() == 0

    # early close (consumer abandons the stream) drains the same way
    db2, blocks2 = _cold_blocks(backend, tmp_path, "close")
    units2 = [stream.StreamUnit(b, list(names), None, upload=False)
              for b in blocks2]
    it2 = stream.stream_staged(units2, depth=3)
    next(it2)
    it2.close()
    assert stream._GATE.inflight_bytes() == 0

    # and the pool still serves a fresh, healthy run end to end
    db3, blocks3 = _cold_blocks(backend, tmp_path, "ok")
    units3 = [stream.StreamUnit(b, list(names), None, upload=False)
              for b in blocks3]
    outs = list(stream.stream_staged(units3, depth=3))
    assert len(outs) == len(blocks3) and all(r for _, r in outs)
    for db_ in (db, db2, db3):
        db_.close()


def test_plan_error_does_not_stall_turnstile(tmp_path, monkeypatch):
    """Regression: an exception INSIDE unit planning (after passing the
    admission turnstile, before admit_done) used to leave _admitted
    stuck, spinning every later unit forever -- and HostPrefetch.wait()
    has no timeout. The failing unit must fail alone; siblings complete
    and every waiter returns."""
    backend = _mk_backend(tmp_path, n_blocks=5, n_traces=20)
    db, blocks = _cold_blocks(backend, tmp_path, "plan")
    names = [n for n in blocks[0].pack.names() if n.startswith("span.")][:3]
    monkeypatch.setattr(
        blocks[1].pack, "plan_fetch",
        lambda *a, **k: (_ for _ in ()).throw(MemoryError("injected plan")))
    hp = stream.HostPrefetch([(b, list(names)) for b in blocks])
    assert hp.wait(blocks[1], timeout=30) is False  # the faulty unit
    for b in blocks:
        if b is not blocks[1]:
            assert hp.wait(b, timeout=30) is True  # siblings unaffected
    hp.close()
    assert stream._GATE.inflight_bytes() == 0
    db.close()


def test_host_prefetch_close_strands_no_waiter(tmp_path):
    """HostPrefetch.close mid-flight: wait() never blocks forever, and
    admitted bytes drain back to the gate."""
    backend = _mk_backend(tmp_path, n_blocks=5, n_traces=20)
    db, blocks = _cold_blocks(backend, tmp_path, "hp")
    names = [n for n in blocks[0].pack.names() if n.startswith("span.")][:3]
    hp = stream.HostPrefetch([(b, list(names)) for b in blocks])
    hp.close()
    for b in blocks:
        assert hp.wait(b, timeout=5) in (True, False)  # returns, promptly
    deadline = time.time() + 10
    while stream._GATE.inflight_bytes() and time.time() < deadline:
        time.sleep(0.01)  # started units finish their stage, then release
    assert stream._GATE.inflight_bytes() == 0
    db.close()


# ----------------------------------------------------------- byte budget
def test_byte_budget_admission_many_tiny_blocks(tmp_path, monkeypatch):
    """A tiny TEMPO_STREAM_MEM_BUDGET over many tiny units: everything
    still completes in order (one unit always admits -- stall, never
    deadlock) and the admission high-water stays bounded by the budget
    or by the single largest unit."""
    backend = _mk_backend(tmp_path, n_blocks=10, n_traces=12)
    db, blocks = _cold_blocks(backend, tmp_path, "bb")
    names = [n for n in blocks[0].pack.names() if n.startswith("span.")][:4]
    budget = 4096
    monkeypatch.setenv("TEMPO_STREAM_MEM_BUDGET", str(budget))
    stream._GATE.peak_bytes = 0
    units = [stream.StreamUnit(b, list(names), None, upload=False)
             for b in blocks]
    outs = list(stream.stream_staged(units, depth=6))
    assert [u for u, _ in outs] == units  # strict unit order
    assert all(r for _, r in outs)
    biggest = max(u.est_bytes for u in units)
    assert stream._GATE.peak_bytes <= max(budget, biggest) + biggest
    # the prefetched columns are genuinely cache-resident and correct
    for b in blocks:
        for n in names:
            assert b.pack.has_cached_array(n)
            assert b.pack.read(n) is not None
    db.close()


# ----------------------------------------------------------- passthrough
def test_compaction_passthrough_bit_identical(tmp_path, monkeypatch):
    """A compaction output that inherits one whole input block: with
    passthrough ON the data object is a verbatim byte copy of the input
    (never decompressed), and the decoded output is bit-identical to a
    passthrough-OFF full rewrite."""
    from tempo_tpu.block.builder import build_block_from_traces
    from tempo_tpu.block.colio import ColumnPack
    from tempo_tpu.db.compactor import CompactionJob, CompactorConfig, compact

    a = LocalBackend(str(tmp_path / "a"))
    traces = make_traces(50, seed=40, n_spans=5)
    meta_a = build_block_from_traces(a, TENANT, traces)
    shutil.copytree(str(tmp_path / "a"), str(tmp_path / "b"))
    b = LocalBackend(str(tmp_path / "b"))
    meta_b = meta_a  # same ids: b is a byte copy of a

    cfg = CompactorConfig(concat_small_input_bytes=0)
    pt0 = TEL.compact_passthrough_bytes.get()

    monkeypatch.setenv("TEMPO_COMPACT_PASSTHROUGH", "0")
    rw = compact(a, CompactionJob(TENANT, [meta_a]), cfg)
    monkeypatch.setenv("TEMPO_COMPACT_PASSTHROUGH", "1")
    pt = compact(b, CompactionJob(TENANT, [meta_b]), cfg)

    assert len(rw.new_blocks) == len(pt.new_blocks) == 1
    assert TEL.compact_passthrough_bytes.get() > pt0
    assert (rw.traces_out, rw.spans_out) == (pt.traces_out, pt.spans_out)
    m_rw, m_pt = rw.new_blocks[0], pt.new_blocks[0]
    assert m_pt.compaction_level == m_rw.compaction_level

    # verbatim: the passthrough output's data object is byte-equal to
    # the INPUT block's (the rewrite's is not required to be)
    assert (b.read(TENANT, m_pt.block_id, "data.vtpu")
            == b.read(TENANT, meta_b.block_id, "data.vtpu"))

    # logical bit-identity: every column decodes to the same arrays and
    # the dictionaries resolve the same strings per trace. Compare via
    # decoded columns + dictionary string lookups.
    pack_rw = ColumnPack.from_bytes(a.read(TENANT, m_rw.block_id, "data.vtpu"))
    pack_pt = ColumnPack.from_bytes(b.read(TENANT, m_pt.block_id, "data.vtpu"))
    assert set(pack_rw.names()) == set(pack_pt.names())
    from tempo_tpu.block.dictionary import Dictionary

    d_rw = Dictionary.from_bytes(a.read(TENANT, m_rw.block_id, "dict.vtpu"))
    d_pt = Dictionary.from_bytes(b.read(TENANT, m_pt.block_id, "dict.vtpu"))
    for name in sorted(pack_rw.names()):
        x, y = pack_rw.read(name), pack_pt.read(name)
        assert x.shape == y.shape, name
        if name.endswith("_id") or name.endswith(".key_id"):
            # dictionary codes may differ; the STRINGS must not
            assert [d_rw.string(int(v)) for v in np.asarray(x).ravel()[:200]] \
                == [d_pt.string(int(v)) for v in np.asarray(y).ravel()[:200]], name
        else:
            assert np.array_equal(np.asarray(x), np.asarray(y)), name

    # inputs consumed in both worlds
    assert rw.compacted_ids == pt.compacted_ids == [meta_a.block_id]
