"""Native C++ layer: bit-for-bit parity with the pure-Python paths.

Each binding is compared against its Python oracle; if the shared
library is unavailable the suite skips (the fallbacks are what the rest
of the test suite then exercises)."""

import os
import random

import numpy as np
import pytest

from tempo_tpu import native
from tempo_tpu.block.bloom import ShardedBloom
from tempo_tpu.util.hashing import ring_token

# The native layer is a required part of the framework: skipping this
# suite silently would drop its only coverage on an image change. Allow
# a skip only when explicitly requested (e.g. a deliberately
# Python-only environment).
if not native.available() and not os.environ.get("TEMPO_TPU_ALLOW_NATIVE_SKIP"):
    pytest.fail("native lib not built -- run `make -C native` "
                "(set TEMPO_TPU_ALLOW_NATIVE_SKIP=1 to skip deliberately)",
                pytrace=False)
pytestmark = pytest.mark.skipif(not native.available(), reason="native lib not built")


def test_ring_tokens_match_python():
    rng = random.Random(1)
    ids = [rng.getrandbits(128).to_bytes(16, "big") for _ in range(200)]
    got = native.ring_tokens("tenant-x", ids)
    expected = np.asarray([ring_token("tenant-x", t) for t in ids], dtype=np.uint32)
    np.testing.assert_array_equal(got, expected)


def test_bloom_add_batch_matches_python():
    rng = random.Random(2)
    ids = [rng.getrandbits(128).to_bytes(16, "big") for _ in range(500)]
    b_native = ShardedBloom(4, shard_bits=1 << 15)
    from tempo_tpu.block.bloom import _K
    assert native.bloom_add_batch(b_native, ids, _K)
    b_py = ShardedBloom(4, shard_bits=1 << 15)
    for t in ids:
        b_py.add(t)
    np.testing.assert_array_equal(b_native.words, b_py.words)
    for t in ids:
        assert b_native.test(t)


def test_varint_frames_roundtrip_and_torn_tail(tmp_path):
    from tempo_tpu.db.wal import WALBlock

    wal = WALBlock(str(tmp_path), "t")
    rng = random.Random(3)
    recs = []
    for i in range(50):
        tid = rng.getrandbits(128).to_bytes(16, "big")
        seg = bytes(rng.getrandbits(8) for _ in range(rng.randrange(1, 300)))
        recs.append((tid, seg))
        wal.append(tid, 10, 20, seg)
    wal.close()

    out, clean = WALBlock.read_records(wal.path)
    assert clean and len(out) == 50
    assert [(r.trace_id, r.segment) for r in out] == recs

    # torn tail: truncate mid-record
    with open(wal.path, "r+b") as f:
        f.truncate(os.path.getsize(wal.path) - 5)
    out, clean = WALBlock.read_records(wal.path)
    assert not clean and len(out) == 49
    # after truncation the file re-reads clean
    out2, clean2 = WALBlock.read_records(wal.path)
    assert clean2 and len(out2) == 49


def test_zstd_batch_roundtrip():
    rng = np.random.default_rng(4)
    chunks = [
        rng.integers(0, 50, size=rng.integers(200, 5000)).astype(np.int32).tobytes()
        for _ in range(20)
    ]
    comp = native.zstd_compress_chunks(chunks)
    assert comp is not None
    # native-compressed chunks decode with the python zstd library too
    # (images without the wheel still prove the native round-trip below)
    try:
        import zstandard
    except ModuleNotFoundError:
        zstandard = None
    if zstandard is not None:
        d = zstandard.ZstdDecompressor()
        for raw, z in zip(chunks, comp):
            assert d.decompress(z, max_output_size=len(raw)) == raw
    # and the native batch decompressor round-trips
    back = native.zstd_decompress_chunks(comp, [len(c) for c in chunks])
    assert back == chunks


def test_speed_codec_batch_roundtrip():
    """The snappy/lz4 halves of the codec matrix: threaded native batch
    compress -> batch decompress round-trips every chunk shape (runs,
    entropy, tiny, empty)."""
    rng = np.random.default_rng(6)
    chunks = [
        b"",
        b"x" * 3,
        np.zeros(40_000, np.uint8).tobytes(),
        rng.integers(0, 256, size=65_536, dtype=np.uint8).tobytes(),
        rng.integers(0, 4, size=30_000, dtype=np.uint8).tobytes(),
        b"ab" * 9_000,
    ]
    for codec in ("snappy", "lz4"):
        comp = native.block_compress_chunks(codec, chunks)
        assert comp is not None, codec
        back = native.block_decompress_chunks(codec, comp, [len(c) for c in chunks])
        assert back == chunks, codec


def test_colio_pack_native_roundtrip():
    """pack_columns (native batch compress) -> ColumnPack (native batch
    decompress) round-trips arrays exactly."""
    from tempo_tpu.block.colio import AxisChunks, ColumnPack, pack_columns

    rng = np.random.default_rng(5)
    ax = AxisChunks([0, 1000, 2000, 3000])
    cols = {
        "a": rng.integers(0, 100, size=3000).astype(np.int32),
        "b": rng.normal(size=3000).astype(np.float32),
        "c": rng.integers(0, 2**31, size=64).astype(np.int32),
    }
    blob = pack_columns(cols, axes={"span": ax}, col_axis={"a": "span", "b": "span"})
    pack = ColumnPack.from_bytes(blob)
    for k, v in cols.items():
        np.testing.assert_array_equal(pack.read(k), v)
    np.testing.assert_array_equal(pack.read_groups("a", [1, 2]), cols["a"][1000:3000])


def test_lex_bisect16_matches_searchsorted():
    from tempo_tpu.native import lex_bisect16

    rng = np.random.default_rng(3)
    ids = rng.integers(0, 256, size=(500, 16), dtype=np.uint8)
    ids = np.ascontiguousarray(ids[np.argsort(ids.view("V16").ravel())])
    hits = ids[rng.integers(0, 500, size=64)]
    misses = rng.integers(0, 256, size=(64, 16), dtype=np.uint8)
    q = np.ascontiguousarray(np.concatenate([hits, misses]))
    got = lex_bisect16(ids, q)
    if got is None:
        pytest.skip("native unavailable")
    iv = ids.view("V16").ravel()
    qv = q.view("V16").ravel()
    pos = np.searchsorted(iv, qv)
    clip = np.minimum(pos, len(iv) - 1)
    want = np.where((pos < len(iv)) & (iv[clip] == qv), pos, -1).astype(np.int32)
    np.testing.assert_array_equal(got, want)


def test_otlp_scan_huge_varint_lengths_rejected():
    """Regression: a length varint >= 2^63 must read as malformed at every
    nesting level, never as a negative int64 that bypasses the bounds
    check (previously a deterministic SIGSEGV from a ~15-byte payload,
    reachable unauthenticated through push_raw)."""
    hv = b"\x80" * 9 + b"\x01"  # varint encoding of 2^63

    # top-level ResourceSpans length
    assert native.otlp_scan(b"\x0a" + hv + b"\x00" * 4) is None

    # huge length on a field inside ResourceSpans (the advisory's payload shape)
    inner = b"\x0a" + hv + b"\x00"
    assert native.otlp_scan(b"\x0a" + bytes([len(inner)]) + inner) is None

    # huge length on a field inside ScopeSpans
    ss_body = b"\x0a" + hv + b"\x00"
    ss = b"\x12" + bytes([len(ss_body)]) + ss_body
    assert native.otlp_scan(b"\x0a" + bytes([len(ss)]) + ss) is None

    # huge length on a field inside a Span submessage
    span_body = b"\x0a" + hv
    span = b"\x12" + bytes([len(span_body)]) + span_body
    ss2 = b"\x12" + bytes([len(span)]) + span
    assert native.otlp_scan(b"\x0a" + bytes([len(ss2)]) + ss2) is None


def test_varint_frames_huge_length_reads_as_torn():
    """A WAL frame header claiming >= 2^63 bytes is a torn tail, not a
    negative-length frame."""
    good = b"\x03abc"
    hv = b"\x80" * 9 + b"\x01"
    res = native.varint_frames(good + hv + b"xyz")
    assert res is not None
    offs, lens, clean, torn_at = res
    assert not clean
    assert len(offs) == 1 and lens[0] == 3
    assert torn_at == len(good)


def test_otlp_splice_matches_python_splice():
    """The one-call native splice (vtpu_otlp_splice) emits byte-identical
    segments to the Python splice loop it replaces."""
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire import otlp_pb
    from tempo_tpu.wire.model import Trace
    from tempo_tpu.wire.otlp_splice import _split_by_trace_py, split_by_trace

    traces = make_traces(6, seed=9, n_spans=7)
    mixed = Trace()
    for _, t in traces:
        mixed.resource_spans.extend(t.resource_spans)
    payloads = [otlp_pb.encode_trace(mixed)] + [
        otlp_pb.encode_trace(t) for _, t in traces
    ]
    for payload in payloads:
        got = split_by_trace(payload)
        want = _split_by_trace_py(payload)
        assert got == want


def test_otlp_splice_capacity_regrow():
    """Output larger than 2x the payload (many single-span traces sharing
    one big resource envelope) exercises the rc=2 re-call path."""
    from tempo_tpu.wire import otlp_pb
    from tempo_tpu.wire.model import Resource, ResourceSpans, ScopeSpans, Span, Trace
    from tempo_tpu.wire.otlp_splice import _split_by_trace_py, split_by_trace

    rs = ResourceSpans(resource=Resource(attrs={"pad": "x" * 2000}))
    ss = ScopeSpans()
    for i in range(64):  # every span its own trace id -> envelope repeats 64x
        ss.spans.append(Span(
            trace_id=i.to_bytes(16, "big"), span_id=i.to_bytes(8, "big"),
            name=f"s{i}", start_unix_nano=10**18, end_unix_nano=10**18 + 1000))
    rs.scope_spans.append(ss)
    payload = otlp_pb.encode_trace(Trace(resource_spans=[rs]))
    got = split_by_trace(payload)
    want = _split_by_trace_py(payload)
    assert got == want
    segs, k = got
    assert k == 64 and len(segs) == 64
    assert sum(len(s) for _, _, s in segs.values()) > 2 * len(payload)


def test_otlp_splice_timestamp_near_u64_max():
    """End timestamps near 2^64 (tolerated nonconformant input) must not
    wrap in the native ceiling-divide; both paths agree."""
    from tempo_tpu.wire import otlp_pb
    from tempo_tpu.wire.model import ResourceSpans, ScopeSpans, Span, Trace
    from tempo_tpu.wire.otlp_splice import _split_by_trace_py, split_by_trace

    for end in (2**64 - 1, 18446744072800000000, 18446744073000000000, 10**9, 1):
        sp = Span(trace_id=b"\x01" * 16, span_id=b"\x02" * 8, name="edge",
                  start_unix_nano=min(end, 2**64 - 5), end_unix_nano=end)
        payload = otlp_pb.encode_trace(
            Trace(resource_spans=[ResourceSpans(scope_spans=[ScopeSpans(spans=[sp])])]))
        got = split_by_trace(payload)
        want = _split_by_trace_py(payload)
        assert got == want, f"end={end}"
        (_, end_s, _), = got[0].values()
        assert end_s == (end + 10**9 - 1) // 10**9, f"end={end}"
