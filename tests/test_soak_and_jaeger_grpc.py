"""Short CI runs of the two integration surfaces: the soak rig (the
reference's k6 smoke/stress analog, soak.py) and the Jaeger gRPC
storage plugin (cmd/tempo-query analog, tempo_tpu/tempo_query.py)."""

import json
import sys
import threading
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tempo_tpu.services.app import App, AppConfig, IngesterConfig
from tempo_tpu.wire import pbwire as w


@pytest.fixture(scope="module")
def live_app(tmp_path_factory):
    cfg = AppConfig(
        target="all", http_port=0,
        storage_path=str(tmp_path_factory.mktemp("store")),
        ingester=IngesterConfig(max_trace_idle_s=0.2, max_block_age_s=0.5,
                                flush_check_period_s=0.1),
    )
    app = App(cfg)
    app.start()
    srv = app.serve_http(background=True)
    port = srv.server_address[1]
    yield app, f"http://127.0.0.1:{port}"
    srv.shutdown()
    app.stop()


def test_soak_smoke(live_app):
    """A short sustained run: concurrent writers + readers, zero errors,
    every sampled write findable, latency under thresholds."""
    from soak import Soak

    _, url = live_app
    soak = Soak(url, writers=3, readers=2, spans_per_trace=4, batch=3)
    report = soak.run(duration_s=4.0, settle_s=2.0,
                      max_write_p95_s=2.0, max_search_p95_s=5.0)
    assert report["ok"], json.dumps(report, indent=2)
    assert report["written"] >= 20
    assert report["error_count"] == 0 and not report["missing_after_settle"]


def _grpc_call_unary(channel, method, body: bytes) -> bytes:
    return channel.unary_unary(method)(body)


def test_jaeger_grpc_storage_plugin(live_app):
    """The storage plugin serves GetServices / GetOperations /
    FindTraces / GetTrace over real gRPC against a live instance."""
    import grpc

    from tempo_tpu import tempo_query

    app, url = live_app
    # seed a known trace through the public API
    import urllib.request

    tid = "000000000000000000000000000000ab"
    body = json.dumps({"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name", "value": {"stringValue": "jaeger-svc"}}]},
        "scopeSpans": [{"scope": {}, "spans": [{
            "traceId": tid, "spanId": "00000000000000ab", "name": "jop",
            "startTimeUnixNano": "1700000001000000000",
            "endTimeUnixNano": "1700000001200000000"}]}]}]}).encode()
    urllib.request.urlopen(urllib.request.Request(
        url + "/v1/traces", data=body,
        headers={"Content-Type": "application/json"}), timeout=10)
    time.sleep(1.0)  # let it flush into a block

    server, port, plugin = tempo_query.serve(tempo_query.TempoHTTP(url), port=0)
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        base = "/jaeger.storage.v1.SpanReaderPlugin/"

        services = _grpc_call_unary(ch, base + "GetServices", b"")
        names = [bytes(v).decode() for f, wt, v in w.iter_fields(services) if f == 1]
        assert "jaeger-svc" in names

        ops = _grpc_call_unary(ch, base + "GetOperations", b"")
        opnames = [bytes(v).decode() for f, wt, v in w.iter_fields(ops) if f == 1]
        assert "jop" in opnames

        # GetTrace: streamed SpansResponseChunk
        req = bytearray()
        w.write_bytes_field(req, 1, bytes.fromhex(tid))
        chunks = list(ch.unary_stream(base + "GetTrace")(bytes(req)))
        assert chunks
        span_msgs = [v for f, wt, v in w.iter_fields(chunks[0]) if f == 1]
        assert len(span_msgs) == 1
        fields = {f: v for f, wt, v in w.iter_fields(bytes(span_msgs[0]))}
        assert bytes(fields[1]).hex() == tid  # trace id round-trips
        assert bytes(fields[3]).decode() == "jop"

        # FindTraces by service tag
        q = bytearray()
        w.write_string_field(q, 1, "jaeger-svc")
        freq = bytearray()
        w.write_message_field(freq, 1, bytes(q))
        found = list(ch.unary_stream(base + "FindTraces")(bytes(freq)))
        assert found, "FindTraces returned no chunks"
        assert plugin.requests >= 4
    finally:
        server.stop(grace=1)
