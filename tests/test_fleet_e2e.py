"""Fleet e2e: the ISSUE's satellite #3 certification as a pytest --
rolling SIGKILL restart of every ingester at RF=2 while vulture
find_by_id/search probes run continuously against the real multi-process
topology (gossip membership, replicated distributor, quorum-reading
queriers behind a dispatcher frontend). Zero miss/corrupt allowed; sheds
are acceptable.

Marked BOTH slow (excluded from the tier-1 870s box) and fleet (so
`pytest -m fleet` runs exactly the fleet certs). Wall-clock is bounded:
the quick topology (2 ingesters, 1 querier) plus short settle windows
keeps a full run well under the e2e budget; a hard deadline assertion
makes a hung fleet fail fast instead of eating the suite."""

import threading
import time

import pytest

from tempo_tpu.fleet.harness import FleetTopology
from tempo_tpu.vulture import Vulture, VultureConfig

pytestmark = [pytest.mark.slow, pytest.mark.fleet]

E2E_DEADLINE_S = 240.0


def test_rolling_restart_rf2_zero_miss(tmp_path):
    t_start = time.time()
    topo = FleetTopology(str(tmp_path), ingesters=2, queriers=1, rf=2,
                         worker_concurrency=2)
    outcomes: dict[str, int] = {}
    fails: list[str] = []
    stop = threading.Event()

    def vloop(v: Vulture) -> None:
        while not stop.is_set():
            for r in v.cycle():
                outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1
                if r.outcome not in ("ok", "shed") and len(fails) < 10:
                    fails.append(f"{r.family}: {r.outcome} {r.detail}")

    try:
        topo.start()
        topo.push_traces(3, seed=21)
        v = Vulture(VultureConfig(
            push_url=topo.dist_url, query_url=topo.fe_url,
            families=("find_by_id", "search"), flush_every=0,
            generator_probes=False, visibility_timeout_s=30.0,
            spans_per_trace=3, batch_ids=2, seed=17))
        vt = threading.Thread(target=vloop, args=(v,), daemon=True)
        vt.start()
        time.sleep(2.0)  # probes in flight before the first kill
        for name in list(topo._ingesters):
            topo.kill_ingester(name)       # SIGKILL: no LEAVE record
            time.sleep(topo.hb + 1.0)      # heartbeat prune window
            topo.respawn_ingester(name)
            time.sleep(2.0)                # WAL replay + rejoin settle
        time.sleep(2.0)  # post-roll probes against the healed fleet
        stop.set()
        vt.join(timeout=90)
        assert not vt.is_alive(), "vulture probe loop hung"
        assert v.cycles > 0, "no probe cycle completed during the roll"
        misses = outcomes.get("miss", 0) + outcomes.get("timeout", 0)
        corrupt = outcomes.get("corrupt", 0)
        errors = outcomes.get("error", 0)
        assert misses == 0 and corrupt == 0 and errors == 0, (
            f"outcomes={outcomes} failures={fails}")
        assert time.time() - t_start < E2E_DEADLINE_S, (
            "fleet e2e blew its wall-clock budget")
    finally:
        stop.set()
        topo.stop()
