"""Streaming metrics-generator (the PR-17 device reduction plane).

The load-bearing property is the DIFFERENTIAL: the streaming
processors (coded columns + packed-key series assembly + per-window
device folds) must be bit-identical to the legacy decoded-trace
processors across randomized push/cut/flush interleavings -- both
expose through the same registry/exposition code, so comparing
metrics_text() lines compares every counter, histogram bucket and
exemplar at once. Durations are dyadic (exact in float32) so "bit
identical" is a hard equality, not a tolerance.
"""

import random
import time

import numpy as np
import pytest

from tempo_tpu.ingest.columnar import LiveDict, span_columns_from_trace
from tempo_tpu.services.generator import (
    LATENCY_BUCKETS,
    MetricsGenerator,
    ServiceGraphsProcessor,
    SpanMetricsProcessor,
    StreamingServiceGraphs,
    StreamingSpanMetrics,
)
from tempo_tpu.services.overrides import Limits, Overrides
from tempo_tpu.wire.model import Resource, ResourceSpans, ScopeSpans, Span, Trace

TENANT = "t1"

# dyadic seconds: exact in f32 AND in the f64 accumulators, so host and
# device folds agree bit-for-bit regardless of summation order
_DYADIC_NS = (125_000_000, 250_000_000, 500_000_000, 1_000_000_000,
              62_500_000, 2_000_000_000)
_SERVICES = ["api-gateway", "auth", "cart", "db", "payments"]
_OPS = ["GET /", "POST /api", "db.query", "rpc.Call"]


def _span(rng, tid, svc_unused, name, kind, status, parent=b"", span_id=None):
    start = 1_700_000_000_000_000_000 + rng.randrange(10**9)
    dur = rng.choice(_DYADIC_NS)
    return Span(trace_id=tid, span_id=span_id or rng.getrandbits(64).to_bytes(8, "big"),
                parent_span_id=parent, name=name, kind=kind,
                start_unix_nano=start, end_unix_nano=start + dur,
                status_code=status)


def _graph_trace(rng):
    """One trace holding a client/server pair (sometimes unpaired,
    sometimes failed) plus internal spans: exercises series assembly,
    edge pairing, exemplars and the failed path together."""
    tid = rng.getrandbits(128).to_bytes(16, "big")
    tr = Trace()
    csvc, ssvc = rng.sample(_SERVICES, 2)
    cid = rng.getrandbits(64).to_bytes(8, "big")
    c_status = 2 if rng.random() < 0.2 else 0
    client = _span(rng, tid, csvc, "call " + rng.choice(_OPS), 3, c_status,
                   span_id=cid)
    tr.resource_spans.append(ResourceSpans(
        resource=Resource(attrs={"service.name": csvc}),
        scope_spans=[ScopeSpans(spans=[client])]))
    spans = []
    if rng.random() < 0.8:  # paired server half (else the edge dangles)
        spans.append(_span(rng, tid, ssvc, "serve " + rng.choice(_OPS), 2,
                           2 if rng.random() < 0.2 else 0, parent=cid))
    for _ in range(rng.randrange(0, 3)):
        spans.append(_span(rng, tid, ssvc, rng.choice(_OPS),
                           rng.choice([1, 4, 5]), 2 if rng.random() < 0.1 else 0))
    if spans:
        tr.resource_spans.append(ResourceSpans(
            resource=Resource(attrs={"service.name": ssvc}),
            scope_spans=[ScopeSpans(spans=spans)]))
    return tr


@pytest.mark.parametrize("seed", [3, 11, 29])
def test_streaming_matches_legacy_differential(seed):
    """Randomized interleavings of push / collect (the cut analog) /
    metrics_text (the flush/scrape analog): every exposition line --
    counters, bucket cumsums, exemplars, service-graph edges -- from
    the streaming plane equals the legacy decoded-trace plane."""
    rng = random.Random(seed)
    legacy_sm = SpanMetricsProcessor()
    legacy_sg = ServiceGraphsProcessor()
    stream = MetricsGenerator(Overrides(), stale_series_s=3600.0)

    for _ in range(rng.randrange(6, 12)):
        batch = [_graph_trace(rng) for _ in range(rng.randrange(1, 5))]
        legacy_sm.push(TENANT, batch)
        legacy_sg.push(TENANT, batch)
        stream.push(TENANT, batch)
        r = rng.random()
        if r < 0.3:  # mid-stream cut: legacy folds its buffered columns
            legacy_sm.collect()
            legacy_sg.collect()
        elif r < 0.5:  # mid-stream scrape on both planes
            legacy_sm.metrics_text()
            legacy_sg.metrics_text()
            stream.metrics_text()

    legacy = sorted(legacy_sm.metrics_text() + legacy_sg.metrics_text())
    streaming = sorted(stream.metrics_text())
    assert streaming == legacy
    assert any(l.startswith("traces_service_graph_request_total") for l in legacy)
    # unpaired edges match too (dangling client halves, not yet expired)
    sg = stream._procs(TENANT)["service-graphs"]
    assert len(sg.pending) == len(legacy_sg.pending)


def test_streaming_shed_matches_legacy_and_readmits():
    """max-active-series sheds the same spans on both planes, and a
    shed key is NOT cached: capacity freed by eviction re-admits it."""
    rng = random.Random(5)
    traces = [_graph_trace(rng) for _ in range(10)]
    legacy = SpanMetricsProcessor(max_active_series=3)
    legacy.push(TENANT, traces)
    ov = Overrides(defaults=Limits(metrics_generator_max_active_series=3))
    gen = MetricsGenerator(ov, stale_series_s=3600.0)
    gen.push(TENANT, traces)
    sm = gen._procs(TENANT)["span-metrics"]
    assert sm.dropped_series == legacy.dropped_series > 0
    assert sorted(sm.metrics_text()) == sorted(legacy.metrics_text())
    # evict everything -> the previously-shed keys can claim the freed
    # slots (the packed caches were cleared wholesale)
    assert sm.evict_stale(0.0) == 3
    n = sm.push_columns([span_columns_from_trace(traces[-1], LiveDict().code)],
                        LiveDict())
    assert n > 0 and len(sm.keys) <= 3


def test_edge_reduce_device_host_twin_parity():
    """edge_metrics_reduce: the fused device program, its host twin and
    a numpy oracle agree exactly on integer outputs and bit-for-bit on
    dyadic-duration sums."""
    from tempo_tpu.ops.reduce import _edge_reduce_host, edge_metrics_reduce

    rng = np.random.default_rng(7)
    n, e = 400, 13
    eid = rng.integers(0, e, size=n).astype(np.int32)
    cdur = (rng.integers(1, 64, size=n) * 0.125).astype(np.float32)
    sdur = (rng.integers(1, 64, size=n) * 0.0625).astype(np.float32)
    failed = (rng.random(n) < 0.3).astype(np.int32)
    dev = edge_metrics_reduce(eid, cdur, sdur, failed, e, LATENCY_BUCKETS)
    host = _edge_reduce_host(eid, cdur, sdur, failed, e, LATENCY_BUCKETS)
    for d, h in zip(dev, host):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(h))
    edges_f32 = np.asarray(LATENCY_BUCKETS, np.float32)
    for k in range(e):
        m = eid == k
        assert dev[0][k] == m.sum()
        assert dev[1][k] == failed[m].sum()
        assert dev[2][k] == cdur[m].astype(np.float64).sum()
        assert dev[3][k] == sdur[m].astype(np.float64).sum()
        np.testing.assert_array_equal(
            dev[4][k], np.bincount(np.searchsorted(edges_f32, cdur[m]),
                                   minlength=len(LATENCY_BUCKETS) + 1))
    # empty window short-circuits with correctly-shaped zeros
    z = edge_metrics_reduce(np.zeros(0, np.int32), np.zeros(0, np.float32),
                            np.zeros(0, np.float32), np.zeros(0, np.int32),
                            e, LATENCY_BUCKETS)
    assert all(np.asarray(a).sum() == 0 for a in z)


def test_tap_zero_extra_decodes(tmp_path):
    """The counter proof for the tentpole claim: the streaming tap reads
    SpanColumns out of ColumnarIngest's identity-keyed cache, so after a
    push window + tap drain the decode counter equals the cached-segment
    count -- zero proto walks beyond the one ingest decode."""
    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire.otlp_pb import encode_trace

    app = App(AppConfig(
        target="all", storage_path=str(tmp_path / "store"),
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999)))
    app.start()
    try:
        traces = make_traces(8, seed=13, n_spans=4)
        for _, tr in traces:
            app.distributor.push_raw(TENANT, encode_trace(tr))
        app.distributor.flush_generator_tap()
        st = app.ingester.instance(TENANT).columnar.stats()
        assert st["decodes"] > 0
        assert st["decodes"] - st["cached"] == 0, st
        # and the window actually became series
        lines = app.generator.metrics_text()
        calls = [l for l in lines
                 if l.startswith("traces_spanmetrics_calls_total")]
        total = sum(int(l.rsplit(" ", 1)[1]) for l in calls)
        assert total == sum(t.span_count() for _, t in traces)
    finally:
        app.stop()


def test_generator_off_read_path_unchanged(tmp_path):
    """enable_generator=False: no tap, no generator, and the read path
    serves pushes exactly as before."""
    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.util.testdata import make_traces

    app = App(AppConfig(
        target="all", storage_path=str(tmp_path / "store"),
        enable_generator=False, compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999)))
    app.start()
    try:
        assert app.generator is None
        assert app.distributor.generator_window is None
        assert app.distributor.generator_forward is None
        traces = make_traces(5, seed=3, n_spans=3)
        for _, tr in traces:
            app.distributor.push(TENANT, tr.resource_spans)
        app.distributor.flush_generator_tap()
        for tid, tr in traces:
            got = app.querier.find_trace_by_id(TENANT, tid)
            assert got is not None and got.span_count() == tr.span_count()
    finally:
        app.stop()


def test_kerneltel_generator_plane():
    """The generator section of /status/kernels: windows, edge-store
    depth, per-stage time, shed counters and the freshness aggregate."""
    from tempo_tpu.util.kerneltel import TEL

    g0 = TEL.generator_stats()
    TEL.record_generator_stage("span-metrics", 0.002)
    TEL.record_generator_window(40, 7, unpaired=3, expired=1)
    TEL.record_generator_shed(TENANT, 2)
    TEL.record_generator_freshness(0.25)
    g = TEL.generator_stats()
    assert g["windows"] == g0["windows"] + 1
    assert g["window_spans"] == g0["window_spans"] + 40
    assert g["edges_completed"] == g0["edges_completed"] + 7
    assert g["unpaired"] == 3 and g["expired"] == 1
    assert g["shed"].get(TENANT, 0) >= 2
    assert g["stages"]["span-metrics"]["count"] >= 1
    assert g["freshness_max_s"] >= 0.25 and g["freshness_avg_s"] > 0
    assert "generator" in TEL.snapshot()


def test_generator_freshness_slo_objective():
    """Targets hosting a generator carry the push->series-visible
    freshness objective; generator-less targets don't."""
    from tempo_tpu.services.app import build_default_slo

    gen = MetricsGenerator(Overrides())
    names = [o.name for o in build_default_slo(None, gen).objectives()]
    assert names == ["generator-freshness"]
    assert "generator-freshness" not in [
        o.name for o in build_default_slo(None, None).objectives()]


def test_streaming_exemplars_carry_trace_ids():
    """Exemplar plumbing end to end: the last trace to touch a series
    is the one its bucket exemplar names."""
    rng = random.Random(19)
    gen = MetricsGenerator(Overrides(), stale_series_s=3600.0)
    tr = _graph_trace(rng)
    gen.push(TENANT, [tr])
    tid_hex = tr.resource_spans[0].scope_spans[0].spans[0].trace_id.hex()
    text = "\n".join(gen.metrics_text())
    assert f'trace_id="{tid_hex}"' in text
