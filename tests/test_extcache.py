"""External cache tier: memcached text protocol + RESP clients against
in-process fake servers, and the tiered CachedBackend composition."""

import socketserver
import threading

import pytest

from tempo_tpu.backend.cache import CachedBackend
from tempo_tpu.backend.extcache import MemcachedCache, RedisCache, open_external_cache
from tempo_tpu.backend.mem import MemBackend


class _FakeMemcached(socketserver.StreamRequestHandler):
    store: dict[bytes, bytes] = {}

    def handle(self):
        while True:
            line = self.rfile.readline()
            if not line:
                return
            parts = line.strip().split()
            if parts and parts[0] == b"get":
                val = self.store.get(parts[1])
                if val is not None:
                    self.wfile.write(b"VALUE %s 0 %d\r\n%s\r\nEND\r\n" % (parts[1], len(val), val))
                else:
                    self.wfile.write(b"END\r\n")
            elif parts and parts[0] == b"set":
                n = int(parts[4])
                data = self.rfile.read(n)
                self.rfile.read(2)
                self.store[parts[1]] = data
                self.wfile.write(b"STORED\r\n")
            else:
                self.wfile.write(b"ERROR\r\n")


class _FakeRedis(socketserver.StreamRequestHandler):
    store: dict[bytes, bytes] = {}

    def _read_cmd(self):
        line = self.rfile.readline()
        if not line or not line.startswith(b"*"):
            return None
        n = int(line[1:].strip())
        parts = []
        for _ in range(n):
            ln = int(self.rfile.readline()[1:].strip())
            parts.append(self.rfile.read(ln))
            self.rfile.read(2)
        return parts

    def handle(self):
        while True:
            cmd = self._read_cmd()
            if cmd is None:
                return
            if cmd[0].upper() == b"GET":
                val = self.store.get(cmd[1])
                if val is None:
                    self.wfile.write(b"$-1\r\n")
                else:
                    self.wfile.write(b"$%d\r\n%s\r\n" % (len(val), val))
            elif cmd[0].upper() == b"SETEX":
                self.store[cmd[1]] = cmd[3]
                self.wfile.write(b"+OK\r\n")
            else:
                self.wfile.write(b"-ERR\r\n")


def _serve(handler_cls):
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), handler_cls)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"127.0.0.1:{srv.server_address[1]}"


@pytest.fixture()
def memcached():
    _FakeMemcached.store = {}
    srv, addr = _serve(_FakeMemcached)
    yield addr
    srv.shutdown()


@pytest.fixture()
def redis():
    _FakeRedis.store = {}
    srv, addr = _serve(_FakeRedis)
    yield addr
    srv.shutdown()


def test_memcached_roundtrip(memcached):
    c = MemcachedCache([memcached])
    assert c.get("k1") is None
    c.set("k1", b"\x00\x01bloom-bytes")
    assert c.get("k1") == b"\x00\x01bloom-bytes"
    # oversized values are refused, not errors
    c.set("big", b"x" * (2 << 20))
    assert c.get("big") is None


def test_redis_roundtrip(redis):
    c = RedisCache(redis)
    assert c.get("k") is None
    c.set("k", b"DICT")
    assert c.get("k") == b"DICT"


def test_cache_down_degrades():
    """A dead cache server degrades to misses/no-ops, never errors."""
    c = MemcachedCache(["127.0.0.1:1"])  # nothing listens there
    assert c.get("k") is None
    c.set("k", b"v")  # swallowed
    r = RedisCache("127.0.0.1:1")
    assert r.get("k") is None


def test_tiered_cached_backend(memcached):
    """Fleet semantics: a SECOND process (fresh local LRU) finds control
    objects in the shared external tier without touching the store."""
    ext = open_external_cache({"kind": "memcached", "addrs": [memcached]})
    store = MemBackend()
    store.write("t", "b", "bloom-0", b"BLOOM")

    class Counting(MemBackend):
        pass

    c1 = CachedBackend(store, external=ext)
    assert c1.read("t", "b", "bloom-0") == b"BLOOM"  # miss -> store, fills both
    ext.flush()  # external writes ride the write-behind queue

    reads = []
    orig = store.read

    def spy(tenant, block_id, name):
        reads.append(name)
        return orig(tenant, block_id, name)

    store.read = spy
    c2 = CachedBackend(store, external=ext)  # "another querier process"
    assert c2.read("t", "b", "bloom-0") == b"BLOOM"
    assert reads == []  # answered by the external tier
    assert c2.external_hits == 1
    # and now it's in c2's local LRU too
    assert c2.read("t", "b", "bloom-0") == b"BLOOM"
    assert c2.hits == 1


def test_background_writeback_survives_stalled_cache():
    """A STALLED cache tier (accepts connections, never answers) must not
    block the read path: set() returns immediately through the
    write-behind queue, over-budget writes drop, and get() fails fast on
    its own socket timeout (reference: pkg/cache/background.go:22-80)."""
    import socketserver
    import threading
    import time

    from tempo_tpu.backend.extcache import BackgroundWriteCache, MemcachedCache

    class _Stalled(socketserver.StreamRequestHandler):
        def handle(self):
            time.sleep(30)  # never respond

    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Stalled)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        slow = MemcachedCache([f"127.0.0.1:{srv.server_address[1]}"], timeout=0.2)
        cache = BackgroundWriteCache(slow, max_queued_bytes=1024, writers=1)
        t0 = time.perf_counter()
        cache.set("a", b"x" * 512)  # queued; writer blocks on the stall
        cache.set("b", b"y" * 600)  # over budget while the writer stalls -> drop
        assert time.perf_counter() - t0 < 0.05, "set() blocked on the cache tier"
        assert cache.dropped >= 1
        t0 = time.perf_counter()
        assert cache.get("a") is None  # socket timeout, not a hang
        assert time.perf_counter() - t0 < 2.0
        cache.stop()
    finally:
        srv.shutdown()


def test_background_writeback_delivers():
    """With a healthy tier, queued writes land and later gets hit."""
    import time

    from tempo_tpu.backend.extcache import BackgroundWriteCache

    class _Mem:
        def __init__(self):
            self.d = {}

        def get(self, k):
            return self.d.get(k)

        def set(self, k, v):
            self.d[k] = v

    cache = BackgroundWriteCache(_Mem(), writers=1)
    for i in range(50):
        cache.set(f"k{i}", b"v%d" % i)
    deadline = time.time() + 5
    while time.time() < deadline and cache.get("k49") is None:
        time.sleep(0.01)
    assert cache.get("k0") == b"v0" and cache.get("k49") == b"v49"
    assert cache.dropped == 0
    cache.stop()
