"""Service layer: ring, distributor->ingester->block, querier/frontend,
WAL replay, compactor ownership, metrics-generator.

Mirrors the reference's module tests (modules/distributor rebatching
golden cases, ingester lifecycle, frontend sharding) at the same seams.
"""

import threading
import time
from dataclasses import replace

import numpy as np
import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.db.search import SearchRequest
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.db.wal import WAL
from tempo_tpu.ring.ring import InMemoryKV, InstanceState, Lifecycler, Ring
from tempo_tpu.services.distributor import Distributor, PushError
from tempo_tpu.services.frontend import Frontend
from tempo_tpu.services.generator import MetricsGenerator
from tempo_tpu.services.ingester import Ingester, IngesterConfig
from tempo_tpu.services.overrides import Limits, Overrides
from tempo_tpu.services.querier import Querier
from tempo_tpu.util.testdata import make_traces
from tempo_tpu.wire.model import SpanKind

TENANT = "t1"


# ------------------------------------------------------------------- ring


def test_ring_replication_and_ownership():
    kv = InMemoryKV()
    for i in range(3):
        lc = Lifecycler(kv, "r", f"inst-{i}")
        lc.join()
    ring = Ring(kv, "r", replication_factor=2)
    assert len(ring.healthy_instances()) == 3
    rs = ring.get(12345)
    assert len(rs.instances) == 2
    assert rs.instances[0].instance_id != rs.instances[1].instance_id
    # deterministic routing
    rs2 = ring.get(12345)
    assert [d.instance_id for d in rs.instances] == [d.instance_id for d in rs2.instances]
    # every job is owned by exactly one instance
    for h in ("job-a", "job-b", "job-c"):
        owners = [i for i in range(3) if ring.owns(f"inst-{i}", h)]
        assert len(owners) == 1
    # unhealthy instances drop out
    kv.get_all("r")["inst-0"].heartbeat_ts = time.time() - 9999
    assert len(ring.healthy_instances()) == 2


def test_ring_shuffle_shard_deterministic():
    kv = InMemoryKV()
    for i in range(8):
        Lifecycler(kv, "r", f"i{i}").join()
    ring = Ring(kv, "r")
    s1 = [d.instance_id for d in ring.shuffle_shard("tenant-a", 3)]
    s2 = [d.instance_id for d in ring.shuffle_shard("tenant-a", 3)]
    s3 = [d.instance_id for d in ring.shuffle_shard("tenant-b", 3)]
    assert s1 == s2 and len(s1) == 3
    assert s1 != s3 or True  # different tenants usually differ; no hard guarantee


# ------------------------------------------------------- pipeline fixture


@pytest.fixture()
def pipeline(tmp_path):
    db = TempoDB(
        TempoDBConfig(wal_path=str(tmp_path / "db-wal")), backend=MemBackend()
    )
    wal = WAL(str(tmp_path / "wal"))
    overrides = Overrides()
    cfg = IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0)
    ing = Ingester(wal, db, overrides, cfg)
    kv = InMemoryKV()
    lc = Lifecycler(kv, "ing", "ing-0")
    lc.join()
    ring = Ring(kv, "ing", replication_factor=1)
    clients = {lc.desc.addr: ing}
    dist = Distributor(ring, clients.__getitem__, overrides)
    q = Querier(db, ring, clients.__getitem__)
    fe = Frontend(q, n_workers=4)
    yield db, ing, dist, q, fe
    fe.stop()
    db.close()


def _push_all(dist, traces):
    for tid, tr in traces:
        dist.push(TENANT, tr.resource_spans)


def test_e2e_push_cut_query(pipeline):
    db, ing, dist, q, fe = pipeline
    traces = make_traces(25, seed=3, n_spans=6)
    _push_all(dist, traces)
    assert dist.stats.spans_received == sum(t.span_count() for _, t in traces)

    # before cut: live in ingester, visible via querier ingester leg
    tid0 = traces[0][0]
    tr = q.find_trace_by_id(TENANT, tid0)
    assert tr is not None and tr.trace_id() == tid0

    # cut everything into a block
    ing.sweep_all(force=True)
    inst = ing.instance(TENANT)
    assert inst.blocks_flushed == 1
    assert len(db.blocklist.metas(TENANT)) == 1
    assert not inst.live and not inst.cut

    # after cut: found via backend leg
    for tid, t in traces[:5]:
        got = fe.find_trace_by_id(TENANT, tid)
        assert got is not None
        assert got.span_count() == t.span_count()
    # miss
    assert fe.find_trace_by_id(TENANT, b"\x00" * 16) is None


def test_e2e_search_live_and_backend(pipeline):
    db, ing, dist, q, fe = pipeline
    traces = make_traces(30, seed=9, n_spans=5)
    _push_all(dist, traces)

    def expect(pred):
        return {
            tid.hex() for tid, t in traces if any(pred(r, s) for r, _, s in t.all_spans())
        }

    # live search (nothing cut yet)
    resp = fe.search(TENANT, SearchRequest(tags={"service.name": "db"}, limit=100))
    assert {r.trace_id for r in resp.traces} == expect(
        lambda r, s: r.service_name == "db"
    )

    # cut to backend, search again through the sharded path
    ing.sweep_all(force=True)
    resp = fe.search(TENANT, SearchRequest(tags={"service.name": "db"}, limit=100))
    assert {r.trace_id for r in resp.traces} == expect(
        lambda r, s: r.service_name == "db"
    )
    # TraceQL through the frontend
    resp = fe.search(TENANT, SearchRequest(query='{ resource.service.name = "db" }', limit=100))
    assert {r.trace_id for r in resp.traces} == expect(
        lambda r, s: r.service_name == "db"
    )


def test_rate_limit_and_trace_size(tmp_path):
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dw")), backend=MemBackend())
    wal = WAL(str(tmp_path / "w"))
    overrides = Overrides(defaults=Limits(ingestion_rate_limit_bytes=1, ingestion_burst_size_bytes=1))
    ing = Ingester(wal, db, overrides)
    kv = InMemoryKV()
    lc = Lifecycler(kv, "r", "i0")
    lc.join()
    dist = Distributor(Ring(kv, "r"), {lc.desc.addr: ing}.__getitem__, overrides)
    traces = make_traces(2, seed=1, n_spans=4)
    with pytest.raises(PushError) as ei:
        _push_all(dist, traces)
    assert ei.value.status == 429
    db.close()


def test_wal_replay_recovers_unflushed(tmp_path):
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dw")), backend=MemBackend())
    wal_dir = str(tmp_path / "w")
    overrides = Overrides()
    ing = Ingester(WAL(wal_dir), db, overrides)
    traces = make_traces(10, seed=4, n_spans=4)
    kv = InMemoryKV()
    lc = Lifecycler(kv, "r", "i0")
    lc.join()
    dist = Distributor(Ring(kv, "r"), {lc.desc.addr: ing}.__getitem__, overrides)
    _push_all(dist, traces)
    # crash: no cut, no flush. A new ingester over the same WAL dir replays
    db2 = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dw2")), backend=MemBackend())
    ing2 = Ingester(WAL(wal_dir), db2, overrides)
    n = ing2.replay_wal()
    assert n == len(traces)
    assert len(db2.blocklist.metas(TENANT)) >= 1
    for tid, t in traces:
        got = db2.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == t.span_count()
    # WAL files are consumed
    assert ing2.replay_wal() == 0
    db.close()
    db2.close()


def test_generator_span_metrics_and_service_graphs():
    overrides = Overrides()
    gen = MetricsGenerator(overrides)
    traces = make_traces(20, seed=7, n_spans=6)
    gen.push(TENANT, [t for _, t in traces])
    lines = gen.metrics_text()
    calls = [l for l in lines if l.startswith("traces_spanmetrics_calls_total")]
    assert calls
    # total calls across series == total spans
    total = sum(int(l.rsplit(" ", 1)[1]) for l in calls)
    assert total == sum(t.span_count() for _, t in traces)
    # histogram counts match calls
    lat_count = sum(
        int(l.rsplit(" ", 1)[1]) for l in lines if l.startswith("traces_spanmetrics_latency_count")
    )
    assert lat_count == total
    # service graph edges exist when client/server pairs exist
    has_pairs = any(
        sp.kind == SpanKind.CLIENT for _, t in traces for _, _, sp in t.all_spans()
    )
    if has_pairs:
        assert any(l.startswith("traces_service_graph_request_total") for l in lines) or True


def test_generator_reduce_oracle():
    """Device segmented reduce == numpy oracle."""
    from tempo_tpu.ops.reduce import span_metrics_reduce

    rng = np.random.default_rng(5)
    n, s = 500, 17
    sid = rng.integers(0, s, size=n).astype(np.int32)
    dur = rng.uniform(0, 20, size=n).astype(np.float32)
    edges = (0.5, 1.0, 5.0)
    calls, lsum, hist = span_metrics_reduce(sid, dur, s, edges)
    for k in range(s):
        m = sid == k
        assert calls[k] == m.sum()
        np.testing.assert_allclose(lsum[k], dur[m].sum(), rtol=1e-4)
        idx = np.searchsorted(np.asarray(edges, np.float32), dur[m])
        np.testing.assert_array_equal(hist[k], np.bincount(idx, minlength=4))
    assert hist.sum() == n


def test_compactor_ring_ownership(tmp_path):
    from tempo_tpu.services.compactor import Compactor

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dw")), backend=MemBackend())
    # two small RECENT blocks -> one compaction job (old timestamps would
    # be swept by retention right after compaction, which is correct)
    now_ns = time.time_ns()
    db.write_block(TENANT, make_traces(10, seed=1, n_spans=3, base_time_ns=now_ns))
    db.write_block(TENANT, make_traces(10, seed=2, n_spans=3, base_time_ns=now_ns))
    kv = InMemoryKV()
    lc = Lifecycler(kv, "comp", "c0")
    lc.join()
    ring = Ring(kv, "comp")
    comp = Compactor(db, ring, "c0", cycle_s=9999)
    comp.run_once()
    assert comp.stats.blocks_compacted >= 2
    metas = db.blocklist.metas(TENANT)
    # small level-0 inputs take the concat path: parts of ONE compound
    assert len(metas) == 2 and all(m.compaction_level == 1 for m in metas)
    assert len({m.block_id.split("/")[0] for m in metas}) == 1
    # a non-member instance owns nothing
    db2 = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dw2")), backend=MemBackend())
    db2.write_block(TENANT, make_traces(6, seed=3, n_spans=2, base_time_ns=now_ns))
    db2.write_block(TENANT, make_traces(6, seed=4, n_spans=2, base_time_ns=now_ns))
    comp2 = Compactor(db2, ring, "not-in-ring", cycle_s=9999)
    comp2.run_once()
    assert comp2.stats.blocks_compacted == 0
    db.close()
    db2.close()


def test_generator_stale_series_eviction():
    overrides = Overrides()
    gen = MetricsGenerator(overrides, stale_series_s=0.0)  # everything stale instantly
    traces = make_traces(5, seed=11, n_spans=3)
    gen.push(TENANT, [t for _, t in traces])
    time.sleep(0.01)
    lines = gen.metrics_text()
    assert not any(l.startswith("traces_spanmetrics_calls_total") for l in lines)


def test_app_target_gating(tmp_path):
    from tempo_tpu.services.app import App, AppConfig

    # querier-only process: no ingester, no compactor, queries served
    app = App(AppConfig(target="querier", storage_path=str(tmp_path / "s1")))
    assert app.ingester is None and app.compactor is None and app.distributor is None
    assert app.querier is not None
    app.start()
    assert app.ready()
    app.stop()

    # compactor-only process
    app = App(AppConfig(target="compactor", storage_path=str(tmp_path / "s2"),
                        compaction_cycle_s=9999))
    assert app.compactor is not None and app.querier is None
    app.stop()

    # standalone distributor is rejected (needs remote transport)
    with pytest.raises(ValueError):
        App(AppConfig(target="distributor", storage_path=str(tmp_path / "s3")))
    with pytest.raises(ValueError):
        App(AppConfig(target="bogus", storage_path=str(tmp_path / "s4")))


def test_frontend_find_shards_blocks(pipeline):
    """Trace-by-ID over a many-block backend shards the candidate block
    set into parallel find_blocks jobs and combines PARTIAL traces from
    different shards (tracebyidsharding.go:30-48 analog)."""
    db, ing, dist, q, fe = pipeline
    # one trace whose spans are split across two blocks far apart in the
    # candidate list, plus filler blocks so sharding kicks in
    tid, tr = make_traces(1, seed=91, n_spans=8)[0]
    spans = tr.resource_spans
    from tempo_tpu.wire.model import Trace

    part1, part2 = Trace(resource_spans=spans[:1]), Trace(resource_spans=spans[1:])
    # pad the trace to have >=2 resource_spans for the split
    if len(spans) < 2:
        part1 = part2 = tr
    db.write_block(TENANT, [(tid, part1)])
    for i in range(40):
        db.write_block(TENANT, sorted(make_traces(2, seed=200 + i, n_spans=2),
                                      key=lambda t: t[0]))
    db.write_block(TENANT, [(tid, part2)])

    from tempo_tpu.services import frontend as fe_mod

    calls = []
    orig = q.find_in_blocks

    def spy(tenant, trace_id, metas):
        calls.append(len(metas))
        return orig(tenant, trace_id, metas)

    q.find_in_blocks = spy
    n_candidates = len(db.find_candidates(TENANT, tid))
    assert n_candidates >= 2  # both halves' blocks at minimum
    old = fe_mod.FIND_SHARD_BLOCKS
    fe_mod.FIND_SHARD_BLOCKS = 2  # force multiple shard jobs
    try:
        got = fe.find_trace_by_id(TENANT, tid)
    finally:
        fe_mod.FIND_SHARD_BLOCKS = old
    assert got is not None
    # the frontend must have issued one job per 2-block partition
    assert len(calls) == -(-n_candidates // 2), calls
    assert sum(calls) == n_candidates
    if part1 is not part2:
        assert got.span_count() == tr.span_count()  # partials combined


def test_generator_shuffle_shard_disjoint():
    """Two tenants route to DISJOINT generator subsets at ring size 2
    (distributor.go:410-442 shuffle-sharded generator writes)."""
    kv = InMemoryKV()
    clients = {}
    pushed = {}  # addr -> [(tenant, n_traces)]

    class FakeGen:
        def __init__(self, addr):
            self.addr = addr

        def push_generator_blobs(self, tenant, blobs):
            # the tap ships otlp-proto blobs sliced from segments
            pushed.setdefault(self.addr, []).append((tenant, len(blobs)))

    for i in range(4):
        lc = Lifecycler(kv, "generator-ring", f"gen-{i}", addr=f"gen-{i}:9095")
        lc.join()
        clients[f"gen-{i}:9095"] = FakeGen(f"gen-{i}:9095")
    gen_ring = Ring(kv, "generator-ring")

    # also a local ingester ring so pushes succeed
    lc = Lifecycler(kv, "ing", "ing-0")
    lc.join()

    class FakeIng:
        def push_segments(self, tenant, batch):
            pass

    ing_ring = Ring(kv, "ing")
    clients[lc.desc.addr] = FakeIng()
    ov = Overrides()
    ov.defaults = replace(ov.defaults, metrics_generator_ring_size=2)
    dist = Distributor(ing_ring, clients.__getitem__, ov, generator_ring=gen_ring)

    # find two tenants with disjoint shuffle shards (deterministic)
    names = [f"tenant-{i}" for i in range(40)]
    subset = {n: frozenset(d.addr for d in gen_ring.shuffle_shard(n, 2)) for n in names}
    pair = next(
        (a, b) for a in names for b in names if not (subset[a] & subset[b])
    )
    for tenant in pair:
        for tid, tr in make_traces(6, seed=hash(tenant) % 1000, n_spans=2):
            dist.push(tenant, tr.resource_spans)
    dist.flush_generator_tap()  # the tap runs async off the push path

    got = {t: set() for t in pair}
    for addr, recs in pushed.items():
        for tenant, _n in recs:
            if tenant in got:
                got[tenant].add(addr)
    a, b = pair
    assert got[a] and got[a] <= subset[a]
    assert got[b] and got[b] <= subset[b]
    assert not (got[a] & got[b])  # disjoint generator subsets


def test_queue_querier_shuffle_shard(pipeline):
    """With max_queriers_per_tenant=1, every job of a tenant is leased to
    the SAME remote worker; the other attached worker never sees it
    (pkg/scheduler/queue/user_queues.go)."""
    db, ing, dist, q, _fe = pipeline
    ov = Overrides()
    ov.defaults = replace(ov.defaults, max_queriers_per_tenant=1)
    fe = Frontend(q, n_workers=0, overrides=ov)  # dispatcher-only

    # attach two workers (a poll registers the worker id)
    assert fe.poll_job(wait_s=0.01, worker_id="w1") is None
    assert fe.poll_job(wait_s=0.01, worker_id="w2") is None

    from tempo_tpu.services.frontend import _Job

    for i in range(6):
        fe.queue.enqueue(TENANT, _Job(kind="search_recent", payload={},
                                      fn=lambda: None, args=()))
    leased = {"w1": 0, "w2": 0}
    for _ in range(12):
        for w in ("w1", "w2"):
            job = fe.poll_job(wait_s=0.01, worker_id=w)
            if job:
                leased[w] += 1
    assert sorted(leased.values()) == [0, 6], leased


def test_ingester_flush_backoff(tmp_path):
    """A failing block flush backs off exponentially per tenant instead
    of retrying every sweep, and recovers once the backend heals
    (reference: flushqueues retry-with-backoff, flush.go:62-67)."""
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dw")), backend=MemBackend())
    ing = Ingester(WAL(str(tmp_path / "w")), db, Overrides(),
                   IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0))
    from tempo_tpu.wire.segment import segment_for_write

    traces = make_traces(4, seed=5, n_spans=3)
    batch = []
    for tid, tr in traces:
        lo, hi = tr.time_range_nanos()
        batch.append((tid, lo // 10**9, hi // 10**9 + 1,
                      segment_for_write(tr, lo // 10**9, hi // 10**9 + 1)))
    ing.push_segments(TENANT, batch)

    calls = []
    orig = db.write_block

    def failing(tenant, trs):
        calls.append(time.time())
        raise OSError("backend down")

    db.write_block = failing
    ing.sweep_all()  # first failure -> backoff armed
    n1 = len(calls)
    assert n1 == 1
    ing.sweep_all()  # inside backoff window: no retry
    assert len(calls) == n1
    ing._flush_retry_at[TENANT] = 0.0  # window elapsed
    ing.sweep_all()
    assert len(calls) == n1 + 1
    assert ing._flush_backoff[TENANT] == 4.0  # doubled

    db.write_block = orig  # backend heals
    ing._flush_retry_at[TENANT] = 0.0
    ing.sweep_all()
    assert len(db.blocklist.metas(TENANT)) == 1
    assert TENANT not in ing._flush_backoff  # state cleared
    db.close()


def test_serverless_external_search(tmp_path):
    """Block-shard search jobs dispatch to an external serverless
    handler (tempo_tpu.serverless HTTP mode) with local fallback
    (querier.go:401-458 searchExternalEndpoints): results match local
    execution, a frontend search rides the external path for oversized
    blocks, and a dead endpoint degrades to local, never failing."""
    import threading

    from tempo_tpu import serverless
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.services.frontend import Frontend
    from tempo_tpu.services.querier import Querier

    store = str(tmp_path / "store")
    db = TempoDB(
        TempoDBConfig(backend={"backend": "local", "path": store},
                      wal_path=str(tmp_path / "wal")),
        backend=LocalBackend(store),
    )
    traces = make_traces(40, seed=9, n_spans=6)
    db.write_block(TENANT, traces)
    db.poll_now()
    meta = db.blocklist.metas(TENANT)[0]

    srv = serverless.serve(0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{port}/"

    req = SearchRequest(limit=100)
    local = db.search_block_shard(TENANT, meta, req, None)

    q = Querier(db, None, None, external_endpoints=[url],
                external_hedge_after_s=2.0)
    ext = q.search_block_shard(TENANT, meta, req, None)
    assert q.stats.external_searches == 1 and q.stats.external_failures == 0
    assert {t.trace_id for t in ext.traces} == {t.trace_id for t in local.traces}
    assert ext.inspected_spans == local.inspected_spans > 0

    # frontend e2e: tiny batch budget forces row-group shard jobs, which
    # all ride the external endpoint
    fe = Frontend(q, n_workers=2, batch_bytes=1)
    before = q.stats.external_searches
    resp = fe.search(TENANT, SearchRequest(limit=100))
    assert len(resp.traces) == 40
    assert q.stats.external_searches > before
    fe.close() if hasattr(fe, "close") else None

    # dead endpoint: falls back to local, still correct
    qdead = Querier(db, None, None, external_endpoints=["http://127.0.0.1:1/"],
                    external_hedge_after_s=0.2)
    got = qdead.search_block_shard(TENANT, meta, req, None)
    assert qdead.stats.external_failures == 1
    assert {t.trace_id for t in got.traces} == {t.trace_id for t in local.traces}
    srv.shutdown()
