"""TraceQL: parser unit tests + end-to-end execution against blocks."""

import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.db.search import SearchRequest
from tempo_tpu.traceql import ParseError, parse
from tempo_tpu.traceql.ast import Comparison, LogicalExpr, Scope
from tempo_tpu.util.testdata import make_traces

TENANT = "t"


# ----------------------------------------------------------------- parser


def test_parse_basic():
    q = parse('{ span.foo = "bar" }')
    c = q.expr
    assert isinstance(c, Comparison)
    assert c.field.scope == Scope.SPAN and c.field.name == "foo"
    assert c.op == "=" and c.value.value == "bar"


def test_parse_scopes_and_intrinsics():
    q = parse('{ resource.service.name = "x" && name = "y" && .cluster = "z" }')
    e = q.expr
    assert isinstance(e, LogicalExpr) and e.op == "&&"
    # left-assoc: ((a && b) && c)
    assert e.rhs.field.scope == Scope.EITHER and e.rhs.field.name == "cluster"
    assert e.lhs.lhs.field.scope == Scope.RESOURCE
    assert e.lhs.lhs.field.name == "service.name"
    assert e.lhs.rhs.field.scope == Scope.INTRINSIC


def test_parse_values():
    q = parse("{ duration > 1h30m && span.count >= 100 && span.ratio < 0.5 && span.ok = true }")
    comps = []

    def walk(e):
        if isinstance(e, LogicalExpr):
            walk(e.lhs)
            walk(e.rhs)
        else:
            comps.append(e)

    walk(q.expr)
    dur = comps[0]
    assert dur.value.kind == "duration" and dur.value.value == 5400 * 10**9
    assert comps[1].value.kind == "int" and comps[1].value.value == 100
    assert comps[2].value.kind == "float"
    assert comps[3].value.kind == "bool"


def test_parse_status_kind_regex():
    q = parse("{ status = error && kind = server }")
    assert q.expr.lhs.value.kind == "status" and q.expr.lhs.value.value == 2
    assert q.expr.rhs.value.kind == "kind" and q.expr.rhs.value.value == 2
    q2 = parse('{ span.http.url =~ "api/.*" }')
    assert q2.expr.op == "=~"


def test_parse_parens_and_or():
    q = parse('{ (span.a = "1" || span.b = "2") && name = "n" }')
    assert isinstance(q.expr, LogicalExpr) and q.expr.op == "&&"
    assert q.expr.lhs.op == "||"


def test_parse_reversed_operands():
    q = parse("{ 100 < span.count }")
    assert q.expr.field.name == "count" and q.expr.op == ">"


def test_parse_empty_and_exists():
    # `{}` is a parse error per the reference grammar (test_examples
    # parse_fails); a bare field is truthiness, not existence
    with pytest.raises(ParseError):
        parse("{}")
    q = parse("{ span.foo }")
    from tempo_tpu.traceql.ast import Field
    assert isinstance(q.expr, Field) and q.expr.name == "foo"
    q2 = parse("{ span.foo != nil }")
    assert q2.expr.op == "!=" and q2.expr.value.kind == "nil"


def test_parse_errors():
    for bad in ["span.x = 1", "{ span.x = }", "{", "{ true } | count()", '{ name = "x" } { }']:
        with pytest.raises(ParseError):
            parse(bad)


# ----------------------------------------------------------- execution


@pytest.fixture(scope="module")
def db(tmp_path_factory):
    d = TempoDB(TempoDBConfig(wal_path=str(tmp_path_factory.mktemp("wal"))), backend=MemBackend())
    traces = make_traces(80, seed=21, n_spans=8)
    d.write_block(TENANT, traces)
    return d, traces


def _expect(traces, pred):
    return {tid.hex() for tid, t in traces if any(pred(res, sp) for res, _, sp in t.all_spans())}


def _run(db, q):
    return {r.trace_id for r in db.search(TENANT, SearchRequest(query=q, limit=1000)).traces}


def test_query_service_name(db):
    d, traces = db
    got = _run(d, '{ resource.service.name = "db" }')
    assert got == _expect(traces, lambda res, sp: res.service_name == "db")


def test_query_span_attr_and_duration(db):
    d, traces = db
    got = _run(d, '{ span.http.method = "GET" && duration > 500ms }')
    assert got == _expect(
        traces,
        lambda res, sp: sp.attrs.get("http.method") == "GET" and sp.duration_nanos > 500_000_000,
    )
    assert got  # non-trivial


def test_query_duration_exact_boundary(db):
    d, traces = db
    # pick an actual span duration and query strictly-greater: that span
    # must NOT match on its own duration
    tid0, t0 = traces[0]
    sp0 = next(t0.all_spans())[2]
    ns = sp0.duration_nanos
    got_gt = _run(d, f"{{ duration > {ns}ns }}")
    expect_gt = _expect(traces, lambda res, sp: sp.duration_nanos > ns)
    assert got_gt == expect_gt
    got_ge = _run(d, f"{{ duration >= {ns}ns }}")
    expect_ge = _expect(traces, lambda res, sp: sp.duration_nanos >= ns)
    assert got_ge == expect_ge
    assert tid0.hex() in got_ge


def test_query_int_attr(db):
    d, traces = db
    got = _run(d, "{ span.http.status_code >= 500 }")
    assert got == _expect(
        traces,
        lambda res, sp: isinstance(sp.attrs.get("http.status_code"), int)
        and sp.attrs["http.status_code"] >= 500,
    )


def test_query_status_error(db):
    d, traces = db
    got = _run(d, "{ status = error }")
    assert got == _expect(traces, lambda res, sp: sp.status_code == 2)


def test_query_or_and_parens(db):
    d, traces = db
    got = _run(d, '{ (resource.service.name = "db" || resource.service.name = "auth") && kind = client }')
    assert got == _expect(
        traces, lambda res, sp: res.service_name in ("db", "auth") and sp.kind == 3
    )


def test_query_regex(db):
    d, traces = db
    got = _run(d, '{ name =~ "GET.*" }')
    assert got == _expect(traces, lambda res, sp: sp.name.startswith("GET"))
    got2 = _run(d, '{ name !~ "GET.*" }')
    assert got2 == _expect(traces, lambda res, sp: not sp.name.startswith("GET"))


def test_query_neq_semantics(db):
    d, traces = db
    # != requires the attribute to EXIST and differ (TraceQL nil-compare is false)
    got = _run(d, '{ span.http.method != "GET" }')
    assert got == _expect(
        traces,
        lambda res, sp: "http.method" in sp.attrs and sp.attrs["http.method"] != "GET",
    )


def test_query_bool_attr(db):
    d, traces = db
    got = _run(d, "{ span.cache.hit = true }")
    assert got == _expect(traces, lambda res, sp: sp.attrs.get("cache.hit") is True)


def test_query_either_scope(db):
    d, traces = db
    got = _run(d, '{ .k8s.namespace.name = "apps" }')
    assert got == _expect(traces, lambda res, sp: res.attrs.get("k8s.namespace.name") == "apps")


def test_query_same_span_semantics(db):
    d, traces = db
    # spanset AND: both conditions on the SAME span
    got = _run(d, '{ span.http.method = "GET" && span.http.status_code = 500 }')
    assert got == _expect(
        traces,
        lambda res, sp: sp.attrs.get("http.method") == "GET"
        and sp.attrs.get("http.status_code") == 500,
    )


def test_tags_trace_level_semantics(db):
    """Tag search (unlike TraceQL) matches tags anywhere in the trace."""
    d, traces = db
    resp = d.search(TENANT, SearchRequest(tags={"service.name": "db", "http.method": "GET"}, limit=1000))

    def trace_pred(t):
        has_db = any(res.service_name == "db" for res, _, _ in t.all_spans())
        has_get = any(sp.attrs.get("http.method") == "GET" for _, _, sp in t.all_spans())
        return has_db and has_get

    assert {r.trace_id for r in resp.traces} == {tid.hex() for tid, t in traces if trace_pred(t)}


def test_query_nonexistent_prunes(db):
    d, traces = db
    assert _run(d, '{ span.nope = "nothing" }') == set()
    assert _run(d, '{ resource.service.name = "zzz-absent" }') == set()


# --------------------------------------------- regression: review findings


@pytest.fixture(scope="module")
def db2(tmp_path_factory):
    """Handcrafted traces for same-span / clamped-duration / escape cases."""
    from tempo_tpu.wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

    base = 1_700_000_000_000_000_000

    def mk(tid_byte, spans):
        tid = bytes([tid_byte]) * 16
        sps = []
        for i, (name, attrs, dur_ns) in enumerate(spans):
            sps.append(
                Span(
                    trace_id=tid,
                    span_id=bytes([i + 1]) * 8,
                    parent_span_id=b"" if i == 0 else bytes([1]) * 8,
                    name=name,
                    start_unix_nano=base,
                    end_unix_nano=base + dur_ns,
                    attrs=attrs,
                )
            )
        rs = ResourceSpans(
            resource=Resource(attrs={"service.name": "svc"}),
            scope_spans=[ScopeSpans(scope=Scope(), spans=sps)],
        )
        return tid, Trace(resource_spans=[rs])

    traces = [
        # t1: a and b on DIFFERENT spans, root name "root-a"
        mk(1, [("root-a", {"a": "v"}, 10_000), ("child", {"b": "v"}, 10_000)]),
        # t2: a and b on the SAME span
        mk(2, [("root-b", {"a": "v", "b": "v"}, 10_000)]),
        # t3: 50-minute span (dur_us clamps at ~35.8 min) + a short one
        mk(3, [("long-op", {}, 3000 * 10**9), ("short-op", {}, 5_000_000)]),
        # t4: newline in an attr value
        mk(4, [("esc", {"msg": "a\nb"}, 10_000)]),
    ]
    d = TempoDB(TempoDBConfig(wal_path=str(tmp_path_factory.mktemp("wal2"))), backend=MemBackend())
    d.write_block(TENANT, traces)
    return d, traces


def test_mixed_and_keeps_same_span_semantics(db2):
    """{spanA && spanB && traceC}: span conds must hold on ONE span even
    when a trace-level cond is ANDed in (normalize_tree grouping)."""
    d, _ = db2
    got = _run(d, '{ span.a = "v" && span.b = "v" }')
    assert got == {("\x02" * 16).encode("latin1").hex() if False else (bytes([2]) * 16).hex()}
    got = _run(d, '{ span.a = "v" && span.b = "v" && rootName = "root-b" }')
    assert got == {(bytes([2]) * 16).hex()}
    got = _run(d, '{ span.a = "v" && span.b = "v" && rootName = "root-a" }')
    assert got == set()


def test_clamped_duration_query(db2):
    """Durations past the int32-us clamp (~35.8 min) verify exactly."""
    d, _ = db2
    t3 = (bytes([3]) * 16).hex()
    assert _run(d, "{ duration > 40m }") == {t3}
    assert _run(d, "{ duration > 60m }") == set()
    assert _run(d, "{ duration >= 50m }") == {t3}
    # < past the clamp still finds the short spans (conservative + verify)
    assert t3 in _run(d, "{ duration < 45m }")


def test_string_escape_newline(db2):
    d, _ = db2
    assert _run(d, '{ span.msg = "a\\nb" }') == {(bytes([4]) * 16).hex()}
    assert _run(d, '{ span.msg = "a\\tb" }') == set()


def test_wellknown_resource_exists(db2):
    d, _ = db2
    # existence is `!= nil` (reference semantics: a BARE field is
    # boolean truthiness, so `{ resource.service.name }` matches nothing)
    assert len(_run(d, "{ resource.service.name != nil }")) == 4
    assert _run(d, "{ resource.k8s.pod.name != nil }") == set()
    assert _run(d, "{ resource.service.name }") == set()


def test_pipeline_aggregates_parse_and_eval():
    """`{...} | count()/avg()/... op N` scalar filters (expr.y pipeline
    stages), evaluated exactly on the wire model."""
    from tempo_tpu.traceql.ast import Pipeline
    from tempo_tpu.traceql.hosteval import trace_matches
    from tempo_tpu.traceql.parser import parse
    from tempo_tpu.wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

    def mk_trace(durs_ms, svc="api"):
        spans = [
            Span(trace_id=b"\x01" * 16, span_id=bytes([i] * 8), name=f"op{i}",
                 start_unix_nano=10**18, end_unix_nano=10**18 + d * 10**6,
                 attrs={"n": i})
            for i, d in enumerate(durs_ms)
        ]
        return Trace(resource_spans=[ResourceSpans(
            resource=Resource(attrs={"service.name": svc}),
            scope_spans=[ScopeSpans(scope=Scope(), spans=spans)])])

    q = parse("{ true } | count() > 2")
    assert isinstance(q, Pipeline)
    assert trace_matches(q, mk_trace([1, 2, 3]))
    assert not trace_matches(q, mk_trace([1, 2]))

    # aggregate over the filtered spanset, not all spans
    q = parse('{ duration > 5ms } | count() = 2')
    assert trace_matches(q, mk_trace([1, 10, 20]))
    assert not trace_matches(q, mk_trace([10, 20, 30]))

    q = parse("{ true } | avg(duration) >= 10ms")
    assert trace_matches(q, mk_trace([5, 15]))
    assert not trace_matches(q, mk_trace([5, 5]))

    q = parse("{ true } | max(duration) < 10ms | min(duration) > 1ms")
    assert trace_matches(q, mk_trace([2, 9]))
    assert not trace_matches(q, mk_trace([2, 19]))

    q = parse("{ true } | sum(span.n) = 3")
    assert trace_matches(q, mk_trace([1, 1, 1]))  # n = 0+1+2

    # empty spansets never reach the pipeline (reference semantics):
    # the live and block paths must agree
    q = parse("{ duration > 1s } | count() < 1")
    assert not trace_matches(q, mk_trace([1, 2]))

    import pytest as _pytest
    from tempo_tpu.traceql.ast import ParseError
    for bad in ("{ true } | count(duration) > 1", "{ true } | avg() > 1",
                "{ true } | p99() > 1", '{ true } | count() > "x"',
                "{ true } | avg(name) > 0",
                "{ true } | max(status) = 2"):
        with _pytest.raises(ParseError):
            parse(bad)


def test_pipeline_aggregates_e2e_search(tmp_path):
    """Pipelines run through the full search path: device spanset
    prefilter + exact host aggregate verification."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal")), backend=MemBackend())
    traces = make_traces(30, seed=17, n_spans=5)  # 5 spans each
    few = make_traces(6, seed=18, n_spans=2)  # 2 spans each
    db.write_block("t", sorted(traces + few, key=lambda t: t[0]))

    resp = db.search("t", SearchRequest(query="{ true } | count() > 3", limit=100))
    assert {t.trace_id for t in resp.traces} == {tid.hex() for tid, _ in traces}
    resp = db.search("t", SearchRequest(query="{ true } | count() <= 2", limit=100))
    assert {t.trace_id for t in resp.traces} == {tid.hex() for tid, _ in few}
    db.close()


def test_structural_operators():
    """`{a} > {b}`, `>>`, `~`, `&&`, `||` between spansets."""
    from tempo_tpu.traceql.hosteval import trace_matches
    from tempo_tpu.traceql.parser import parse
    from tempo_tpu.wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

    def sp(name, sid, parent=b""):
        return Span(trace_id=b"\x01" * 16, span_id=sid, parent_span_id=parent,
                    name=name, start_unix_nano=10**18, end_unix_nano=10**18 + 10**6)

    # a -> b -> c, plus sibling d of b
    a, b, c, d = (bytes([i] * 8) for i in (1, 2, 3, 4))
    spans = [sp("a", a), sp("b", b, a), sp("c", c, b), sp("d", d, a)]
    tr = Trace(resource_spans=[ResourceSpans(
        resource=Resource(attrs={"service.name": "s"}),
        scope_spans=[ScopeSpans(scope=Scope(), spans=spans)])])

    assert trace_matches(parse('{ name = "a" } > { name = "b" }'), tr)
    assert not trace_matches(parse('{ name = "a" } > { name = "c" }'), tr)  # not direct
    assert trace_matches(parse('{ name = "a" } >> { name = "c" }'), tr)  # descendant
    assert not trace_matches(parse('{ name = "c" } >> { name = "a" }'), tr)
    assert trace_matches(parse('{ name = "b" } ~ { name = "d" }'), tr)  # siblings
    assert not trace_matches(parse('{ name = "b" } ~ { name = "c" }'), tr)
    assert trace_matches(parse('{ name = "a" } && { name = "d" }'), tr)
    assert not trace_matches(parse('{ name = "a" } && { name = "zzz" }'), tr)
    assert trace_matches(parse('{ name = "zzz" } || { name = "d" }'), tr)
    # structural + pipeline: children of a == {b, d}
    assert trace_matches(parse('{ name = "a" } > { true } | count() = 2'), tr)
    assert not trace_matches(parse('{ name = "a" } > { true } | count() > 2'), tr)


def test_structural_e2e_search(tmp_path):
    """Structural queries through the full block search path: device
    leaf prefilter + exact host relation verification."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest
    from tempo_tpu.wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

    def mk(tid_byte, parent_child):
        tid = bytes([tid_byte]) * 16
        spans = []
        for i, (name, sid_b, parent_b) in enumerate(parent_child):
            spans.append(Span(
                trace_id=tid, span_id=bytes([sid_b] * 8) if isinstance(sid_b, int) else sid_b,
                parent_span_id=bytes([parent_b] * 8) if parent_b else b"",
                name=name, start_unix_nano=10**18 + i, end_unix_nano=10**18 + 10**6))
        return tid, Trace(resource_spans=[ResourceSpans(
            resource=Resource(attrs={"service.name": "s"}),
            scope_spans=[ScopeSpans(scope=Scope(), spans=spans)])])

    # t1: gateway -> db (direct); t2: gateway -> mid -> db; t3: db alone
    t1 = mk(1, [("gateway", 1, 0), ("db", 2, 1)])
    t2 = mk(2, [("gateway", 1, 0), ("mid", 2, 1), ("db", 3, 2)])
    t3 = mk(3, [("db", 1, 0)])
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal")), backend=MemBackend())
    db.write_block("t", sorted([t1, t2, t3], key=lambda t: t[0]))

    def search(q):
        return {t.trace_id for t in db.search("t", SearchRequest(query=q, limit=10)).traces}

    assert search('{ name = "gateway" } > { name = "db" }') == {t1[0].hex()}
    assert search('{ name = "gateway" } >> { name = "db" }') == {t1[0].hex(), t2[0].hex()}
    assert search('{ name = "gateway" } && { name = "mid" }') == {t2[0].hex()}
    assert search('{ name = "mid" } || { name = "db" }') == {t1[0].hex(), t2[0].hex(), t3[0].hex()}
    db.close()


def test_structural_precedence_and_twins():
    """expr.y precedence: > binds tighter than && ; ~ matches twin
    same-name siblings; zero-filled parents are not siblings."""
    from tempo_tpu.traceql.ast import SpansetOp
    from tempo_tpu.traceql.hosteval import trace_matches
    from tempo_tpu.traceql.parser import parse
    from tempo_tpu.wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

    q = parse('{ name = "a" } && { name = "b" } > { name = "c" }')
    assert isinstance(q, SpansetOp) and q.op == "&&"
    assert isinstance(q.rhs, SpansetOp) and q.rhs.op == ">"  # b > c under &&

    def sp(name, sid, parent=b""):
        return Span(trace_id=b"\x01" * 16, span_id=sid, parent_span_id=parent,
                    name=name, start_unix_nano=10**18, end_unix_nano=10**18 + 10**6)

    p, x1, x2 = bytes([9] * 8), bytes([1] * 8), bytes([2] * 8)
    twins = Trace(resource_spans=[ResourceSpans(
        resource=Resource(attrs={"service.name": "s"}),
        scope_spans=[ScopeSpans(scope=Scope(), spans=[
            sp("par", p), sp("x", x1, p), sp("x", x2, p)])])])
    assert trace_matches(parse('{ name = "x" } ~ { name = "x" }'), twins)

    roots = Trace(resource_spans=[ResourceSpans(
        resource=Resource(attrs={"service.name": "s"}),
        scope_spans=[ScopeSpans(scope=Scope(), spans=[
            sp("a", x1, b"\x00" * 8), sp("b", x2, b"\x00" * 8)])])])
    assert not trace_matches(parse('{ name = "a" } ~ { name = "b" }'), roots)


def test_parenthesized_spanset_expressions():
    from tempo_tpu.traceql.ast import SpansetOp
    from tempo_tpu.traceql.parser import parse

    q = parse('({ name = "a" } || { name = "b" }) > { name = "c" }')
    assert isinstance(q, SpansetOp) and q.op == ">"
    assert isinstance(q.lhs, SpansetOp) and q.lhs.op == "||"
    # without parens, || binds looser: a || (b > c)
    q2 = parse('{ name = "a" } || { name = "b" } > { name = "c" }')
    assert q2.op == "||" and q2.rhs.op == ">"


def test_grammar_tail_execution():
    """Execution semantics of the expr.y grammar tail: parent scope,
    childCount, field arithmetic, field-to-field compares, nil, bare
    fields, by()/coalesce(), scalar-pipeline expressions."""
    from tempo_tpu.traceql.hosteval import trace_matches
    from tempo_tpu.traceql.parser import parse
    from tempo_tpu.wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

    def sp(name, sid, parent=b"", dur_ms=10, attrs=None):
        return Span(trace_id=b"\x01" * 16, span_id=sid, parent_span_id=parent,
                    name=name, start_unix_nano=10**18,
                    end_unix_nano=10**18 + dur_ms * 10**6, attrs=attrs or {})

    a, b, c, d = (bytes([i] * 8) for i in (1, 2, 3, 4))
    spans = [
        sp("root", a, dur_ms=100, attrs={"x": 10, "flag": True, "svc": "api"}),
        sp("mid", b, a, dur_ms=50, attrs={"x": 4, "y": 4}),
        sp("leaf", c, b, dur_ms=5, attrs={"x": 7}),
        sp("leaf", d, b, dur_ms=5, attrs={"x": 3, "flag": False}),
    ]
    tr = Trace(resource_spans=[ResourceSpans(
        resource=Resource(attrs={"service.name": "s", "env": "prod"}),
        scope_spans=[ScopeSpans(scope=Scope(), spans=spans)])])

    m = lambda q: trace_matches(parse(q), tr)  # noqa: E731

    # childCount: root has 1 child (mid), mid has 2
    assert m("{ childCount = 2 }")
    assert m("{ 1 = childCount }")
    assert not m("{ childCount > 2 }")
    # parent intrinsic and parent-scoped attrs
    assert m("{ parent = nil }")  # the root
    assert m('{ parent.name = "mid" }')  # parent's intrinsic name
    assert m("{ parent.x = 4 }")  # leaf's parent is mid (x=4)
    assert m("{ parent.span.x = 10 }")  # mid's parent is root
    assert m('{ parent.resource.env = "prod" }')
    assert not m("{ parent.x = 99 }")
    # field arithmetic + field-to-field
    assert m("{ .x + 1 = 5 }")  # mid: 4+1
    assert m("{ .x * 2 = 20 }")  # root
    assert m("{ .x ^ 2 = 49 }")  # leaf: 7^2
    assert m("{ .x = .y }")  # mid: x=4, y=4
    assert not m("{ .x + .y = 999 }")
    assert m("{ -.x = -10 }")
    assert m("{ duration > 40ms && .x = 4 }")
    # nil and bare fields
    assert m("{ .flag }")  # root's flag is true
    assert not m("{ .y && .x = 10 }")  # y absent on root
    assert m("{ .y != nil }")  # mid has y
    assert m("{ .missing = nil }")
    assert not m("{ .x = nil }")
    # by()/coalesce(): group by name -> 2 leaf spans in one group
    assert m('{ true } | by(name) | count() = 2')
    assert m('{ true } | by(.x) | count() = 1 | coalesce() | count() = 4')
    assert not m('{ true } | by(name) | count() = 3')
    # scalar-pipeline expressions
    assert m('({ name =~ "leaf.*" } | count()) + ({ name = "mid" } | count()) = 3')
    assert m('({ true } | count()) > ({ name = "mid" } | count())')
    assert m('{ true } | count() + count() = 8')
    assert m('max(duration) - min(duration) > 90ms')
    assert m('avg(.x) = 6')  # (10+4+7+3)/4


def test_structural_device_pruning(tmp_path):
    """Pure structural queries compile to exact ('struct', ...) span
    trees over span.parent_idx: needs_verify is OFF, and the host and
    device engines agree with the wire-model evaluator on every block
    trace (VERDICT r3 item 3; reference ops:
    pkg/traceql/enum_operators.go OpSpansetChild/Descendant/Sibling)."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest, _plan_for_block, search_block
    from tempo_tpu.traceql.hosteval import trace_matches
    from tempo_tpu.traceql.parser import parse
    from tempo_tpu.util.testdata import make_traces

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w")), backend=MemBackend())
    traces = make_traces(60, seed=33, n_spans=10)
    db.write_block(TENANT, traces)
    blk = db.open_block(db.blocklist.metas(TENANT)[0])

    queries = [
        '{ name = "GET /api" } > { true }',
        '{ true } > { name = "db.query" }',
        '{ name = "GET /api" } >> { name = "db.query" }',
        '{ name = "GET /api" } ~ { true }',
        '{ name = "GET /api" } > { true } >> { name = "db.query" }',
    ]
    for q in queries:
        p = _plan_for_block(blk, SearchRequest(query=q))
        # '~' trees keep verification (orphan-sibling over-match); the
        # parent/descendant relations are exact with no verify
        want_verify = "~" in q
        assert p.prune or (p.has_struct and p.needs_verify == want_verify), (q, p)
        want = {tid.hex() for tid, t in traces if trace_matches(parse(q), t)}
        got_h = {t.trace_id for t in
                 search_block(blk, SearchRequest(query=q, limit=1000), mode="host").traces}
        got_d = {t.trace_id for t in
                 search_block(blk, SearchRequest(query=q, limit=1000), mode="device").traces}
        assert got_h == want, (q, len(got_h), len(want))
        assert got_d == want, (q, len(got_d), len(want))

    # mixed structural (trace-level cond inside) still verifies
    p = _plan_for_block(blk, SearchRequest(query='{ traceDuration > 1ms } > { true }'))
    assert p.needs_verify and not p.has_struct


def test_structural_orphan_siblings(tmp_path):
    """Spans sharing a parent ID whose span was never ingested (orphans)
    are still siblings; the struct kernel over-matches them and host
    verification keeps the result exact."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest, search_block
    from tempo_tpu.traceql.hosteval import trace_matches
    from tempo_tpu.traceql.parser import parse
    from tempo_tpu.wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

    missing = b"\xaa" * 8
    spans = [
        Span(trace_id=b"\x07" * 16, span_id=bytes([i] * 8), parent_span_id=missing,
             name=n, start_unix_nano=10**18, end_unix_nano=10**18 + 10**6)
        for i, n in ((1, "a"), (2, "b"))
    ]
    tr = Trace(resource_spans=[ResourceSpans(
        resource=Resource(attrs={"service.name": "s"}),
        scope_spans=[ScopeSpans(scope=Scope(), spans=spans)])])
    q = '{ name = "a" } ~ { name = "b" }'
    assert trace_matches(parse(q), tr)

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w")), backend=MemBackend())
    db.write_block(TENANT, [(b"\x07" * 16, tr)])
    blk = db.open_block(db.blocklist.metas(TENANT)[0])
    for mode in ("host", "device"):
        got = search_block(blk, SearchRequest(query=q, limit=10), mode=mode)
        assert len(got.traces) == 1, mode
