"""Pipelined concurrent compaction (db/compact_pipeline).

The load-bearing guarantees, each with its own test:
  * differential: pipelined output blocks are BIT-identical to a
    sequential compact() run -- multi-output jobs, with trace-id
    collisions across inputs;
  * crash/ordering: a failure injected between output writes leaves no
    input mark_compacted, nothing visible to blocklist polling, and a
    re-run converges;
  * compression matrix: the pipeline runs on the zlib zstd-shim
    (images without the zstandard wheel) and with the native
    gather_runs/dict_union helpers unavailable;
  * scheduling: per-tenant round-robin admission, the host-RAM
    admission gate never deadlocks, and the service-level sweep
    (TEMPO_COMPACT_CONCURRENCY) updates the blocklist per job.
Plus the select_jobs regression: an input block larger than
max_block_bytes must cut the batch on its own, never batch with more.
"""

from __future__ import annotations

import shutil

import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.backend.base import DoesNotExist
from tempo_tpu.backend.local import LocalBackend
from tempo_tpu.block.builder import BLOOM_PREFIX, build_block_from_traces
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.db import compactor as comp
from tempo_tpu.db.blocklist import Poller
from tempo_tpu.db.compact_pipeline import CompactionPipeline
from tempo_tpu.db.compactor import CompactionJob, CompactorConfig, compact
from tempo_tpu.util.kerneltel import TEL
from tempo_tpu.util.testdata import make_traces

TENANT = "t1"


def _meta(size: int, level: int = 0, end_ns: int = 1_700_000_000 * 10**9):
    from tempo_tpu.block.meta import BlockMeta

    m = BlockMeta.new(TENANT)
    m.size_bytes = size
    m.compaction_level = level
    m.end_time_unix_nano = end_ns
    return m


# ------------------------------------------------------ select_jobs fix
def test_select_jobs_oversized_block_cuts_batch():
    """Regression: a single input block larger than max_block_bytes used
    to be admitted (the size guard only fired once the batch was
    non-empty) and then batched with further blocks."""
    cfg = CompactorConfig(max_block_bytes=100, min_input_blocks=2,
                          max_input_blocks=10, active_window_s=10**12)
    big = _meta(500)
    smalls = [_meta(10) for _ in range(3)]
    jobs = comp.select_jobs(TENANT, [big] + smalls, cfg)
    assert jobs, "small blocks must still batch"
    picked = {m.block_id for j in jobs for m in j.blocks}
    assert big.block_id not in picked
    assert picked == {m.block_id for m in smalls}
    # all-oversized group: no job at all (merging any two would exceed)
    jobs2 = comp.select_jobs(TENANT, [_meta(500), _meta(600)], cfg)
    assert jobs2 == []


# ------------------------------------------------------------- helpers
def _build_inputs(backend, n_blocks: int = 4, n_traces: int = 30,
                  collide: bool = True) -> list:
    """n_blocks small blocks; with collide=True consecutive blocks share
    some trace ids (replicated partial traces -- the collision path)."""
    metas = []
    for b in range(n_blocks):
        traces = make_traces(n_traces, seed=100 + b, n_spans=4)
        if collide and b:
            prev = make_traces(n_traces, seed=100 + b - 1, n_spans=4)
            traces = sorted(traces[:-3] + prev[:3], key=lambda p: p[0])
        metas.append(build_block_from_traces(backend, TENANT, traces))
    return metas


def _output_objects(backend, meta) -> dict[str, bytes]:
    out = {}
    for name in ("data.vtpu", "dict.vtpu"):
        out[name] = backend.read(TENANT, meta.block_id, name)
    for s in range(meta.bloom_shards):
        out[f"{BLOOM_PREFIX}{s}"] = backend.read(
            TENANT, meta.block_id, f"{BLOOM_PREFIX}{s}")
    return out


# ---------------------------------------------------------- differential
def test_pipeline_bit_identical_to_sequential(tmp_path):
    """Multi-output jobs with cross-block id collisions: every output
    object (data, dictionary, bloom shards) byte-equal between the
    sequential driver and the pipelined executor."""
    a = LocalBackend(str(tmp_path / "a"))
    metas = _build_inputs(a, n_blocks=4)
    shutil.copytree(str(tmp_path / "a"), str(tmp_path / "b"))
    b = LocalBackend(str(tmp_path / "b"))

    # tiny target -> several output blocks per job; concat disabled so
    # the columnar merge (the pipelined stage split) is what runs
    cfg = CompactorConfig(concat_small_input_bytes=0, target_block_bytes=16000)
    jobs_a = [CompactionJob(TENANT, metas[:2]), CompactionJob(TENANT, metas[2:])]
    seq = [compact(a, j, cfg) for j in jobs_a]
    assert any(len(r.new_blocks) > 1 for r in seq), "want a multi-output job"

    jobs_b = [CompactionJob(TENANT, metas[:2]), CompactionJob(TENANT, metas[2:])]
    outs = CompactionPipeline(b, cfg, concurrency=4).run({TENANT: jobs_b})
    assert [o.error for o in outs] == [None, None]

    for rs, oc in zip(seq, outs):
        rp = oc.result
        assert rp.traces_out == rs.traces_out and rp.spans_out == rs.spans_out
        assert len(rp.new_blocks) == len(rs.new_blocks)
        for ms, mp in zip(rs.new_blocks, rp.new_blocks):
            assert _output_objects(a, ms) == _output_objects(b, mp)


# -------------------------------------------------------- crash/ordering
def test_pipeline_crash_between_outputs_is_invisible(tmp_path, monkeypatch):
    """Fail the SECOND output write of a multi-output job: no input may
    be mark_compacted, no partial output may surface to blocklist
    polling, and an unpatched re-run converges."""
    import tempo_tpu.db.columnar_compact as cc

    backend = MemBackend()
    metas = _build_inputs(backend, n_blocks=3, collide=False)
    cfg = CompactorConfig(concat_small_input_bytes=0, target_block_bytes=16000,
                          prefetch_depth=0)
    job = CompactionJob(TENANT, list(metas))

    real_write = cc.write_block
    calls = {"n": 0}

    def boom(*args, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise OSError("injected: disk died between outputs")
        return real_write(*args, **kw)

    monkeypatch.setattr(cc, "write_block", boom)
    outs = CompactionPipeline(backend, cfg, concurrency=2).run(
        {TENANT: [job]})
    assert len(outs) == 1 and isinstance(outs[0].error, OSError)
    assert calls["n"] >= 2, "the job must have attempted multiple outputs"

    # no input consumed, nothing new visible
    for m in metas:
        assert not backend.has_object(TENANT, m.block_id, "meta.compacted.json")
    polled, compacted = Poller(backend, build_index=False).poll()
    assert {m.block_id for m in polled[TENANT]} == {m.block_id for m in metas}
    assert not compacted.get(TENANT)

    # re-run (no fault) converges
    monkeypatch.setattr(cc, "write_block", real_write)
    outs2 = CompactionPipeline(backend, cfg, concurrency=2).run(
        {TENANT: [CompactionJob(TENANT, list(metas))]})
    assert outs2[0].error is None
    res = outs2[0].result
    assert len(res.new_blocks) >= 2
    polled2, _ = Poller(backend, build_index=False).poll()
    live = {m.block_id for m in polled2[TENANT] if not m.compacted_at_unix}
    assert {m.block_id for m in res.new_blocks} <= live
    for m in metas:
        assert backend.has_object(TENANT, m.block_id, "meta.compacted.json")


# ---------------------------------------------------- compression matrix
def test_pipeline_on_zstd_shim_and_without_native(tmp_path, monkeypatch):
    """CI images carry no zstandard wheel and may lack the native
    helpers: pin the zlib shim codec AND the pure-Python fallbacks
    (gather_runs -> numpy indexing, dict_union -> numpy merge, fused
    remap off) and prove the pipeline still matches sequential output
    byte-for-byte."""
    import tempo_tpu.block.colio as colio
    import tempo_tpu.block.dictionary as dictionary
    import tempo_tpu.native as native
    from tempo_tpu.util import zstdshim

    monkeypatch.setattr(colio, "zstandard", zstdshim)
    monkeypatch.setattr(dictionary, "zstandard", zstdshim)
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", True)
    assert not native.available()

    a = LocalBackend(str(tmp_path / "a"))
    metas = _build_inputs(a, n_blocks=4)
    shutil.copytree(str(tmp_path / "a"), str(tmp_path / "b"))
    b = LocalBackend(str(tmp_path / "b"))

    cfg = CompactorConfig(concat_small_input_bytes=0, target_block_bytes=16000)
    jobs = lambda ms: [CompactionJob(TENANT, ms[:2]), CompactionJob(TENANT, ms[2:])]  # noqa: E731
    seq = [compact(a, j, cfg) for j in jobs(metas)]
    outs = CompactionPipeline(b, cfg, concurrency=3).run({TENANT: jobs(metas)})
    assert [o.error for o in outs] == [None, None]
    for rs, oc in zip(seq, outs):
        for ms, mp in zip(rs.new_blocks, oc.result.new_blocks):
            assert _output_objects(a, ms) == _output_objects(b, mp)
    # the outputs are readable (shim round-trip, not just equal garbage)
    from tempo_tpu.block.versioned import open_block_versioned

    blk = open_block_versioned(b, outs[0].result.new_blocks[0])
    assert blk.materialize_traces([0])[0].span_count() > 0


def test_pipeline_falls_back_when_assemble_refuses_late(tmp_path, monkeypatch):
    """UnsupportedColumnar can surface AFTER planning (e.g. an unknown
    column family in _assemble): the pipeline must fall back to the
    wire merge like the sequential driver, not strand the job as a
    permanent error."""
    import tempo_tpu.db.columnar_compact as cc

    backend = MemBackend()
    metas = _build_inputs(backend, n_blocks=2, collide=False)
    cfg = CompactorConfig(concat_small_input_bytes=0, prefetch_depth=0)

    def refuse(plan, cfg_):
        raise cc.UnsupportedColumnar("late refusal (fixture)")
        yield  # noqa: unreachable -- keeps this a generator like the real one

    monkeypatch.setattr(cc, "iter_outputs", refuse)
    outs = CompactionPipeline(backend, cfg, concurrency=2).run(
        {TENANT: [CompactionJob(TENANT, list(metas))]})
    assert outs[0].error is None, outs[0].error
    res = outs[0].result
    assert res.new_blocks and res.traces_out > 0
    for m in metas:
        assert backend.has_object(TENANT, m.block_id, "meta.compacted.json")


def test_pipeline_falls_back_when_plan_refuses(tmp_path, monkeypatch):
    """Plan-stage refusal (e.g. differing column sets) must route the
    already-fetched job straight to the wire merge -- once, not via a
    second full fetch+decode through compact()."""
    import tempo_tpu.db.columnar_compact as cc

    backend = MemBackend()
    metas = _build_inputs(backend, n_blocks=2, collide=False)
    cfg = CompactorConfig(concat_small_input_bytes=0, prefetch_depth=0)

    real_plan = cc.plan_columnar
    plan_calls = {"n": 0}

    def refuse(*a, **kw):
        plan_calls["n"] += 1
        raise cc.UnsupportedColumnar("differing column sets (fixture)")

    monkeypatch.setattr(cc, "plan_columnar", refuse)
    outs = CompactionPipeline(backend, cfg, concurrency=2).run(
        {TENANT: [CompactionJob(TENANT, list(metas))]})
    monkeypatch.setattr(cc, "plan_columnar", real_plan)
    assert outs[0].error is None, outs[0].error
    assert plan_calls["n"] == 1, "fallback must not re-plan through compact()"
    res = outs[0].result
    assert res.new_blocks and res.traces_out > 0
    for m in metas:
        assert backend.has_object(TENANT, m.block_id, "meta.compacted.json")


def test_select_jobs_oversized_does_not_cut_neighbors():
    """Skipping an oversized block must not flush the batch in progress:
    its smaller neighbors still compact together."""
    cfg = CompactorConfig(max_block_bytes=100, min_input_blocks=2,
                          max_input_blocks=10, active_window_s=10**12)
    metas = [_meta(10), _meta(500), _meta(20)]
    jobs = comp.select_jobs(TENANT, metas, cfg)
    assert len(jobs) == 1
    assert {m.block_id for m in jobs[0].blocks} == {
        metas[0].block_id, metas[2].block_id}


# ----------------------------------------------------------- scheduling
def test_round_robin_interleaves_tenants():
    pipe = CompactionPipeline(MemBackend(), CompactorConfig())
    j = lambda t, i: CompactionJob(t, [_meta(10)], hash=f"{t}-{i}")  # noqa: E731
    tickets = pipe._round_robin({
        "a": [j("a", 0), j("a", 1), j("a", 2)],
        "b": [j("b", 0)],
        "c": [j("c", 0), j("c", 1)],
    })
    assert [t.tenant for t in tickets] == ["a", "b", "c", "a", "c", "a"]


def test_admission_gate_tiny_budget_never_deadlocks(tmp_path):
    """A budget smaller than any single job must still admit one at a
    time (serial) and finish every job."""
    backend = LocalBackend(str(tmp_path / "s"))
    metas = _build_inputs(backend, n_blocks=4, collide=False)
    cfg = CompactorConfig(concat_small_input_bytes=0,
                          pipeline_mem_budget_bytes=1)
    jobs = [CompactionJob(TENANT, metas[:2]), CompactionJob(TENANT, metas[2:])]
    outs = CompactionPipeline(backend, cfg, concurrency=4).run({TENANT: jobs})
    assert [o.error for o in outs] == [None, None]


def test_compact_tenants_updates_blocklist_and_telemetry(tmp_path):
    """The TempoDB-level concurrent sweep: per-job blocklist updates land
    (inputs gone from live, outputs present), and the kerneltel
    compaction section advances."""
    mark = TEL.compaction_stats()
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal")),
                 backend=MemBackend())
    db.cfg.compaction.concurrency = 3
    db.cfg.compaction.concat_small_input_bytes = 0
    db.cfg.compaction.min_input_blocks = 2
    for t in ("ta", "tb"):
        for b in range(2):
            db.blocklist.update(t, add=[build_block_from_traces(
                db.backend, t, make_traces(20, seed=7 * b + (t == "tb"),
                                           n_spans=3))])
    outcomes = db.compact_tenants()
    assert [oc.error for oc in outcomes] == [None, None]
    assert {oc.tenant for oc in outcomes} == {"ta", "tb"}
    for t in ("ta", "tb"):
        live = db.blocklist.metas(t)
        assert all(m.compaction_level >= 1 for m in live)
        assert db.blocklist.compacted_metas(t)
    now = TEL.compaction_stats()
    assert now["jobs"] - mark["jobs"] == 2
    assert now["runs"] - mark["runs"] == 1
    assert now["stage_seconds"], "per-stage histogram section populated"
    db.close()


def test_service_sweep_uses_pipeline(tmp_path, monkeypatch):
    """services/compactor routes through the pipeline when
    TEMPO_COMPACT_CONCURRENCY > 1 and keeps its stats/retention
    behavior."""
    from tempo_tpu.services.compactor import Compactor

    monkeypatch.setenv("TEMPO_COMPACT_CONCURRENCY", "4")
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal")),
                 backend=MemBackend())
    db.cfg.compaction.concat_small_input_bytes = 0
    db.cfg.compaction.min_input_blocks = 2
    db.cfg.compaction.retention_s = 10**9  # keep retention out of the sweep
    db.blocklist.update(TENANT, add=[
        build_block_from_traces(db.backend, TENANT, make_traces(15, seed=s))
        for s in (1, 2)])
    svc = Compactor(db)
    svc.run_once()
    assert svc.stats.errors == []
    assert svc.stats.blocks_compacted == 2
    assert all(m.compaction_level >= 1 for m in db.blocklist.metas(TENANT))
    db.close()


def test_local_backend_copy_object_hardlink(tmp_path):
    """The concat path's backend-side copy: content equal, and a
    subsequent overwrite of the SOURCE (tmp+rename) must not mutate the
    copy (immutability via inode sharing is safe only because writes
    replace directory entries)."""
    be = LocalBackend(str(tmp_path / "s"))
    be.write(TENANT, "blk-a", "data.vtpu", b"payload-1")
    n = be.copy_object(TENANT, "blk-a", "data.vtpu", "blk-b")
    assert n == len(b"payload-1")
    assert be.read(TENANT, "blk-b", "data.vtpu") == b"payload-1"
    be.write(TENANT, "blk-a", "data.vtpu", b"payload-2-replaced")
    assert be.read(TENANT, "blk-b", "data.vtpu") == b"payload-1"
    with pytest.raises(DoesNotExist):
        be.copy_object(TENANT, "blk-a", "missing", "blk-b")
