"""util/slo: multi-window burn-rate engine + the app's /status/slo."""

import json
import socket
import urllib.parse
import urllib.request

from tempo_tpu.util.metrics import Counter, Histogram
from tempo_tpu.util.slo import (
    FAST_BURN,
    Objective,
    SLOEngine,
    counter_sli,
    histogram_sli,
)


def _avail(counter: Counter):
    return counter_sli(counter,
                       good=lambda l: 'outcome="ok"' in l,
                       bad=lambda l: 'outcome="error"' in l)


def test_burn_rate_window_differencing():
    """Burn = windowed error rate / budget, differenced against the
    newest sample at-or-before the window start; partial windows fall
    back to the oldest sample."""
    c = Counter("t_total")
    eng = SLOEngine(windows=(("5m", 300), ("1h", 3600)))
    eng.register(Objective("o", "availability", target=0.99, sli=_avail(c)))

    c.inc(100, labels='outcome="ok"')
    eng.evaluate(now=1000.0)  # baseline: 100 good, 0 bad

    # 50 good + 50 bad land before t=1200
    c.inc(50, labels='outcome="ok"')
    c.inc(50, labels='outcome="error"')
    st = eng.evaluate(now=1200.0)
    b = st["objectives"]["o"]["burn_rates"]
    # both windows are partial -> ref is the baseline: err 50/100 = 0.5,
    # budget 0.01 -> burn 50
    assert b["5m"] == 50.0 and b["1h"] == 50.0

    # much later, nothing new: the 5m window ref is now the t=1200
    # sample (delta 0 -> burn 0); the 1h window still sees the burn
    st = eng.evaluate(now=1600.0)
    b = st["objectives"]["o"]["burn_rates"]
    assert b["5m"] == 0.0
    assert b["1h"] == 50.0


def test_no_traffic_is_not_an_outage():
    c = Counter("t_total")
    eng = SLOEngine()
    eng.register(Objective("o", "availability", target=0.999, sli=_avail(c)))
    st = eng.evaluate(now=10.0)
    st = eng.evaluate(now=400.0)
    assert st["objectives"]["o"]["burn_rates"]["5m"] == 0.0
    assert st["verdict"] == "ok"


def test_counter_sli_excludes_shed():
    """429 sheds are neither good nor bad: the availability SLI must
    not move when the QoS budget refuses work."""
    c = Counter("t_total")
    sli = _avail(c)
    c.inc(10, labels='outcome="ok"')
    c.inc(999, labels='outcome="shed"')
    assert sli() == (10.0, 0.0)
    c.inc(2, labels='outcome="error"')
    assert sli() == (10.0, 2.0)


def test_histogram_sli_threshold_on_bucket_edges():
    h = Histogram("lat_seconds", buckets=(0.1, 0.5, 1.0))
    sli = histogram_sli(h, 0.5)
    h.observe(0.05, 'op="a"')   # <= 0.1 bucket: good
    h.observe(0.4, 'op="a"')    # <= 0.5 bucket: good
    h.observe(0.9, 'op="a"')    # <= 1.0 bucket: bad (over threshold)
    h.observe(7.0, 'op="a"')    # overflow: bad
    assert sli() == (2.0, 2.0)
    # label filtering
    h.observe(0.05, 'op="b"')
    only_b = histogram_sli(h, 0.5, labels_pred=lambda l: 'op="b"' in l)
    assert only_b() == (1.0, 0.0)


def test_verdict_multiwindow_pairs():
    v = SLOEngine._verdict
    hot = FAST_BURN + 1
    assert v({"5m": hot, "1h": hot, "6h": 0.0}) == "critical"
    # fast window spiking alone (recovered burst) does NOT page
    assert v({"5m": hot, "1h": 0.5, "6h": 0.5}) == "ok"
    assert v({"5m": 0.0, "1h": 7.0, "6h": 7.0}) == "warning"
    assert v({"5m": 0.1, "1h": 0.1, "6h": 0.1}) == "ok"


def test_sli_error_does_not_kill_the_plane():
    eng = SLOEngine()
    eng.register(Objective("broken", "availability", 0.99,
                           sli=lambda: 1 / 0))
    c = Counter("t_total")
    c.inc(5, labels='outcome="ok"')
    eng.register(Objective("fine", "availability", 0.99, sli=_avail(c)))
    st = eng.evaluate(now=1.0)
    assert "error" in st["objectives"]["broken"]
    assert st["objectives"]["fine"]["verdict"] == "ok"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_app_status_slo_and_metrics(tmp_path):
    """/status/slo serves every default objective, goes critical when
    the availability SLI burns, and the burn gauges ship on /metrics
    (strict OpenMetrics)."""
    from test_observability import parse_openmetrics_strict

    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.util.kerneltel import TEL

    cfg = AppConfig(storage_path=str(tmp_path / "store"),
                    http_port=_free_port(), compaction_cycle_s=9999,
                    ingester=IngesterConfig(flush_check_period_s=9999))
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    base = f"http://127.0.0.1:{cfg.http_port}"
    try:
        urllib.request.urlopen(
            base + "/api/search?tags=service.name%3Dnope&limit=5",
            timeout=30).read()
        st = json.load(urllib.request.urlopen(base + "/status/slo",
                                              timeout=10))
        assert st["verdict"] == "ok"
        assert {"read-availability", "latency-traces", "latency-search",
                "latency-search_stream", "latency-metrics",
                "live-freshness"} <= set(st["objectives"])
        av = st["objectives"]["read-availability"]
        # totals are process-cumulative (other tests may have recorded
        # outcomes); the verdict is delta-based over THIS app's life
        assert av["good_total"] >= 1

        # burn the availability budget: errors recorded at the same
        # chokepoint the frontend uses
        for _ in range(40):
            TEL.record_query("search", 0.01, outcome="error")
        st = json.load(urllib.request.urlopen(base + "/status/slo",
                                              timeout=10))
        assert st["objectives"]["read-availability"]["verdict"] == "critical"
        assert st["verdict"] == "critical"
        for w in ("5m", "1h", "6h"):
            assert (st["objectives"]["read-availability"]["burn_rates"][w]
                    > FAST_BURN)

        text = urllib.request.urlopen(base + "/metrics",
                                      timeout=10).read().decode()
        fams = parse_openmetrics_strict(text)
        assert fams.get("tempo_slo_burn_rate") == "gauge"
        assert fams.get("tempo_slo_verdict") == "gauge"
        assert fams.get("tempo_query_outcomes") == "counter"
        assert 'objective="read-availability"' in text
    finally:
        app.stop()


def test_frontend_query_class_attribution(tmp_path):
    """Each query class lands under its own op label, sheds under
    outcome=shed: the attribution the SLO objectives read."""
    import urllib.error

    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.util.kerneltel import TEL

    cfg = AppConfig(storage_path=str(tmp_path / "store"),
                    http_port=_free_port(), compaction_cycle_s=9999,
                    ingester=IngesterConfig(flush_check_period_s=9999))
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    base = f"http://127.0.0.1:{cfg.http_port}"
    try:
        before = TEL.query_outcomes.snapshot()
        urllib.request.urlopen(
            base + "/api/search?tags=service.name%3Dx&limit=2",
            timeout=30).read()
        with urllib.request.urlopen(
                base + "/api/search?tags=service.name%3Dx&stream=true",
                timeout=30) as r:
            r.read()
        try:
            urllib.request.urlopen(base + f"/api/traces/{'ab' * 16}",
                                   timeout=30)
        except urllib.error.HTTPError as e:
            assert e.code == 404  # not-found is a SERVED query
        urllib.request.urlopen(
            base + "/api/metrics/query_range?q="
            + urllib.parse.quote("{ true } | rate()")
            + "&start=1&end=600&step=60", timeout=30).read()
        after = TEL.query_outcomes.snapshot()

        def delta(labels):
            return after.get(labels, 0) - before.get(labels, 0)

        for op in ("search", "search_stream", "traces", "metrics"):
            assert delta(f'op="{op}",outcome="ok"') >= 1, (op, after)
    finally:
        app.stop()
