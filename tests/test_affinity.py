"""Cache-affinity scheduling + per-tenant read QoS (services/frontend).

Block->querier affinity: jobs hash their lead block onto the cache-
domain ring and the dequeue prefers the owner, with a bounded steal
timeout so a dead owner never strands work. QoS: overrides-driven
per-tenant concurrency/byte budgets shed with 429. Both layers must
vanish exactly when disabled: affinity off (or one cache domain) is the
legacy head-of-queue dequeue, no overrides means no admission gate.
"""

import threading
import time
from dataclasses import replace
from types import SimpleNamespace

import pytest

from tempo_tpu.db.search import SearchRequest, SearchResponse
from tempo_tpu.services.frontend import (
    Frontend,
    RequestQueue,
    TooManyRequests,
    _Job,
)
from tempo_tpu.services.overrides import Limits, Overrides, QueryAdmission
from tempo_tpu.util.kerneltel import TEL

TENANT = "t-aff"


def _job(kind="search_blocks", key=None, batch_key=None, fn=None):
    return _Job(kind=kind, payload={}, fn=fn or (lambda: None), args=(),
                affinity_key=key, batch_key=batch_key)


class _StubQuerier:
    """Just enough querier for Frontend.search's search_recent leg:
    an empty blocklist and a configurable-latency live search."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.db = SimpleNamespace(
            blocklist=SimpleNamespace(metas=lambda tenant: []))

    def search_recent(self, tenant, req):
        if self.delay:
            time.sleep(self.delay)
        return SearchResponse()


def _dispatcher(**kw) -> Frontend:
    """Dispatcher-only frontend (no local workers): remote queriers are
    the only cache domains, exactly the multi-chip fleet shape."""
    kw.setdefault("n_workers", 0)
    kw.setdefault("affinity", True)
    return Frontend(_StubQuerier(), **kw)


def _attach(fe: Frontend, *workers: str) -> None:
    for w in workers:
        assert fe.poll_job(wait_s=0.01, worker_id=w) is None


def _owner_of(fe: Frontend, key: str) -> str:
    return fe._aff_ring.owner_of(key, instances=fe._affinity_members())


def _keys_by_owner(fe: Frontend, workers, n=64) -> dict:
    """A block id owned by each worker (the ring is deterministic, so
    scan candidate ids until every worker has one)."""
    out = {}
    for i in range(n):
        k = f"block-{i:04x}"
        o = _owner_of(fe, k)
        if o in workers and o not in out:
            out[o] = k
        if len(out) == len(workers):
            return out
    raise AssertionError("no key found for some worker")


# ------------------------------------------------------ queue-level claim


def test_queue_claim_owner_and_unowned_immediate():
    """A claimer takes its own jobs and placement-free jobs at once;
    a peer's job is deferred while the steal clock runs."""
    q = RequestQueue()
    mine, theirs, free = _job(key="b-mine"), _job(key="b-theirs"), _job()
    for j in (theirs, mine, free):
        q.enqueue(TENANT, j)

    def claim(tenant, job, now):
        if job.affinity_key is None:
            return "unowned"
        if job.affinity_key == "b-mine":
            return "own"
        return None  # peer's, clock running

    got = []
    for _ in range(2):
        item = q.dequeue(timeout=0.2, claim=claim)
        assert item is not None
        got.append(item[1])
    assert got == [mine, free]  # FIFO among claimable, peer's skipped
    assert mine.placement == "own" and free.placement == "unowned"
    # only the deferred job remains; this claimer cannot take it yet
    assert q.dequeue(timeout=0.05, claim=claim) is None
    assert theirs.placement == ""


def test_queue_claim_steal_after_timeout():
    """The steal clock is the job's queue age: once it expires the same
    claim call flips from defer to steal, without a fresh enqueue."""
    q = RequestQueue()
    j = _job(key="b-other")
    q.enqueue(TENANT, j)
    steal_s = 0.08

    def claim(tenant, job, now):
        age = now - job.queued_at
        return "steal" if age >= steal_s else None

    t0 = time.monotonic()
    item = q.dequeue(timeout=2.0, claim=claim)
    waited = time.monotonic() - t0
    assert item is not None and item[1] is j
    assert j.placement == "steal"
    # the dequeue's periodic re-check fired the clock, not a notify
    assert steal_s <= waited < 1.0


def test_queue_claim_none_is_legacy_fifo():
    """claim=None must be byte-for-byte the legacy dequeue: strict FIFO
    within a tenant, affinity metadata ignored."""
    q = RequestQueue()
    jobs = [_job(key=f"b{i}") for i in range(4)]
    for j in jobs:
        q.enqueue(TENANT, j)
    out = [q.dequeue(timeout=0.1)[1] for _ in range(4)]
    assert out == jobs
    assert all(j.placement == "" for j in jobs)


def test_queue_batch_extras_ride_lead_claim():
    """Same-coalesce-key window mates join the lead's claim wherever
    they sit in the scan window (same blocks -> same owner), and carry
    the lead's placement."""
    q = RequestQueue()
    bk = ("search_blocks", TENANT, ("blk",))
    lead = _job(key="blk", batch_key=bk)
    other = _job(key="peer-blk", batch_key=("search_blocks", TENANT, ("p",)))
    mate = _job(key="blk", batch_key=bk)
    for j in (lead, other, mate):
        q.enqueue(TENANT, j)

    def claim(tenant, job, now):
        return "own" if job.affinity_key == "blk" else None

    tenant, got, extras = q.dequeue_batch(
        timeout=0.2, max_batch=4, key_fn=lambda j: j.batch_key, claim=claim)
    assert got is lead and [j for _, j in extras] == [mate]
    assert mate.placement == lead.placement == "own"
    # the peer-owned job was skipped over, not consumed
    assert q.dequeue(timeout=0.05) is not None


# -------------------------------------------------- frontend-level routing


def test_frontend_owner_preferred_and_wire_placement():
    """Each attached worker is handed its ring-owned jobs first, and the
    wire job carries the placement for remote staged-cache attribution."""
    fe = _dispatcher(affinity_steal_ms=10_000.0)
    try:
        _attach(fe, "w1", "w2")
        keys = _keys_by_owner(fe, {"w1", "w2"})
        fe.queue.enqueue(TENANT, _job(key=keys["w2"]))
        fe.queue.enqueue(TENANT, _job(key=keys["w1"]))
        # w1 skips w2's (older!) job and takes its own
        wire = fe.poll_job(wait_s=0.5, worker_id="w1")
        assert wire is not None and wire["placement"] == "own"
        wire2 = fe.poll_job(wait_s=0.5, worker_id="w2")
        assert wire2 is not None and wire2["placement"] == "own"
    finally:
        fe.stop()


def test_frontend_single_domain_is_legacy():
    """With one attached worker there is nothing to route between: the
    claimer is None and jobs flow strictly FIFO with no placement."""
    fe = _dispatcher()
    try:
        _attach(fe, "only")
        assert fe._claimer("only") is None
        fe.queue.enqueue(TENANT, _job(key="whatever"))
        wire = fe.poll_job(wait_s=0.5, worker_id="only")
        assert wire is not None and wire["placement"] == ""
    finally:
        fe.stop()


def test_frontend_affinity_off_is_legacy():
    fe = _dispatcher(affinity=False)
    try:
        _attach(fe, "w1", "w2")
        assert fe._claimer("w1") is None and fe._claimer("w2") is None
    finally:
        fe.stop()


def test_affinity_respects_querier_shuffle_shard():
    """With max_queriers_per_tenant=1 ownership is resolved within the
    tenant's one-worker shard: every job is that worker's "own"
    immediately -- a fleet-wide owner outside the shard must never make
    shard members wait out the steal timeout for a worker that cannot
    take the job."""
    ov = Overrides()
    ov.defaults = replace(ov.defaults, max_queriers_per_tenant=1)
    fe = _dispatcher(overrides=ov, affinity_steal_ms=60_000.0)
    try:
        _attach(fe, "w1", "w2")
        keys = _keys_by_owner(fe, {"w1", "w2"})
        # one job per fleet-wide owner: whichever worker the tenant's
        # shard picked must claim BOTH as "own", instantly
        fe.queue.enqueue(TENANT, _job(key=keys["w1"]))
        fe.queue.enqueue(TENANT, _job(key=keys["w2"]))
        got = []
        t0 = time.monotonic()
        for _ in range(4):
            for w in ("w1", "w2"):
                wire = fe.poll_job(wait_s=0.05, worker_id=w)
                if wire:
                    got.append((w, wire["placement"]))
            if len(got) == 2:
                break
        assert time.monotonic() - t0 < 5.0  # nobody waited the steal clock
        assert len(got) == 2
        assert len({w for w, _ in got}) == 1  # all to the shard member
        assert all(p == "own" for _, p in got)
    finally:
        fe.stop()


def test_crashed_owner_jobs_complete_via_steal():
    """Regression (anti-starvation): a worker that stops polling must
    not strand its affinity-owned jobs past the steal timeout -- the
    live worker steals and completes them long before the dispatch
    deadline / lease expiry would fire."""
    steal_ms = 120.0
    fe = _dispatcher(affinity_steal_ms=steal_ms, lease_s=30.0)
    try:
        _attach(fe, "w-live", "w-dead")
        keys = _keys_by_owner(fe, {"w-dead"})
        jobs = [_job(key=keys["w-dead"]) for _ in range(3)]
        t0 = time.monotonic()
        for j in jobs:
            fe.queue.enqueue(TENANT, j)
        # w-dead never polls again (simulated crash); w-live keeps polling
        done = 0
        while done < len(jobs) and time.monotonic() - t0 < 5.0:
            wire = fe.poll_job(wait_s=0.3, worker_id="w-live")
            if wire is None:
                continue
            assert wire["placement"] == "steal"
            fe.complete_job(wire["id"], ok=True,
                            result={"trace": None} if wire["kind"] == "find_blocks"
                            else {"traces": [], "metrics": {}})
            done += 1
        elapsed = time.monotonic() - t0
        assert done == len(jobs)
        # stolen promptly after the timeout, nowhere near lease expiry
        assert steal_ms / 1e3 <= elapsed < 5.0
        assert all(j.done.is_set() and j.error is None for j in jobs)
    finally:
        fe.stop()


def test_sick_owner_does_not_monopolize_retries():
    """Regression: a fast-failing but ALIVE owner polls again first and
    would win its own job back inside the steal window on every retry,
    burning MAX_RETRIES against the same corrupt state. The retry path
    demotes the job to placement-free, so a healthy peer takes it
    instantly regardless of the steal timeout."""
    fe = _dispatcher(affinity_steal_ms=60_000.0)
    try:
        _attach(fe, "w-healthy", "w-sick")
        keys = _keys_by_owner(fe, {"w-sick"})
        j = _job(key=keys["w-sick"])
        fe.queue.enqueue(TENANT, j)
        wire = fe.poll_job(wait_s=0.5, worker_id="w-sick")
        assert wire is not None and wire["placement"] == "own"
        fe.complete_job(wire["id"], ok=False, error="corrupt state",
                        retryable=True)
        wire2 = fe.poll_job(wait_s=0.5, worker_id="w-healthy")
        assert wire2 is not None and wire2["placement"] == "unowned"
        fe.complete_job(wire2["id"], ok=True,
                        result={"traces": [], "metrics": {}})
        assert j.done.is_set() and j.error is None
    finally:
        fe.stop()


def test_placement_counters_recorded():
    base = TEL.affinity_stats()["jobs"]
    fe = _dispatcher(affinity_steal_ms=10_000.0)
    try:
        _attach(fe, "w1", "w2")
        keys = _keys_by_owner(fe, {"w1"})
        fe.queue.enqueue(TENANT, _job(key=keys["w1"]))
        assert fe.poll_job(wait_s=0.5, worker_id="w1") is not None
    finally:
        fe.stop()
    now = TEL.affinity_stats()["jobs"]
    assert now.get("own", 0) >= base.get("own", 0) + 1


# ----------------------------------------------------------- per-tenant QoS


def test_query_admission_budgets():
    ov = Overrides()
    ov.defaults = replace(ov.defaults, max_concurrent_queries=2,
                          max_inflight_query_bytes=100)
    qa = QueryAdmission(ov)
    assert qa.try_admit("a", 40) is None
    assert qa.try_admit("a", 40) is None
    assert qa.try_admit("a", 1) == "concurrency"
    qa.release("a", 40)
    # byte budget: 40 in flight, +70 would breach 100
    assert qa.try_admit("a", 70) == "bytes"
    assert qa.try_admit("a", 50) is None
    # tenants are independent
    assert qa.try_admit("b", 99) is None
    qa.release("a", 40)
    qa.release("a", 50)
    qa.release("b", 99)
    assert qa.inflight("a") == (0, 0) and qa.inflight("b") == (0, 0)


def test_query_admission_first_query_always_admits():
    """A lone query larger than the tenant's own byte budget is the
    budget's unit of progress, never a livelock."""
    ov = Overrides()
    ov.defaults = replace(ov.defaults, max_inflight_query_bytes=10)
    qa = QueryAdmission(ov)
    assert qa.try_admit("a", 10_000) is None  # over budget but alone
    assert qa.try_admit("a", 1) == "bytes"
    qa.release("a", 10_000)


def test_frontend_qos_shed_429_isolated_per_tenant():
    """A tenant at its concurrency budget sheds with TooManyRequests
    (the HTTP 429) while another tenant's queries are untouched."""
    ov = Overrides()
    ov.defaults = replace(ov.defaults, max_concurrent_queries=1)
    fe = Frontend(_StubQuerier(delay=0.5), n_workers=2, overrides=ov,
                  hedge_after_s=0.0, affinity=False)
    try:
        req = SearchRequest(limit=5)
        errs: list = []

        def slow():
            try:
                fe.search("heavy", req)
            except Exception as e:  # pragma: no cover - surfaced below
                errs.append(e)

        t = threading.Thread(target=slow, daemon=True)
        t.start()
        time.sleep(0.15)  # slow() is now inside its admitted search
        with pytest.raises(TooManyRequests):
            fe.search("heavy", req)
        # an unrelated tenant is admitted while heavy is at budget
        assert fe.search("light", req) is not None
        t.join(timeout=5)
        assert not errs
        # budget returned: heavy admits again
        assert fe.search("heavy", req) is not None
    finally:
        fe.stop()


def test_qos_shed_telemetry():
    before = TEL.affinity_stats()["qos_sheds"].get("q-tel", {})
    ov = Overrides()
    ov.defaults = replace(ov.defaults, max_concurrent_queries=1)
    qa = QueryAdmission(ov)
    fe = Frontend(_StubQuerier(), n_workers=0, overrides=ov, affinity=False)
    fe.qos = qa
    try:
        assert qa.try_admit("q-tel") is None
        with pytest.raises(TooManyRequests):
            fe._qos_admit("q-tel", 0)
    finally:
        qa.release("q-tel")
        fe.stop()
    after = TEL.affinity_stats()["qos_sheds"]["q-tel"]
    assert after.get("concurrency", 0) >= before.get("concurrency", 0) + 1


def test_shed_tenant_label_escaped():
    """Tenant names come off the X-Scope-OrgID header: quotes,
    backslashes and newlines must be escaped before they reach a
    Prometheus label or one hostile client corrupts every /metrics
    scrape."""
    TEL.record_shed('ev"il\\ten\nant', "bytes")
    want = 'tenant="ev\\"il\\\\ten\\nant",budget="bytes"'
    assert TEL.qos_shed.get(labels=want) >= 1
    # the raw name is preserved in the status aggregates
    assert 'ev"il\\ten\nant' in TEL.affinity_stats()["qos_sheds"]


def test_no_overrides_means_no_gate():
    fe = _dispatcher()
    try:
        assert fe.qos is None
        assert fe._qos_admit(TENANT, 1 << 40) == 0  # never sheds
    finally:
        fe.stop()
