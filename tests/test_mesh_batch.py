"""Mesh-batched serving: one admission window -> all chips.

Three planes keep the new subsystem honest:
  * differential: batched-mesh windows (parallel/multiquery) must be
    bit-identical to the sequential single-chip engine over randomized
    window mixes -- mixed predicate shapes, struct/regex fallbacks,
    ragged block sizes;
  * comm accounting: the PR-10 jaxpr walker's per-collective bytes for
    the shrunk programs must equal a hand-computed ring-model
    expectation (costmodel.ring_wire_bytes), and the struct-op shrink
    must cut the per-node collective >= 5x;
  * fallbacks: TEMPO_BATCH=0, TEMPO_MESH_BATCH=0 and the no-mesh
    (single chip) executor all take the legacy paths byte for byte.
"""

from __future__ import annotations

import tempfile
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.db.search import SearchRequest, search_block
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.util.kerneltel import TEL
from tempo_tpu.util.testdata import make_traces

TENANT = "mesh-batch-t"

# eligible shapes (lower to predicate programs) + deliberate fallbacks
# (struct relation, regex, generic attr) -- a realistic window mix
_QUERIES = [
    '{ name = "db.query" }',
    '{ duration > 500ms }',
    '{ status = error && kind = server }',
    '{ name = "GET /api" || name = "cache.get" }',
    '{ span.http.status_code >= 500 }',
    '{ name = "db.query" && resource.service.name = "db" }',
    '{ name = "GET /" } >> { name = "db.query" }',   # struct: falls back
    '{ name =~ "GET .*" }',                          # regex: falls back
    '{ span.component = "grpc" }',                   # attr table: falls back
]


def _mkdb(**over) -> TempoDB:
    cfg = TempoDBConfig(
        wal_path=tempfile.mkdtemp(prefix="tempo-meshb-wal"),
        batch_window_ms=over.pop("batch_window_ms", 200.0),
        device_promote_touches=over.pop("device_promote_touches", 1),
        **over,
    )
    return TempoDB(cfg, backend=MemBackend())


def _dicts(resp):
    return [{**t.to_dict(), "matchedSpans": t.matched_spans} for t in resp.traces]


def test_mesh_batched_equals_sequential_randomized():
    """Randomized windows (3 seeds x ragged block sizes x shuffled query
    mixes) through the batching executor on the 8-device mesh: every
    result bit-identical to the sequential single-chip engine, and the
    mesh-batched route actually fired."""
    rng = np.random.default_rng(101)
    mesh0 = TEL.mesh_batch_stats()["launches"]
    for seed in (1, 2, 3):
        db = _mkdb()
        # ragged sizes: nothing aligns with the 8-way shard split
        n = int(rng.integers(40, 160))
        m = db.write_block(TENANT, make_traces(n, seed=seed, n_spans=int(rng.integers(3, 9))))
        blk = db.open_block(m)
        picks = [str(rng.choice(_QUERIES)) for _ in range(12)]
        reqs = [SearchRequest(query=q, limit=200) for q in picks]
        expected = [_dicts(search_block(blk, r)) for r in reqs]
        with ThreadPoolExecutor(len(reqs)) as ex:
            futs = [ex.submit(db.search_blocks, TENANT, [m], r) for r in reqs]
            got = [_dicts(f.result()) for f in futs]
        for q, e, g in zip(picks, expected, got):
            assert e == g, f"mesh-batched != sequential for {q!r} (seed {seed})"
        db.close()
    assert TEL.mesh_batch_stats()["launches"] > mesh0, \
        "no window ever took the mesh-batched route"


def test_mesh_kernel_bit_identity_direct():
    """Kernel-level differential: the shard_map multiquery program's
    (trace_mask, counts) equal the single-chip fused interpreter's bit
    for bit, across program shapes and window occupancies."""
    from tempo_tpu.db.search import _plan_for_block
    from tempo_tpu.ops.filter import required_columns
    from tempo_tpu.ops.multiquery import (
        _p2,
        eval_multiquery,
        lower_plan,
        pack_queries,
    )
    from tempo_tpu.ops.stage import stage_block
    from tempo_tpu.parallel import make_mesh
    from tempo_tpu.parallel.multiquery import (
        mesh_batch_eligible,
        mesh_eval_multiquery,
    )

    mesh = make_mesh(8)
    db = _mkdb()
    m = db.write_block(TENANT, make_traces(130, seed=17, n_spans=7))
    blk = db.open_block(m)
    by_shape: dict = {}
    planned_of: dict = {}
    for q in _QUERIES:
        p = _plan_for_block(blk, SearchRequest(query=q))
        if p.prune:
            continue
        lq = lower_plan(p)
        if lq is None:
            continue  # fallback queries are covered by the db-level test
        by_shape.setdefault(lq.shape, []).append(lq)
        planned_of.setdefault(lq.shape, p)
    assert by_shape, "no eligible programs lowered"
    for shape, lqs in by_shape.items():
        needed = required_columns(planned_of[shape].conds) + \
            list(planned_of[shape].extra_cols)
        staged = stage_block(blk, needed + ["trace.start_ms"])
        q_b = _p2(len(lqs), lo=1)
        progs = pack_queries(lqs, q_b)
        tm1, c1 = eval_multiquery(lqs, staged, progs)
        assert mesh_batch_eligible(mesh, staged)
        tm2, c2 = mesh_eval_multiquery(mesh, lqs, staged, progs)
        np.testing.assert_array_equal(np.asarray(tm1), tm2)
        np.testing.assert_array_equal(np.asarray(c1), c2)
    db.close()


def _struct_cols(rng, B, S, NT, orphan_rate=0.05):
    """Stacked struct-query columns with parent chains AND orphans
    (pid == -2) scattered over EVERY sp shard."""
    cols = {
        "span.trace_sid": np.sort(
            rng.integers(0, NT, size=(B, S)).astype(np.int32), axis=1),
        "span.dur_us": rng.integers(0, 1000, size=(B, S)).astype(np.int32),
        "span.parent_idx": np.full((B, S), -1, np.int32),
    }
    for b in range(B):
        sid = cols["span.trace_sid"][b]
        prev_same = np.zeros(S, bool)
        prev_same[1:] = sid[1:] == sid[:-1]
        link = prev_same & (rng.random(S) < 0.5)
        pidx = np.where(link, np.arange(S) - 1, -1).astype(np.int32)
        pidx[rng.random(S) < orphan_rate] = -2
        cols["span.parent_idx"][b] = pidx
    return cols


def test_struct_shrink_bit_identical_and_5x_per_node(monkeypatch):
    """The hoisted + bit-packed struct collectives return byte-identical
    results to the legacy per-node triple gather for every relation, and
    the walker-priced per-node collective shrinks >= 5x (the ISSUE
    acceptance: the '>' node's 6S-byte gather set becomes one packed
    S/8-byte gather)."""
    from tempo_tpu.ops.filter import Cond, Operands, T_SPAN
    from tempo_tpu.parallel import make_mesh
    from tempo_tpu.parallel.search import sharded_search
    from tempo_tpu.util import costmodel

    mesh = make_mesh(8)
    rng = np.random.default_rng(7)
    B, S, NT = 2, 2048, 64  # unique span bucket: keys the walker rows
    cols = _struct_cols(rng, B, S, NT)
    n_spans = np.asarray([S, S - 137], np.int32)
    conds = (Cond(target=T_SPAN, col="span.dur_us", op="lt"),
             Cond(target=T_SPAN, col="span.dur_us", op="ge"))
    operands = Operands.build([(0, 800, 0, 0.0, 0.0), (0, 100, 0, 0.0, 0.0)])
    # '>' LAST: the walker keeps one row per (op, bucket), last capture
    # wins -- ordering leaves the parent-relation node (the common
    # production shape, and the one the >= 5x criterion prices) in the
    # walker rows for both variants
    for op in ("~", ">>", ">"):
        tree = ("struct", op, ("cond", 0), ("cond", 1))
        monkeypatch.setenv("TEMPO_STRUCT_PACK", "1")
        tm1, sc1 = sharded_search(mesh, tree, conds, operands, cols,
                                  n_spans, nt=NT)
        monkeypatch.setenv("TEMPO_STRUCT_PACK", "0")
        tm0, sc0 = sharded_search(mesh, tree, conds, operands, cols,
                                  n_spans, nt=NT)
        np.testing.assert_array_equal(tm1, tm0, err_msg=f"struct {op}")
        np.testing.assert_array_equal(sc1, sc0, err_msg=f"struct {op}")
    assert costmodel.COST.drain(30.0)
    packed = costmodel.COST.comm_for("mesh_search", str(S))
    legacy = costmodel.COST.comm_for("mesh_search_nopack", str(S))
    assert packed.get("all_gather", 0) > 0 and legacy.get("all_gather", 0) > 0
    shrink = legacy["all_gather"] / packed["all_gather"]
    assert shrink >= 5.0, (legacy, packed)
    # psum (the per-trace combine) is untouched by the shrink
    assert packed["psum"] == legacy["psum"]


def test_walker_comm_equals_ring_model():
    """Hand-computed ring-model expectation vs the jaxpr walker, for the
    SHRUNK programs: the packed '>' struct search and the batched
    multiquery launch. Exact byte equality -- the cross-check that the
    static pricing and the program shapes agree."""
    from tempo_tpu.db.search import _plan_for_block
    from tempo_tpu.ops.filter import Cond, Operands, T_SPAN, required_columns
    from tempo_tpu.ops.multiquery import _p2, lower_plan, pack_queries
    from tempo_tpu.ops.stage import stage_block
    from tempo_tpu.parallel import make_mesh
    from tempo_tpu.parallel.multiquery import mesh_eval_multiquery
    from tempo_tpu.parallel.search import sharded_search
    from tempo_tpu.util import costmodel
    from tempo_tpu.util.costmodel import ring_wire_bytes

    mesh = make_mesh(8)
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]

    # --- packed struct '>' search: one bit-packed lhs gather + the
    # per-trace psum stitch
    rng = np.random.default_rng(11)
    B, S, NT = 2, 4096, 128  # unique bucket for this test's walker rows
    cols = _struct_cols(rng, B, S, NT)
    n_spans = np.asarray([S, S - 99], np.int32)
    conds = (Cond(target=T_SPAN, col="span.dur_us", op="lt"),
             Cond(target=T_SPAN, col="span.dur_us", op="ge"))
    operands = Operands.build([(0, 900, 0, 0.0, 0.0), (0, 50, 0, 0.0, 0.0)])
    sharded_search(mesh, ("struct", ">", ("cond", 0), ("cond", 1)),
                   conds, operands, cols, n_spans, nt=NT)
    assert costmodel.COST.drain(30.0)
    got = costmodel.COST.comm_for("mesh_search", str(S))
    Bl = B // dp
    expected = {
        # packed lhs mask: out aval (Bl, S/8) uint8, k=sp, dp groups
        "all_gather": ring_wire_bytes("all_gather", 0, Bl * (S // 8), sp) * dp,
        # seg_reduce count stitch: (Bl, NT) int32. The trace carries TWO
        # psum eqns (the tracify fold and the reporting fold over the
        # same mask) that XLA CSEs into one -- the static walker prices
        # the jaxpr, so the model expects both (a deliberate
        # conservative overcount, never an undercount)
        "psum": 2 * ring_wire_bytes("psum", Bl * NT * 4, Bl * NT * 4, sp) * dp,
    }
    assert got == expected, (got, expected)

    # --- batched multiquery: exactly ONE psum for the whole window,
    # (q_b, NG+1, NT) int32 partial counts over every device
    db = _mkdb()
    m = db.write_block(TENANT, make_traces(90, seed=29, n_spans=6))
    blk = db.open_block(m)
    p = _plan_for_block(blk, SearchRequest(query='{ duration > 100ms }'))
    lqs = [lower_plan(p)] * 3
    q_b = _p2(3, lo=1)
    progs = pack_queries(lqs, q_b)
    needed = required_columns(p.conds) + list(p.extra_cols)
    staged = stage_block(blk, needed + ["trace.start_ms"])
    mesh_eval_multiquery(mesh, lqs, staged, progs)
    assert costmodel.COST.drain(30.0)
    got_mq = costmodel.COST.comm_for("mesh_multiquery", str(staged.n_spans_b))
    ng1 = lqs[0].shape.n_groups_b + 1
    in_b = q_b * ng1 * staged.n_traces_b * 4
    assert got_mq == {"psum": ring_wire_bytes("psum", in_b, in_b, dp * sp)}, \
        (got_mq, {"q_b": q_b, "ng1": ng1, "nt": staged.n_traces_b})
    db.close()


def test_fallback_paths_byte_identical(monkeypatch):
    """TEMPO_BATCH=0 (no executor), TEMPO_MESH_BATCH=0 (single-chip
    fused launch) and a no-mesh executor must all return byte-identical
    results -- the legacy paths are untouched by the mesh route."""
    from tempo_tpu.db.batchexec import batched_search_block_many

    traces = make_traces(110, seed=23, n_spans=6)
    req = SearchRequest(query='{ duration > 50ms && status != error }',
                        limit=200)

    # reference: batching executor disabled end to end
    monkeypatch.setenv("TEMPO_BATCH", "0")
    db0 = _mkdb()
    m0 = db0.write_block(TENANT, traces)
    assert not db0.batchers.enabled
    ref = _dicts(db0.search_blocks(TENANT, [m0], req))
    assert ref == _dicts(search_block(db0.open_block(m0), req))
    db0.close()
    monkeypatch.delenv("TEMPO_BATCH")

    # mesh batching pinned off: window leaders keep the single-chip
    # fused launch; results identical
    monkeypatch.setenv("TEMPO_MESH_BATCH", "0")
    r0 = TEL.routing_counts()
    db1 = _mkdb()
    m1 = db1.write_block(TENANT, traces)
    blk1 = db1.open_block(m1)
    outs = batched_search_block_many(
        db1.batchers.search, [(blk1, req, None)] * 4, promote_touches=1)
    for o in outs:
        assert _dicts(o) == ref
    r1 = TEL.routing_counts()
    assert r1.get(("search_batch", "mesh", "mesh_batched"), 0) == \
        r0.get(("search_batch", "mesh", "mesh_batched"), 0)
    assert r1.get(("search_batch", "device", "coalesced"), 0) > \
        r0.get(("search_batch", "device", "coalesced"), 0)
    db1.close()
    monkeypatch.delenv("TEMPO_MESH_BATCH")

    # single-chip executor (mesh_fn yields nothing): same story
    from tempo_tpu.db.batchexec import QueryBatchers

    db2 = _mkdb()
    m2 = db2.write_block(TENANT, traces)
    blk2 = db2.open_block(m2)
    db2.batchers = QueryBatchers(enabled=True, window_ms=200.0,
                                 mesh_fn=lambda: None)
    outs2 = batched_search_block_many(
        db2.batchers.search, [(blk2, req, None)] * 4, promote_touches=1)
    for o in outs2:
        assert _dicts(o) == ref
    db2.close()
