"""Storage-engine tests: WAL, blocklist/poller, search, compaction, facade."""

import os

import numpy as np
import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.block import build_block_from_traces
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.db import compactor as comp
from tempo_tpu.db.blocklist import Blocklist, Poller
from tempo_tpu.db.search import SearchRequest
from tempo_tpu.db.wal import WAL, WALBlock
from tempo_tpu.util.testdata import make_trace, make_traces
from tempo_tpu.wire import segment
from tempo_tpu.wire.combine import combine_traces

TENANT = "t1"


def _db(tmp_path, backend=None):
    cfg = TempoDBConfig(wal_path=str(tmp_path / "wal"))
    return TempoDB(cfg, backend=backend or MemBackend())


# ---------------------------------------------------------------- WAL


def test_wal_append_replay(tmp_path):
    wal = WAL(str(tmp_path))
    blk = wal.new_block(TENANT)
    traces = make_traces(5, seed=1)
    for tid, t in traces:
        seg = segment.segment_for_write(t, 100, 200)
        blk.append(tid, 100, 200, seg)
    blk.flush()

    replayed = wal.rescan_blocks()
    assert len(replayed) == 1
    rb = replayed[0]
    assert rb.tenant == TENANT and rb.clean
    assert [r.trace_id for r in rb.records] == [tid for tid, _ in traces]
    got = segment.segment_to_trace(rb.records[0].segment)
    assert got.span_count() == traces[0][1].span_count()


def test_wal_torn_tail(tmp_path):
    wal = WAL(str(tmp_path))
    blk = wal.new_block(TENANT)
    traces = make_traces(3, seed=2)
    for tid, t in traces:
        blk.append(tid, 1, 2, segment.segment_for_write(t, 1, 2))
    blk.close()
    # simulate crash mid-append: chop bytes off the tail
    with open(blk.path, "r+b") as f:
        f.truncate(os.path.getsize(blk.path) - 7)
    replayed = wal.rescan_blocks()
    assert not replayed[0].clean
    assert len(replayed[0].records) == 2  # last record dropped
    # file is truncated to a clean boundary: re-open and append works
    # (same format class the block was written with -- w2 by default)
    blk2 = type(blk)(str(tmp_path), TENANT, replayed[0].block_id)
    tid, t = make_traces(1, seed=9)[0]
    blk2.append(tid, 1, 2, segment.segment_for_write(t, 1, 2))
    blk2.flush()
    again = [rb for rb in wal.rescan_blocks() if rb.block_id == replayed[0].block_id]
    assert len(again[0].records) == 3 and again[0].clean


# ------------------------------------------------------- blocklist/poller


def test_poller_and_blocklist():
    backend = MemBackend()
    m1 = build_block_from_traces(backend, TENANT, make_traces(5, seed=3))
    m2 = build_block_from_traces(backend, "t2", make_traces(4, seed=4))
    poller = Poller(backend)
    metas, compacted = poller.poll()
    assert {m.block_id for m in metas[TENANT]} == {m1.block_id}
    assert {m.block_id for m in metas["t2"]} == {m2.block_id}

    bl = Blocklist()
    bl.apply_poll_results(metas, compacted)
    assert len(bl.metas(TENANT)) == 1

    # tenant index was written and round-trips without re-listing
    consumer = Poller(backend, build_index=False)
    metas2, _ = consumer.poll()
    assert {m.block_id for m in metas2[TENANT]} == {m1.block_id}

    # in-flight updates survive a poll (ApplyPollResults patching)
    m3 = build_block_from_traces(backend, TENANT, make_traces(3, seed=5))
    bl.update(TENANT, add=[m3])
    stale_metas = {TENANT: [m for m in metas[TENANT]]}  # poll without m3
    bl.apply_poll_results(stale_metas, {})
    assert {m.block_id for m in bl.metas(TENANT)} == {m1.block_id, m3.block_id}


# ------------------------------------------------------------- facade


def test_find_across_blocks(tmp_path):
    db = _db(tmp_path)
    all_traces = make_traces(40, seed=6, n_spans=6)
    db.write_block(TENANT, all_traces[:20])
    db.write_block(TENANT, all_traces[20:])
    for tid, original in all_traces[::7]:
        got = db.find_trace_by_id(TENANT, tid)
        assert got is not None
        assert got.span_count() == original.span_count()
    assert db.find_trace_by_id(TENANT, b"\x01" * 16) is None


def test_find_combines_partials(tmp_path):
    """Same trace id in two blocks (replicated flush) -> combined, deduped."""
    db = _db(tmp_path)
    tid = b"\x42" * 16
    t1 = make_trace(1, trace_id=tid, n_spans=4)
    t2 = make_trace(2, trace_id=tid, n_spans=5)
    filler1 = make_traces(3, seed=7)
    filler2 = make_traces(3, seed=8)
    db.write_block(TENANT, sorted(filler1 + [(tid, t1)], key=lambda p: p[0]))
    db.write_block(TENANT, sorted(filler2 + [(tid, t2)], key=lambda p: p[0]))
    got = db.find_trace_by_id(TENANT, tid)
    assert got.span_count() == 9


def test_search_end_to_end(tmp_path):
    db = _db(tmp_path)
    traces = make_traces(60, seed=10, n_spans=8)
    db.write_block(TENANT, traces)

    # tag search on a service that exists
    resp = db.search(TENANT, SearchRequest(tags={"service.name": "db"}, limit=100))
    # oracle: traces with any span whose resource service == "db"
    expect = {
        tid.hex()
        for tid, t in traces
        if any(res.service_name == "db" for res, _, _ in t.all_spans())
    }
    assert {r.trace_id for r in resp.traces} == expect

    # absent value prunes everything
    assert db.search(TENANT, SearchRequest(tags={"service.name": "nope"})).traces == []

    # min duration filters (trace-level, exact)
    resp2 = db.search(TENANT, SearchRequest(min_duration_ms=1, limit=1000))
    for r in resp2.traces:
        assert r.duration_ms >= 1

    # attribute search
    resp3 = db.search(TENANT, SearchRequest(tags={"http.method": "GET"}, limit=1000))
    expect3 = {
        tid.hex()
        for tid, t in traces
        if any(sp.attrs.get("http.method") == "GET" for _, _, sp in t.all_spans())
    }
    assert {r.trace_id for r in resp3.traces} == expect3

    # tag discovery
    tags = db.search_tags(TENANT)
    assert "http.method" in tags and "k8s.cluster.name" in tags
    vals = db.search_tag_values(TENANT, "http.method")
    assert set(vals) <= {"GET", "POST", "PUT", "DELETE"} and vals


def test_compaction_roundtrip(tmp_path):
    db = _db(tmp_path)
    db.cfg.compaction.min_input_blocks = 2
    all_traces = make_traces(30, seed=12, n_spans=5)
    db.write_block(TENANT, all_traces[:10])
    db.write_block(TENANT, all_traces[10:20])
    db.write_block(TENANT, all_traces[20:])
    assert len(db.blocklist.metas(TENANT)) == 3

    results = db.compact_once(TENANT)
    assert results and sum(len(r.new_blocks) for r in results) >= 1
    metas = db.blocklist.metas(TENANT)
    assert all(m.compaction_level >= 1 for m in metas)
    # every trace still findable, spans preserved
    for tid, original in all_traces[::5]:
        got = db.find_trace_by_id(TENANT, tid)
        assert got is not None
        assert got.span_count() == original.span_count()

    # compacted originals are marked in the backend
    _, compacted = db.poller.poll()
    assert len(compacted[TENANT]) == 3


def test_compaction_dedupes_across_blocks(tmp_path):
    db = _db(tmp_path)
    tid = b"\x99" * 16
    shared = make_trace(5, trace_id=tid, n_spans=6)
    import copy

    db.write_block(TENANT, sorted(make_traces(4, seed=13) + [(tid, shared)], key=lambda p: p[0]))
    db.write_block(TENANT, sorted(make_traces(4, seed=14) + [(tid, copy.deepcopy(shared))], key=lambda p: p[0]))
    db.compact_once(TENANT)
    got = db.find_trace_by_id(TENANT, tid)
    assert got.span_count() == 6  # replicas deduped, not doubled


def test_retention(tmp_path):
    db = _db(tmp_path)
    db.cfg.compaction.retention_s = 10  # everything is ancient vs 2023 test data
    db.write_block(TENANT, make_traces(5, seed=15))
    res = db.retention_once(TENANT)
    assert len(res.marked) == 1
    assert db.blocklist.metas(TENANT) == []
    db.poll_now()
    assert db.blocklist.metas(TENANT) == []


def test_select_jobs_windows():
    cfg = comp.CompactorConfig()
    now = 1_700_000_000.0
    metas = []
    for i in range(4):
        m = build_block_from_traces(MemBackend(), TENANT, make_traces(2, seed=i))
        m.size_bytes = 100
        metas.append(m)
    jobs = comp.select_jobs(TENANT, metas, cfg, now=1_700_100_000.0)
    assert jobs and all(len(j.blocks) >= 2 for j in jobs)
    assert jobs[0].hash.startswith(f"{TENANT}-0-")


def test_streamed_search_matches_unstreamed(tmp_path):
    """A many-row-group block takes the streaming path and returns the
    same results as the single-stage path."""
    from tempo_tpu.backend import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest, search_block
    from tempo_tpu.util.testdata import make_traces

    db = TempoDB(
        TempoDBConfig(wal_path=str(tmp_path / "w"), row_group_spans=32),
        backend=MemBackend(),
    )
    traces = make_traces(120, seed=13, n_spans=6)  # 720 spans -> ~23 groups
    meta = db.write_block("t", traces)
    assert len(meta.row_groups) > 8  # streaming threshold crossed

    blk = db.open_block(meta)
    req = SearchRequest(query='{ resource.service.name = "db" }', limit=1000)
    resp = search_block(blk, req)
    expect = {
        tid.hex() for tid, t in traces
        if any(r.service_name == "db" for r, _, _ in t.all_spans())
    }
    assert {r.trace_id for r in resp.traces} == expect
    assert resp.inspected_spans == 720
    # sharded path (explicit group range) still agrees on its shard
    half = search_block(blk, req, groups_range=list(range(0, len(meta.row_groups) // 2)))
    assert {r.trace_id for r in half.traces} <= expect
    db.close()


def test_streamed_search_cross_chunk_and(tmp_path):
    """AND of two tracify legs whose matching spans land in DIFFERENT
    chunks must still match the trace (per-leaf cross-chunk combine)."""
    from tempo_tpu.backend import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest, search_block
    from tempo_tpu.wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

    base = 1_700_000_000_000_000_000
    # one giant trace whose "a" span is at the start and "b" span at the
    # end, padded with enough filler spans to span many row groups
    tid = bytes([7]) * 16
    spans = [Span(trace_id=tid, span_id=(1).to_bytes(8, "big"), name="start",
                  attrs={"a": "v"}, start_unix_nano=base, end_unix_nano=base + 10)]
    for i in range(300):
        spans.append(Span(trace_id=tid, span_id=(i + 2).to_bytes(8, "big"),
                          name="filler", start_unix_nano=base, end_unix_nano=base + 10))
    spans.append(Span(trace_id=tid, span_id=(999).to_bytes(8, "big"), name="end",
                      attrs={"b": "v"}, start_unix_nano=base, end_unix_nano=base + 10))
    tr = Trace(resource_spans=[ResourceSpans(
        resource=Resource(attrs={"service.name": "s"}),
        scope_spans=[ScopeSpans(scope=Scope(), spans=spans)])])
    # second trace with only "a" (must NOT match)
    tid2 = bytes([8]) * 16
    tr2 = Trace(resource_spans=[ResourceSpans(
        resource=Resource(attrs={"service.name": "s"}),
        scope_spans=[ScopeSpans(scope=Scope(), spans=[
            Span(trace_id=tid2, span_id=(1).to_bytes(8, "big"), name="x",
                 attrs={"a": "v"}, start_unix_nano=base, end_unix_nano=base + 10)])])])

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w"), row_group_spans=16),
                 backend=MemBackend())
    meta = db.write_block("t", [(tid, tr), (tid2, tr2)])
    assert len(meta.row_groups) > 8  # streaming engages
    blk = db.open_block(meta)
    # tag search: per-tag tracify groups ANDed at trace level
    resp = search_block(blk, SearchRequest(tags={"a": "v", "b": "v"}, limit=10))
    assert {r.trace_id for r in resp.traces} == {tid.hex()}
    db.close()


def test_device_paths_run_mesh_programs(tmp_path):
    """The service-layer Find and search run the sharded mesh programs
    (the same kernels the driver dryrun validates) and match the host
    fallback path exactly."""
    from tempo_tpu.parallel import find as pf
    from tempo_tpu.parallel import search as ps

    db = _db(tmp_path)
    for seed in (21, 22, 23):
        db.write_block(TENANT, make_traces(8, seed=seed))
    assert db.mesh.devices.size == 8  # conftest forces the virtual mesh

    fi = pf.make_sharded_find_rows.cache_info()
    f_before = fi.hits + fi.misses
    si = ps.make_sharded_search.cache_info()
    s_before = si.hits + si.misses

    tid, t = make_traces(8, seed=22)[3]
    got = db.find_trace_by_id(TENANT, tid)
    assert got is not None and got.span_count() == t.span_count()
    fi = pf.make_sharded_find_rows.cache_info()
    assert fi.hits + fi.misses > f_before, "find did not run the mesh program"

    req = SearchRequest(tags={"service.name": "auth"}, limit=100)
    resp = db.search(TENANT, req)
    si = ps.make_sharded_search.cache_info()
    assert si.hits + si.misses > s_before, "search did not run the mesh program"

    db.cfg.device_find = False
    db.cfg.device_search = False
    resp_host = db.search(TENANT, req)
    assert sorted(r.trace_id for r in resp.traces) == sorted(
        r.trace_id for r in resp_host.traces
    )
    got_host = db.find_trace_by_id(TENANT, tid)
    assert got_host.span_count() == got.span_count()


def test_device_search_generic_attr_on_mesh(tmp_path):
    """Arbitrary {span.foo = "bar"} / mixed generic-attr queries run the
    stacked MESH program (attr rows sharded over sp) and match the host
    path -- previously the generic-attr tables forced the per-block
    fallback."""
    from tempo_tpu.parallel import search as ps

    db = _db(tmp_path)
    for seed in (31, 32, 33):
        db.write_block(TENANT, make_traces(10, seed=seed))

    for q in (
        '{ span.component = "grpc" }',          # sattr str eq
        '{ .component =~ "gr.*" }',             # EITHER scope + regex table
        '{ span.latency.weight > 0.25 }',       # float attr (needs_verify)
        '{ span.component != nil && duration > 1ms }',  # exists + span col
    ):
        si = ps.make_sharded_search.cache_info()
        before = si.hits + si.misses
        req = SearchRequest(query=q, limit=100)
        resp = db.search(TENANT, req)
        si = ps.make_sharded_search.cache_info()
        assert si.hits + si.misses > before, f"{q} did not run the mesh program"
        assert resp.traces, q
        db.cfg.device_search = False
        resp_host = db.search(TENANT, req)
        db.cfg.device_search = True
        assert sorted(r.trace_id for r in resp.traces) == sorted(
            r.trace_id for r in resp_host.traces
        ), q


def test_device_find_combines_partials(tmp_path):
    """Device Find returns per-block hit rows so replicated partial
    traces still combine (not a single elected winner)."""
    db = _db(tmp_path)
    tid = b"\x43" * 16
    t1 = make_trace(41, trace_id=tid, n_spans=4)
    t2 = make_trace(42, trace_id=tid, n_spans=5)
    db.write_block(TENANT, sorted(make_traces(3, seed=43) + [(tid, t1)], key=lambda p: p[0]))
    db.write_block(TENANT, sorted(make_traces(3, seed=44) + [(tid, t2)], key=lambda p: p[0]))
    assert db.cfg.device_find
    got = db.find_trace_by_id(TENANT, tid)
    assert got.span_count() == 9


def test_compaction_unions_blooms_on_device(tmp_path, monkeypatch):
    """Compaction must produce the output bloom via the device OR-union
    when input geometries match -- never by re-inserting ids."""
    from tempo_tpu.block.bloom import ShardedBloom

    db = _db(tmp_path)
    db.cfg.compaction.concat_small_input_bytes = 0  # force the real merge
    a = make_traces(8, seed=31)
    b = make_traces(8, seed=32)
    db.write_block(TENANT, a)
    db.write_block(TENANT, b)
    m1, m2 = db.blocklist.metas(TENANT)
    assert (m1.bloom_shards, m1.bloom_shard_bits) == (m2.bloom_shards, m2.bloom_shard_bits)

    def no_rebuild(self, ids):
        raise AssertionError("bloom rebuilt key-by-key; union path not taken")

    monkeypatch.setattr(ShardedBloom, "add_many", no_rebuild)
    results = db.compact_once(TENANT)
    assert results and results[0].new_blocks
    (out,) = db.blocklist.metas(TENANT)
    assert (out.bloom_shards, out.bloom_shard_bits) == (m1.bloom_shards, m1.bloom_shard_bits)
    blk = db.open_block(out)
    for tid, _ in a + b:
        assert blk.bloom_test(tid)


def _canon_trace(t):
    """Canonical comparable form of a wire trace: every span with its
    resource/scope context, attrs, events, links -- order-independent."""
    out = []
    for res, scope, sp in t.all_spans():
        out.append((
            sp.span_id, sp.name, sp.kind, sp.start_unix_nano, sp.end_unix_nano,
            sp.status_code, sp.parent_span_id, tuple(sorted(sp.attrs.items())),
            tuple(sorted(res.attrs.items())), (scope.name, scope.version),
            tuple((e.name, e.time_unix_nano, tuple(sorted(e.attrs.items()))) for e in sp.events),
            tuple((ln.trace_id, ln.span_id, tuple(sorted(ln.attrs.items()))) for ln in sp.links),
        ))
    return sorted(out)


def test_columnar_compaction_golden_vs_wire(tmp_path):
    """The columnar fast path and the wire-model merge produce
    byte-equivalent traces (golden equality), including a collision."""
    tid = b"\x77" * 16
    shared1 = make_trace(51, trace_id=tid, n_spans=4)
    shared2 = make_trace(52, trace_id=tid, n_spans=5)
    inputs = [
        sorted(make_traces(12, seed=53, n_spans=6) + [(tid, shared1)], key=lambda p: p[0]),
        sorted(make_traces(12, seed=54, n_spans=6) + [(tid, shared2)], key=lambda p: p[0]),
        make_traces(12, seed=55, n_spans=6),
    ]
    dbs = {}
    for mode in ("columnar", "wire"):
        db = _db(tmp_path / mode)
        db.cfg.compaction.columnar = mode == "columnar"
        for batch in inputs:
            db.write_block(TENANT, batch)
        res = db.compact_once(TENANT)
        assert res and res[0].new_blocks
        dbs[mode] = db

    all_ids = sorted({tid} | {t for batch in inputs for t, _ in batch})
    for t in all_ids:
        a = dbs["columnar"].find_trace_by_id(TENANT, t)
        b = dbs["wire"].find_trace_by_id(TENANT, t)
        assert a is not None and b is not None, t.hex()
        assert _canon_trace(a) == _canon_trace(b), t.hex()
    # search parity too
    req = SearchRequest(tags={"service.name": "auth"}, limit=1000)
    ra = dbs["columnar"].search(TENANT, req)
    rb = dbs["wire"].search(TENANT, req)
    assert sorted(r.trace_id for r in ra.traces) == sorted(r.trace_id for r in rb.traces)


def test_columnar_compaction_size_cuts(tmp_path):
    """A small target_block_bytes cuts compaction output into multiple
    id-disjoint blocks, all traces intact."""
    db = _db(tmp_path)
    db.cfg.compaction.target_block_bytes = 1  # force per-trace-ish cuts
    all_traces = make_traces(24, seed=61, n_spans=5)
    db.write_block(TENANT, all_traces[:12])
    db.write_block(TENANT, all_traces[12:])
    res = db.compact_once(TENANT)
    assert res
    outs = res[0].new_blocks
    assert len(outs) > 1, "size target did not cut the output"
    # id ranges are disjoint and ordered (merge emits sorted runs)
    ranges = sorted((m.min_id, m.max_id) for m in outs)
    for (lo1, hi1), (lo2, hi2) in zip(ranges, ranges[1:]):
        assert hi1 < lo2
    for t, original in all_traces:
        got = db.find_trace_by_id(TENANT, t)
        assert got is not None and got.span_count() == original.span_count()


def test_block_codec_config(tmp_path):
    """TempoDBConfig.block_codec writes ingest blocks with the chosen
    chunk codec; find/search read them back transparently."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db.search import SearchRequest
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
    from tempo_tpu.util.testdata import make_traces

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w"), block_codec="gzip"),
                 backend=MemBackend())
    traces = make_traces(10, seed=6, n_spans=3)
    meta = db.write_block("t", sorted(traces, key=lambda t: t[0]))
    blk = db.open_block(meta)
    codecs = {rec[3] for col in blk.pack._cols.values() for rec in col["chunks"]
              if rec[2] >= 128}
    assert "gzip" in codecs and "zstd" not in codecs
    tid, tr = traces[2]
    got = db.find_trace_by_id("t", tid)
    assert got is not None and got.span_count() == tr.span_count()
    assert db.search("t", SearchRequest(limit=50)).traces
    db.close()


def test_cli_block_ops(tmp_path, capsys):
    """gen-bloom / dump-columns / rewrite-block (tempo-cli's bloom
    regen, column dump and convert roles)."""
    import glob
    import os

    from tempo_tpu.cli.__main__ import main as cli

    store = str(tmp_path / "store")
    cli(["--backend.path", store, "gen", "t1", "--traces", "20", "--spans", "3"])
    bid = capsys.readouterr().out.split()[2].rstrip(":")

    cli(["--backend.path", store, "dump-columns", "t1", bid])
    out = capsys.readouterr().out
    assert "span.trace_sid" in out and "TOTAL" in out and "zstd" in out

    # nuke the bloom; regen restores find
    for f in glob.glob(os.path.join(store, "t1", bid, "bloom-*")):
        os.remove(f)
    cli(["--backend.path", store, "gen-bloom", "t1", bid])
    assert "regenerated bloom" in capsys.readouterr().out
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w")),
                 backend=LocalBackend(store))
    db.poll_now()
    blk = db.open_block(db.blocklist.metas("t1")[0])
    tid = blk.trace_index["trace.id"][3].tobytes()
    before = db.find_trace_by_id("t1", tid)
    assert before is not None

    cli(["--backend.path", store, "rewrite-block", "t1", bid, "--codec", "gzip"])
    assert "rewrote" in capsys.readouterr().out
    db2 = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w2")),
                  backend=LocalBackend(store))
    db2.poll_now()
    metas = db2.blocklist.metas("t1")
    # the freshly-compacted original stays listed for the swap-window
    # grace (blocklist.COMPACTED_GRACE_S); exactly one LIVE replacement
    live = [m for m in metas if not m.compacted_at_unix]
    assert len(live) == 1 and live[0].block_id != bid
    assert all(m.block_id == bid for m in metas if m.compacted_at_unix)
    got = db2.find_trace_by_id("t1", tid)
    assert got is not None and got.span_count() == before.span_count()
    # attributes survive the lossless conversion
    def attr_sets(t):
        return sorted((sp.name, tuple(sorted(sp.attrs.items())))
                      for _, _, sp in t.all_spans())
    assert attr_sets(got) == attr_sets(before)
    db.close()
    db2.close()


def test_tres_membership_axis(tmp_path):
    """The tres axis (builder.build_tres) is consistent with the span
    axis, drives the res-only host fast path to identical results, and
    survives compaction with remapped res indices."""
    from tempo_tpu.block.builder import build_tres
    from tempo_tpu.db.search import (
        SearchRequest,
        _host_plan,
        _plan_for_block,
        search_block,
    )

    db = _db(tmp_path)
    db.cfg.compaction.min_input_blocks = 2
    all_traces = make_traces(40, seed=21, n_spans=6)
    db.write_block(TENANT, all_traces[:20])
    db.write_block(TENANT, all_traces[20:])
    metas = db.blocklist.metas(TENANT)
    blk = db.open_block(metas[0])

    # tres columns match a recompute from the span axis
    sid = blk.pack.read("span.trace_sid")
    ri = blk.pack.read("span.res_idx")
    want = build_tres(sid, ri, blk.meta.total_traces)
    for n in ("tres.res", "tres.nspans", "trace.tres_off"):
        np.testing.assert_array_equal(blk.pack.read(n), want[n])

    # res-only queries take the tres plan and agree with a span-axis run
    svc = None
    d = blk.dictionary
    for code in blk.pack.read("res.service_id"):
        if code >= 0:
            svc = d.string(int(code))
            break
    assert svc is not None
    req = SearchRequest(tags={"service.name": svc}, limit=100)
    p = _plan_for_block(blk, req)
    host_needed, tres_mode = _host_plan(blk, p, None)
    assert tres_mode and "tres.res" in host_needed
    got = search_block(blk, req, mode="host")

    class _NoTresPack:
        def __init__(self, pack):
            self._p = pack
        def has(self, name):
            return False if name.startswith("tres.") else self._p.has(name)
        def __getattr__(self, a):
            return getattr(self._p, a)

    blk2 = db.open_block(metas[0])
    blk2.__dict__["pack"] = _NoTresPack(blk2.pack)  # cached_property slot
    base = search_block(blk2, req, mode="host")
    assert {(t.trace_id, t.matched_spans) for t in got.traces} == \
           {(t.trace_id, t.matched_spans) for t in base.traces}
    assert len(got.traces) > 0

    # compaction: merged tres equals a recompute from merged span columns
    db.compact_once(TENANT)
    db.poll_now()
    cmeta = [m for m in db.blocklist.metas(TENANT) if m.compaction_level >= 1]
    assert cmeta
    cblk = db.open_block(cmeta[0])
    want2 = build_tres(cblk.pack.read("span.trace_sid"),
                       cblk.pack.read("span.res_idx"), cblk.meta.total_traces)
    for n in ("tres.res", "tres.nspans", "trace.tres_off"):
        np.testing.assert_array_equal(cblk.pack.read(n), want2[n])


def test_grace_listed_blocks_not_reprocessed(tmp_path):
    """Freshly-compacted blocks stay searchable for the grace window but
    must NOT be re-selected as compaction inputs or re-marked by
    retention (their data already lives in an output block)."""
    import time as _time

    backend = MemBackend()
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w1")), backend=backend)
    db.cfg.compaction.min_input_blocks = 2
    all_traces = make_traces(20, seed=51, n_spans=4)
    db.write_block(TENANT, all_traces[:10])
    db.write_block(TENANT, all_traces[10:])
    db.compact_once(TENANT)
    # a DIFFERENT process's poller (fresh db) sees the graced inputs --
    # the compacting process removes them locally and immediately
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w2")), backend=backend)
    db.poll_now()
    metas = db.blocklist.metas(TENANT)
    graced = [m for m in metas if m.compacted_at_unix]
    assert graced, "grace window should keep the inputs listed"

    # compaction sweep: graced blocks are never inputs again
    jobs = comp.select_jobs(TENANT, metas, db.cfg.compaction)
    for j in jobs:
        assert not any(m.compacted_at_unix for m in j.blocks)

    # retention sweep over grace-listed metas must not crash or re-mark
    db.cfg.compaction.retention_s = 0  # everything "expired"
    res = db.retention_once(TENANT)
    assert all(m.block_id not in res.marked for m in graced)

    # idempotent mark: double-marking is a no-op, not DoesNotExist
    db.backend.mark_compacted(TENANT, graced[0].block_id)


def test_concat_compound_compaction(tmp_path):
    """Level-0 small blocks concat into a compound block (no-decode
    verbatim copies); the poller expands it into part blocks that serve
    find + search unchanged; the next level's columnar rewrite merges
    the parts for real; a fully-consumed compound ages out whole."""
    backend = MemBackend()
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w1")), backend=backend)
    db.cfg.compaction.min_input_blocks = 2
    db.cfg.compaction.max_input_blocks = 16
    all_traces = make_traces(40, seed=61, n_spans=5)
    for i in range(8):
        db.write_block(TENANT, all_traces[i * 5:(i + 1) * 5])
    db.poll_now()

    res = db.compact_once(TENANT)
    assert res and all("/" in m.block_id for r in res for m in r.new_blocks), \
        "small level-0 inputs must take the concat path (parts have cid/pN ids)"
    assert sum(r.traces_out for r in res) == 40

    # a fresh process's poll expands the compound into parts
    db2 = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w2")), backend=backend)
    db2.poll_now()
    parts = [m for m in db2.blocklist.metas(TENANT) if "/" in m.block_id]
    assert len(parts) == 8 and all(m.compaction_level == 1 for m in parts)

    for tid, original in all_traces[::7]:
        got = db2.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == original.span_count()
    resp = db2.search(TENANT, SearchRequest(limit=100))
    assert len(resp.traces) == 40

    # the next level merges parts with the real columnar rewrite
    res2 = db2.compact_once(TENANT)
    merged = [m for r in res2 for m in r.new_blocks]
    assert merged and all("/" not in m.block_id for m in merged)
    # freshly-consumed parts keep their searchable grace: the compound
    # does NOT collapse to a whole yet
    db3 = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w3")), backend=backend)
    db3.poll_now()
    assert not [m for m in db3.blocklist.compacted_metas(TENANT)
                if m.version == "vtpu1c"]
    import tempo_tpu.db.blocklist as BL

    _g = BL.COMPACTED_GRACE_S
    BL.COMPACTED_GRACE_S = 0.0  # grace lapsed: whole-collapse kicks in
    db3.poll_now()
    assert len(db3.search(TENANT, SearchRequest(limit=100)).traces) == 40
    for tid, original in all_traces[::11]:
        assert db3.find_trace_by_id(TENANT, tid) is not None

    # every part consumed -> the compound lists as ONE compacted whole
    wholes = [m for m in db3.blocklist.compacted_metas(TENANT)
              if m.version == "vtpu1c"]
    assert wholes, "fully-consumed compound should age out as a whole"

    # retention deletes whole compounds (never individual parts)
    try:
        db3.cfg.compaction.compacted_retention_s = 0
        res3 = db3.retention_once(TENANT)
        assert wholes[0].block_id in res3.deleted
        assert not any("/" in b for b in res3.deleted)
        # the bytes are truly gone (recursive delete incl. parts)
        assert not any(bid.startswith(wholes[0].block_id)
                       for bid in backend.blocks(TENANT))
    finally:
        BL.COMPACTED_GRACE_S = _g


def _old_layout_block(backend, traces):
    """Write a round-3-layout block: today's builder output minus the
    columns that joined in round 4 (tres axis, span.parent_idx). The
    single definition both compat tests share."""
    from tempo_tpu.block.builder import BlockBuilder, write_block

    b = BlockBuilder(TENANT)
    for tid, t in sorted(traces, key=lambda p: p[0]):
        b.add_trace(tid, t)
    fin = b.finalize()
    for name in list(fin.cols):
        if name.startswith("tres.") or name in ("trace.tres_off", "span.parent_idx"):
            del fin.cols[name]
    return write_block(backend, fin)


def test_pre_upgrade_block_compat(tmp_path):
    """A physically OLD-format block (no tres axis, no span.parent_idx --
    the round-3 layout) must keep working end to end: find by id, tag
    search, structural-TraceQL planning without the parent column, and
    compaction MIXED with a current-format block (differing column sets
    force the columnar merge's UnsupportedColumnar fallback to the
    wire-level merge)."""
    from tempo_tpu.backend import MemBackend
    from tempo_tpu.db.compactor import CompactionJob, CompactorConfig, compact
    from tempo_tpu.db.search import search_block

    backend = MemBackend()
    old_traces = make_traces(20, seed=21, n_spans=4)
    old_meta = _old_layout_block(backend, old_traces)

    new_traces = make_traces(20, seed=22, n_spans=4)
    new_meta = build_block_from_traces(backend, TENANT, new_traces)

    db = _db(tmp_path, backend)
    db.poll_now()

    # the old block reads fine: find every id, search without tres/struct
    blk = db.open_block(old_meta)
    assert not blk.pack.has("tres.res") and not blk.pack.has("span.parent_idx")
    for tid, t in old_traces:
        got = db.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == t.span_count()
    svc = next(iter(old_traces[0][1].resource_spans[0].resource.attrs.values()))
    r = search_block(blk, SearchRequest(tags={"service.name": str(svc)}, limit=100),
                     mode="host")
    assert r.inspected_spans == blk.meta.total_spans
    assert any(hit.trace_id == old_traces[0][0].hex() for hit in r.traces)
    # structural TraceQL must plan WITHOUT the parent column (host path);
    # testdata traces have server->client edges, so hits are guaranteed
    r2 = search_block(
        blk, SearchRequest(query='{ kind = server } > { kind = client }', limit=10),
        mode="host")
    assert r2.inspected_spans == blk.meta.total_spans

    # mixed-format compaction: columnar merge refuses (differing column
    # sets) and the wire fallback produces one complete modern block.
    # concat is disabled so the small level-0 inputs don't take the
    # compound-block shortcut (which legitimately keeps old layouts).
    res = compact(backend, CompactionJob(TENANT, [old_meta, new_meta]),
                  CompactorConfig(concat_small_input_bytes=0))
    assert res.traces_out == 40
    db.poll_now()
    merged = [m for m in db.blocklist.metas(TENANT) if m.compaction_level >= 1]
    assert len(merged) >= 1
    mblk = db.open_block(merged[0])
    assert mblk.pack.has("tres.res") and mblk.pack.has("span.parent_idx")
    for tid, t in old_traces + new_traces:
        got = db.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == t.span_count()


def test_compound_block_mixed_layout_compat(tmp_path):
    """The no-decode CONCAT compaction path applied to a rolling-upgrade
    mix (one old-layout sub-block without tres/parent_idx, one current)
    must yield a compound block that still answers find and search."""
    from tempo_tpu.backend import MemBackend
    from tempo_tpu.db.compactor import CompactionJob, CompactorConfig, compact

    backend = MemBackend()
    old_traces = make_traces(15, seed=31, n_spans=4)
    old_meta = _old_layout_block(backend, old_traces)
    new_traces = make_traces(15, seed=32, n_spans=4)
    new_meta = build_block_from_traces(backend, TENANT, new_traces)

    res = compact(backend, CompactionJob(TENANT, [old_meta, new_meta]),
                  CompactorConfig())  # small level-0 inputs -> concat path
    assert res.traces_out == 30

    db = _db(tmp_path, backend)
    db.poll_now()
    merged = [m for m in db.blocklist.metas(TENANT) if m.compaction_level >= 1]
    assert merged
    for tid, t in old_traces + new_traces:
        got = db.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == t.span_count()
    svc = old_traces[0][1].resource_spans[0].resource.attrs["service.name"]
    r = db.search(TENANT, SearchRequest(tags={"service.name": str(svc)}, limit=100))
    assert r.inspected_spans >= 30 * 4
    # the OLD-layout sub-block's matching trace must be among the hits
    assert any(hit.trace_id == old_traces[0][0].hex() for hit in r.traces)
