"""Contract-analysis families as tier-1 gates: the config registry
round-trips against the live tree, deliberately broken telemetry /
config / resilience trees fail --strict, and the interprocedural lock
graph reports its witness path exactly.

tests/test_analysis.py owns the corpus-vs-EXPECT exactness and the
live-tree cleanliness gate; this file owns the *semantics* of the new
families -- each synthetic tree here is the minimal reproduction of the
production failure its rule exists to prevent.
"""

from __future__ import annotations

import ast
import json
import re
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

import tempo_tpu
from tempo_tpu import config_registry
from tempo_tpu.analysis import run_analysis
from tempo_tpu.analysis.__main__ import main as analysis_main

PKG_ROOT = Path(tempo_tpu.__file__).resolve().parent
MINITREE = Path(__file__).resolve().parent / "analysis_fixtures" / "minitree"
ENV_RE = re.compile(r"^TEMPO_[A-Z0-9_]+$")


# ------------------------------------------------------ config registry
def test_registry_round_trip_against_live_tree():
    """Both directions of the config contract, checked at runtime the
    same way the analyzer checks them statically: every TEMPO_* literal
    the package spells is registered, and every registered knob is
    spelled somewhere outside the registry."""
    reads: set[str] = set()
    for p in PKG_ROOT.rglob("*.py"):
        if "__pycache__" in p.parts or p.name == "config_registry.py":
            continue
        for n in ast.walk(ast.parse(p.read_text(encoding="utf-8"))):
            if (isinstance(n, ast.Constant) and isinstance(n.value, str)
                    and ENV_RE.match(n.value)):
                reads.add(n.value)
    registered = set(config_registry.KNOBS)
    assert reads - registered == set(), "unregistered reads"
    assert registered - reads == set(), "dead registry entries"


def test_registry_typed_readers(monkeypatch):
    monkeypatch.delenv("TEMPO_BATCH_MAX", raising=False)
    assert config_registry.get_int("TEMPO_BATCH_MAX") == 16  # default
    monkeypatch.setenv("TEMPO_BATCH_MAX", "4")
    assert config_registry.get_int("TEMPO_BATCH_MAX") == 4
    monkeypatch.setenv("TEMPO_BATCH", "false")
    assert config_registry.get_bool("TEMPO_BATCH") is False
    monkeypatch.setenv("TEMPO_SLO_EVAL_S", "2.5")
    assert config_registry.get_float("TEMPO_SLO_EVAL_S") == 2.5
    with pytest.raises(KeyError):
        config_registry.get("TEMPO_NOT_A_KNOB")


def test_every_knob_has_type_default_doc():
    for name, (typ, default, doc) in config_registry.KNOBS.items():
        assert ENV_RE.match(name), name
        assert typ in ("bool", "int", "float", "str", "path"), name
        assert isinstance(default, str), name
        assert doc.strip(), f"{name} has no doc line"


def test_undeclared_env_read_fails_strict(tmp_path):
    (tmp_path / "config_registry.py").write_text("KNOBS = {}\n")
    svc = tmp_path / "services"
    svc.mkdir()
    svc.joinpath("reader.py").write_text(textwrap.dedent("""\
        import os


        def knob() -> str:
            return os.environ.get("TEMPO_SNEAKY_FLAG", "")
    """))
    assert analysis_main([str(tmp_path), "--strict"]) == 1
    report = run_analysis(tmp_path)
    assert [f.rule for f in report.findings] == ["env-unregistered"]


# ---------------------------------------------------- telemetry contract
def _telemetry_tree(tmp_path: Path, alert_family: str) -> Path:
    svc = tmp_path / "services"
    svc.mkdir()
    svc.joinpath("emit.py").write_text(textwrap.dedent("""\
        from util.metrics import Counter

        PUSHES = Counter("tempo_t_pushes_total")
    """))
    ops = tmp_path / "ops"
    ops.mkdir()
    ops.joinpath("alerts.yaml").write_text(textwrap.dedent(f"""\
        groups:
          - name: t
            rules:
              - alert: TPushesStalled
                expr: rate({alert_family}[5m]) == 0
    """))
    return tmp_path


def test_broken_alerts_yaml_fails_strict(tmp_path):
    """An alert expression naming a family nothing emits is an alert
    that can never fire: --strict must reject the tree."""
    root = _telemetry_tree(tmp_path, "tempo_t_ghost_total")
    report = run_analysis(root)
    assert [(f.file, f.rule) for f in report.findings] == [
        ("ops/alerts.yaml", "alert-unknown-metric")]
    assert analysis_main([str(root), "--strict"]) == 1


def test_matching_alerts_yaml_is_clean(tmp_path):
    root = _telemetry_tree(tmp_path, "tempo_t_pushes_total")
    assert run_analysis(root).findings == []


def test_live_ops_files_reference_only_emitted_families():
    """The shipped alerts.yaml / dashboard reference real families (the
    run_analysis-level restatement of the acceptance criterion)."""
    report = run_analysis(PKG_ROOT)
    bad = [f for f in report.findings
           if f.rule in ("alert-unknown-metric", "dashboard-unknown-metric")]
    assert bad == [], [f.render() for f in bad]


# -------------------------------------------------- resilience contract
def test_deadline_less_rpc_fails_strict(tmp_path):
    svc = tmp_path / "services"
    svc.mkdir()
    svc.joinpath("leg.py").write_text(textwrap.dedent("""\
        import urllib.request


        def poke(url: str) -> bytes:
            return urllib.request.urlopen(url).read()
    """))
    report = run_analysis(tmp_path)
    assert [f.rule for f in report.findings] == ["rpc-no-deadline"]
    assert analysis_main([str(tmp_path), "--strict"]) == 1
    # the fix the hint prescribes makes the same tree clean
    svc.joinpath("leg.py").write_text(textwrap.dedent("""\
        import urllib.request


        def poke(url: str) -> bytes:
            return urllib.request.urlopen(url, timeout=5.0).read()
    """))
    assert run_analysis(tmp_path).findings == []


def test_live_seam_registry_is_complete():
    """chaos/plane.py SEAM_MODULES covers every declared site and every
    urlopen in resilience scope (the fault-certification reachability
    contract)."""
    from tempo_tpu.chaos import plane

    claimed = {s for sites in plane.SEAM_MODULES.values() for s in sites}
    assert claimed == set(plane.SITES), "seam registry out of sync"
    report = run_analysis(PKG_ROOT)
    gaps = [f for f in report.findings if f.rule == "chaos-seam-gap"]
    assert gaps == [], [f.render() for f in gaps]


# ------------------------------------------------------------ lock graph
def test_lock_cycle_witness_path_exact():
    """The fixture cycle reports once, anchored on the A side, with the
    full witness call path -- the part of the finding an engineer
    debugging a deadlock actually needs."""
    report = run_analysis(MINITREE)
    cycles = [f for f in report.findings if f.rule == "lock-order-global"]
    assert len(cycles) == 1
    f = cycles[0]
    assert (f.file, f.line) == ("db/lock_cycle_a.py", 14)
    assert f.message == (
        "lock cycle db.lock_cycle_a.LOCK_A -> db.lock_cycle_b.LOCK_B "
        "-> db.lock_cycle_a.LOCK_A; witness call path: "
        "db.lock_cycle_a.path_ab -> db.lock_cycle_b.helper_b")


def test_lexical_single_module_cycle_left_to_per_module_rule(tmp_path):
    """A lexically inverted pair inside one file belongs to the
    per-module lock-order rule; the global pass must not double-report
    it."""
    svc = tmp_path / "services"
    svc.mkdir()
    svc.joinpath("inverted.py").write_text(textwrap.dedent("""\
        import threading

        LOCK_X = threading.Lock()
        LOCK_Y = threading.Lock()


        def xy():
            with LOCK_X:
                with LOCK_Y:
                    pass


        def yx():
            with LOCK_Y:
                with LOCK_X:
                    pass
    """))
    report = run_analysis(tmp_path)
    rules = sorted(f.rule for f in report.findings)
    assert "lock-order" in rules
    assert "lock-order-global" not in rules


# -------------------------------------------------------------- CLI gates
def test_live_strict_subprocess_all_families():
    """`python -m tempo_tpu.analysis --strict --json` exits 0 on the
    repo with every family having actually run (family_ms proves the
    pass executed, not just registered)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tempo_tpu.analysis", "--strict", "--json"],
        cwd=PKG_ROOT.parent, capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["schema_version"] == 2
    for family in ("kernel", "concurrency", "config", "telemetry",
                   "resilience", "lockgraph", "pragma"):
        assert family in out["family_ms"], family


def test_diff_mode_scopes_and_falls_back(tmp_path, capsys):
    """--diff against a bogus rev falls back to the full (strict-clean)
    run rather than silently checking nothing."""
    assert analysis_main(["--diff", "definitely-not-a-rev",
                          "--strict"]) == 0
    err = capsys.readouterr().err
    assert "falling back to the full run" in err
