"""Randomized three-way TraceQL equivalence: host engine vs device
engine vs the wire-model oracle (traceql.hosteval.trace_matches).

The hand-picked equivalence tests cover known-interesting queries; this
fuzzer composes queries from the grammar's building blocks over random
blocks and demands the two production engines and the oracle agree on
the EXACT trace set for every one. Deterministic seeds (no wall-clock
randomness); the generator is biased toward values that exist in the
testdata vocabulary so most queries have non-trivial match sets.
"""

import random

import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.db.search import SearchRequest, search_block
from tempo_tpu.traceql.hosteval import trace_matches
from tempo_tpu.traceql.parser import parse
from tempo_tpu.util.testdata import make_traces

TENANT = "t1"

_STR_FIELDS = [
    ("span.http.method", ["GET", "POST", "PUT", "nope"]),
    ("span.component", ["net/http", "grpc", "sql", "nope"]),
    ("resource.service.name", ["db", "auth", "frontend", "nope"]),
    (".service.name", ["db", "payments", "nope"]),
    ("name", ["GET /api", "db.query", "render", "nope"]),
]
_INT_FIELDS = [
    ("span.http.status_code", [200, 404, 500, 123]),
]
_DUR = ["1ms", "100ms", "1s", "1500ms"]
_KINDS = ["server", "client", "internal", "producer", "consumer"]


def _leaf(rng: random.Random) -> str:
    roll = rng.random()
    if roll < 0.35:
        f, vals = rng.choice(_STR_FIELDS)
        op = rng.choice(["=", "!=", "=~", "!~"])
        v = rng.choice(vals)
        if op in ("=~", "!~"):
            v = v[: max(1, len(v) // 2)]  # prefix-ish regex
            return f'{f} {op} "{v}.*"'
        return f'{f} {op} "{v}"'
    if roll < 0.5:
        f, vals = rng.choice(_INT_FIELDS)
        return f"{f} {rng.choice(['=', '!=', '<', '<=', '>', '>='])} {rng.choice(vals)}"
    if roll < 0.65:
        return f"duration {rng.choice(['>', '>=', '<', '<='])} {rng.choice(_DUR)}"
    if roll < 0.75:
        return f"kind = {rng.choice(_KINDS)}"
    if roll < 0.85:
        return f"status {rng.choice(['=', '!='])} error"
    if roll < 0.93:
        return f"traceDuration {rng.choice(['>', '<'])} {rng.choice(_DUR)}"
    return f'span.cache.hit = {rng.choice(["true", "false"])}'


def _expr(rng: random.Random, depth: int = 0) -> str:
    if depth >= 2 or rng.random() < 0.45:
        return _leaf(rng)
    op = rng.choice(["&&", "||"])
    lhs, rhs = _expr(rng, depth + 1), _expr(rng, depth + 1)
    return f"({lhs} {op} {rhs})" if rng.random() < 0.5 else f"{lhs} {op} {rhs}"


def _query(rng: random.Random) -> str:
    q = f"{{ {_expr(rng)} }}"
    roll = rng.random()
    if roll < 0.12:
        q = f"{q} {rng.choice(['>', '>>', '~'])} {{ {_leaf(rng)} }}"
    elif roll < 0.3:
        # spanset combinators: each leaf keeps its OWN same-span group,
        # and mixed span/trace ORs inside a leaf must keep verification
        # (a fuzz-found planner regression lost exactly that flag)
        q = f"{q} {rng.choice(['&&', '||'])} {{ {_expr(rng)} }}"
    elif roll < 0.38:
        agg = rng.choice([
            f"count() {rng.choice(['>', '>=', '<', '='])} {rng.choice([0, 1, 2, 5])}",
            f"avg(duration) {rng.choice(['>', '<'])} {rng.choice(_DUR)}",
            f"max(span.http.status_code) {rng.choice(['>=', '<'])} 500",
        ])
        q = f"{q} | {agg}"
    return q


# Tier-1 runs a deterministic PREFIX of each seed's query stream (the
# first _QUICK cases); the full-depth streams ride in tier-2 under the
# slow marker. Same seeds, same generator state, so a quick-run failure
# always reproduces at full depth -- the split only moves wall-clock
# (device-engine compiles dominate at ~7s/query) out of the 870s tier-1
# budget.
_QUICK = 12
_FULL = 40


def _depths(seeds):
    for s in seeds:
        yield pytest.param(s, _QUICK, id=f"{s}")
        yield pytest.param(s, _FULL, id=f"{s}-full", marks=pytest.mark.slow)


@pytest.mark.parametrize("seed,n_cases", _depths([101, 202, 303]))
def test_fuzz_host_device_oracle_agree(tmp_path, seed, n_cases):
    rng = random.Random(seed)
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w")), backend=MemBackend())
    traces = make_traces(50, seed=seed, n_spans=8)
    db.write_block(TENANT, traces)
    blk = db.open_block(db.blocklist.metas(TENANT)[0])

    checked = 0
    for _ in range(n_cases):
        q = _query(rng)
        ast = parse(q)  # generator only emits grammar-valid queries
        want = {tid.hex() for tid, t in traces if trace_matches(ast, t)}
        got_h = {t.trace_id for t in search_block(
            blk, SearchRequest(query=q, limit=1000), mode="host").traces}
        assert got_h == want, (q, sorted(got_h ^ want)[:4])
        got_d = {t.trace_id for t in search_block(
            blk, SearchRequest(query=q, limit=1000), mode="device").traces}
        assert got_d == want, (q, sorted(got_d ^ want)[:4])
        checked += 1
    assert checked == n_cases


@pytest.mark.parametrize("seed,n_cases", _depths([404, 505]))
def test_fuzz_mesh_path_agrees(tmp_path, seed, n_cases):
    """Fourth leg: the stacked MESH program (blocks over dp, span AND
    generic-attr rows over sp, structural ops via all_gathered parent
    tables, parallel/search.py) against the wire oracle on the
    8-virtual-device mesh."""
    from tempo_tpu.db.search import search_blocks_device

    rng = random.Random(seed)
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w")), backend=MemBackend())
    traces1 = make_traces(30, seed=seed, n_spans=6)
    traces2 = make_traces(30, seed=seed + 1, n_spans=6)
    db.write_block(TENANT, traces1)
    # second block written DOWN-LEVEL (vtpu1, JSON footer): the mesh
    # program must stack mixed-version blocks transparently
    from tempo_tpu.block.builder import BlockBuilder, write_block

    b = BlockBuilder(TENANT)
    for tid, t in sorted(traces2):
        b.add_trace(tid, t)
    m1 = write_block(db.backend, b.finalize(), version="vtpu1")
    db.blocklist.update(TENANT, add=[m1])
    blocks = [db.open_block(m) for m in db.blocklist.metas(TENANT)]
    assert {b.meta.version for b in blocks} == {"vtpu1", "vtpu2"}
    assert db.mesh.devices.size == 8
    all_traces = traces1 + traces2

    mesh_ran = 0
    for _ in range(n_cases):
        q = _query(rng)
        ast = parse(q)
        want = {tid.hex() for tid, t in all_traces if trace_matches(ast, t)}
        resp = search_blocks_device(blocks, SearchRequest(query=q, limit=1000), db.mesh)
        if resp is None:
            continue
        got = {t.trace_id for t in resp.traces}
        assert got == want, (q, sorted(got ^ want)[:4])
        mesh_ran += 1
    assert mesh_ran >= n_cases // 2, f"only {mesh_ran} queries ran the mesh path"
