"""tempo_tpu.analysis: the static checker as a tier-1 gate.

Two directions keep each other honest:
  * the LIVE tree must pass --strict (a new violation fails the suite
    here, not in production);
  * the seeded-violation corpus must keep every rule firing on exactly
    the lines its `# EXPECT: rule` markers claim -- so a refactor that
    quietly lobotomizes a pass also fails.
"""

from __future__ import annotations

import json
import re
import subprocess
import sys
import textwrap
from pathlib import Path

import tempo_tpu
from tempo_tpu.analysis import RULES, run_analysis
from tempo_tpu.analysis.__main__ import main as analysis_main

PKG_ROOT = Path(tempo_tpu.__file__).resolve().parent
FIXTURES = Path(__file__).resolve().parent / "analysis_fixtures"
MINITREE = FIXTURES / "minitree"

EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([a-z\-]+(?:\s*,\s*[a-z\-]+)*)")


def _expected_findings() -> set[tuple[str, int, str]]:
    # yaml/json too: the telemetry contract anchors findings in the ops
    # files themselves (EXPECT rides inside a string value there)
    paths = [p for pat in ("*.py", "*.yaml", "*.json")
             for p in MINITREE.rglob(pat)]
    out = set()
    for p in sorted(paths):
        rel = p.relative_to(MINITREE).as_posix()
        for lineno, line in enumerate(p.read_text().splitlines(), start=1):
            m = EXPECT_RE.search(line)
            if m:
                for rule in m.group(1).split(","):
                    out.add((rel, lineno, rule.strip()))
    return out


def test_live_tree_is_clean_under_strict():
    """The acceptance gate: the shipped tree carries zero unsuppressed
    violations and zero parse failures."""
    report = run_analysis(PKG_ROOT)
    assert not report.parse_errors, [f.render() for f in report.parse_errors]
    assert not report.findings, [f.render() for f in report.findings]
    # sanity: the scan actually covered the tree
    assert report.files_scanned > 80


def test_seeded_corpus_fires_every_rule_exactly():
    """Each EXPECT marker produces exactly one finding on its line, and
    nothing unmarked fires: both false negatives AND false positives in
    the passes break this test."""
    expected = _expected_findings()
    report = run_analysis(MINITREE)
    got = {(f.file, f.line, f.rule) for f in report.findings}
    assert got == expected, (
        f"unexpected: {sorted(got - expected)}; missing: {sorted(expected - got)}")
    # the corpus keeps EVERY registered rule under test except
    # parse-error (covered separately below): a new rule lands with its
    # fixture or this fails
    assert {r for _, _, r in expected} == set(RULES) - {"parse-error"}


def test_ignore_pragma_suppresses_and_counts(tmp_path):
    src = textwrap.dedent("""\
        _cache = {}


        def a(k):
            _cache[k] = 1  # tempo: ignore[global-mutation-unlocked] fixture
    """)
    f = tmp_path / "mod.py"
    f.write_text(src)
    report = run_analysis(tmp_path)
    assert not report.findings
    assert report.suppressed == 1
    # without the pragma the same code must fire
    f.write_text(src.replace("  # tempo: ignore[global-mutation-unlocked] fixture", ""))
    report = run_analysis(tmp_path)
    assert [f_.rule for f_ in report.findings] == ["global-mutation-unlocked"]


def test_parse_error_exits_nonzero_unless_skipped(tmp_path, capsys):
    bad = tmp_path / "broken.py"
    bad.write_text("def oops(:\n    pass\n")
    assert analysis_main([str(tmp_path)]) == 2
    capsys.readouterr()
    # the escape hatch still REPORTS the file, it just doesn't gate
    assert analysis_main([str(tmp_path), "--skip-unparsable", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["parse_errors"] and out["parse_errors"][0]["rule"] == "parse-error"


def test_json_report_shape(capsys):
    assert analysis_main([str(MINITREE), "--json"]) == 0  # not strict
    out = json.loads(capsys.readouterr().out)
    assert out["files_scanned"] == 28
    assert set(out["rules"]) == set(RULES)
    sample = out["findings"][0]
    assert {"file", "line", "rule", "message", "hint", "severity"} <= set(sample)
    assert "wall_ms" in out
    assert out["schema_version"] == 2
    assert out["family_ms"]  # per-family timing rides along


def test_strict_and_baseline_workflow(tmp_path, capsys):
    """--strict fails on the corpus; a baseline built from the JSON
    report (the CI diff workflow) makes the same run pass."""
    assert analysis_main([str(MINITREE), "--strict"]) == 1
    capsys.readouterr()
    assert analysis_main([str(MINITREE), "--json"]) == 0
    findings = json.loads(capsys.readouterr().out)["findings"]
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"findings": findings}))
    assert analysis_main(
        [str(MINITREE), "--strict", "--baseline", str(baseline)]) == 0


def test_repo_baseline_file_is_valid():
    """ANALYSIS_BASELINE.json stays parseable and EMPTY: new violations
    must be fixed or pragma'd with a reason, not silently baselined."""
    path = PKG_ROOT.parent / "ANALYSIS_BASELINE.json"
    data = json.loads(path.read_text())
    assert data["findings"] == []


def test_cli_module_entrypoint_strict_clean():
    """`python -m tempo_tpu.analysis --strict` (the acceptance command)
    exits 0 on the repo."""
    proc = subprocess.run(
        [sys.executable, "-m", "tempo_tpu.analysis", "--strict"],
        cwd=PKG_ROOT.parent, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_twin_registry_resolves_at_runtime():
    """The registry the checker trusts statically must also import and
    resolve dynamically: every dotted path names a real callable."""
    import importlib

    from tempo_tpu.ops.twins import DEVICE_HOST_TWINS

    for side in list(DEVICE_HOST_TWINS) + list(DEVICE_HOST_TWINS.values()):
        mod_path, _, func = side.rpartition(".")
        mod = importlib.import_module(f"tempo_tpu.{mod_path}")
        assert callable(getattr(mod, func)), side
