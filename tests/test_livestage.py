"""Live-head device engine (ops/livestage + db/live_engine) and the
progressive streaming search plane.

The load-bearing test is the randomized-interleaving differential: the
device live engine, its numpy twin, and the host index oracle must
return BIT-IDENTICAL results across arbitrary push/cut/flush/rotate
interleavings -- the oracle is the legacy per-trace index walk, so any
divergence is a staging bug, not a test artifact. The streaming test
pins the acceptance contract: the first partial arrives before the
slowest shard completes."""

import random
import threading
import time

import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.db.search import SearchRequest
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.db.wal import WAL
from tempo_tpu.services.ingester import Ingester, IngesterConfig
from tempo_tpu.services.overrides import Overrides
from tempo_tpu.util.kerneltel import TEL
from tempo_tpu.util.testdata import make_trace, make_trace_id, make_traces
from tempo_tpu.wire.segment import segment_for_write

TENANT = "live-t"


@pytest.fixture()
def ingester(tmp_path):
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dw")), backend=MemBackend())
    ing = Ingester(WAL(str(tmp_path / "w")), db, Overrides(), IngesterConfig())
    yield ing
    db.close()


def _push_trace(inst, tid, tr):
    lo, hi = tr.time_range_nanos()
    s, e = lo // 10**9, hi // 10**9 + 1
    inst.push_segments([(tid, s, e, segment_for_write(tr, s, e))])


def _dump(resp):
    """Full wire-relevant tuple per result: bit-identity means THESE
    are equal, ordering included."""
    return [(t.trace_id, t.start_time_unix_nano, t.root_service_name,
             t.root_trace_name, t.duration_ms) for t in resp.traces]


QUERIES = [
    SearchRequest(limit=200),
    SearchRequest(limit=3),
    SearchRequest(tags={"service.name": "db"}, limit=200),
    SearchRequest(tags={"name": "GET /api"}, limit=200),
    SearchRequest(tags={"service.name": "db", "name": "db.query"}, limit=200),
    SearchRequest(tags={"component": "sql"}, limit=200),
    SearchRequest(tags={"http.method": "get"}, limit=200),  # value lowering
    SearchRequest(tags={"no.such.key": "x"}, limit=200),
    SearchRequest(min_duration_ms=500, limit=200),
    SearchRequest(max_duration_ms=500, limit=200),
    SearchRequest(min_duration_ms=100, max_duration_ms=1500, limit=5),
    SearchRequest(start=1_700_000_000 - 50, end=1_700_000_000 + 50, limit=200),
    SearchRequest(start=1_900_000_000, limit=200),  # nothing that new
    SearchRequest(query='{ resource.service.name = "db" }', limit=200),
    SearchRequest(query='{ span.http.status_code = 500 }', limit=200),
    SearchRequest(tags={"service.name": "auth"}, min_duration_ms=200, limit=4),
]


def _assert_engines_identical(inst, monkeypatch, queries=QUERIES):
    for i, req in enumerate(queries):
        oracle = inst.search_live_index(req)
        monkeypatch.setenv("TEMPO_LIVE_ENGINE", "device")
        dev = inst.search_live(req)
        monkeypatch.setenv("TEMPO_LIVE_ENGINE", "host")
        host = inst.search_live(req)
        monkeypatch.delenv("TEMPO_LIVE_ENGINE")
        assert _dump(dev) == _dump(oracle), f"device != oracle on query {i}"
        assert _dump(host) == _dump(oracle), f"host twin != oracle on query {i}"


def test_differential_randomized_interleavings(ingester, monkeypatch):
    """Device live search ≡ host search_live across randomized
    push / late-segment / cut / flush / rotate interleavings."""
    inst = ingester.instance(TENANT)
    rng = random.Random(421)
    known_tids = []
    for step in range(60):
        op = rng.random()
        if op < 0.55 or not known_tids:
            # push a fresh trace; spread base times so the top-k key
            # covers both distinct-second and tied-second regimes
            tid = make_trace_id(rng)
            base = 1_700_000_000_000_000_000 + rng.randrange(0, 4) * 10**9 * 60
            tr = make_trace(rng, trace_id=tid, n_spans=rng.randrange(1, 6),
                            base_time_ns=base)
            _push_trace(inst, tid, tr)
            known_tids.append(tid)
        elif op < 0.72:
            # late segment for an existing trace (possibly already cut)
            tid = rng.choice(known_tids)
            tr = make_trace(rng, trace_id=tid, n_spans=rng.randrange(1, 4))
            _push_trace(inst, tid, tr)
        elif op < 0.85:
            inst.cut_complete_traces(force=rng.random() < 0.5)
        else:
            # flush cut traces into a backend block (retires their rows)
            # or rotate an aged head
            inst.cut_block_if_ready(force=True)
            known_tids = [t for t in known_tids
                          if t in inst.live or t in inst.cut or t in inst.flushing]
        if step % 6 == 5:
            _assert_engines_identical(inst, monkeypatch)
    # drain completely: the staged head must empty out too
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)
    _assert_engines_identical(inst, monkeypatch)
    monkeypatch.setenv("TEMPO_LIVE_ENGINE", "device")
    assert inst.search_live(SearchRequest(limit=200)).traces == []


def test_differential_flush_failure_restore(ingester, monkeypatch):
    """A failed block flush restores the cut set; the staged head must
    keep answering identically through the failure and the retry."""
    inst = ingester.instance(TENANT)
    for tid, tr in make_traces(12, seed=3, n_spans=4):
        _push_trace(inst, tid, tr)
    inst.cut_complete_traces(force=True)
    orig = ingester.db.write_block
    monkeypatch.setattr(ingester.db, "write_block",
                        lambda *a, **k: (_ for _ in ()).throw(OSError("backend down")))
    with pytest.raises(OSError):
        inst.cut_block_if_ready(force=True)
    _assert_engines_identical(inst, monkeypatch)
    monkeypatch.setattr(ingester.db, "write_block", orig)
    inst.cut_block_if_ready(force=True)
    _assert_engines_identical(inst, monkeypatch)


def test_find_differential(ingester, monkeypatch):
    inst = ingester.instance(TENANT)
    traces = make_traces(10, seed=11, n_spans=3)
    for tid, tr in traces:
        _push_trace(inst, tid, tr)
    for tid, tr in traces[:4]:
        monkeypatch.setenv("TEMPO_LIVE_FIND_DEVICE", "1")
        dev = inst.find_trace_by_id(tid)
        monkeypatch.delenv("TEMPO_LIVE_FIND_DEVICE")
        host = inst.find_trace_by_id(tid)
        assert dev is not None and host is not None
        assert dev.span_count() == host.span_count() == tr.span_count()
    monkeypatch.setenv("TEMPO_LIVE_FIND_DEVICE", "1")
    assert inst.find_trace_by_id(b"\x01" * 16) is None
    monkeypatch.delenv("TEMPO_LIVE_FIND_DEVICE")


def test_delta_upload_moves_only_new_rows(ingester, monkeypatch):
    """The second refresh after a small push must move a small delta,
    not re-upload the whole head (the PCIe amortization the subsystem
    exists for)."""
    monkeypatch.setenv("TEMPO_LIVE_ENGINE", "device")
    inst = ingester.instance(TENANT)
    for tid, tr in make_traces(80, seed=5, n_spans=6):
        _push_trace(inst, tid, tr)
    s0 = TEL.livestage_stats()
    inst.search_live(SearchRequest(limit=10))
    s1 = TEL.livestage_stats()
    full_bytes = s1["delta_bytes"] - s0["delta_bytes"]
    assert full_bytes > 0 and s1["full_uploads"] > s0["full_uploads"]
    # two more traces: a delta append, NOT another full upload
    for tid, tr in make_traces(2, seed=99, n_spans=2):
        _push_trace(inst, tid, tr)
    inst.search_live(SearchRequest(limit=10))
    s2 = TEL.livestage_stats()
    delta_bytes = s2["delta_bytes"] - s1["delta_bytes"]
    assert s2["full_uploads"] == s1["full_uploads"]
    assert 0 < delta_bytes < full_bytes / 4
    # an unchanged head re-serves the same generation: no upload at all
    inst.search_live(SearchRequest(limit=10))
    s3 = TEL.livestage_stats()
    assert s3["delta_bytes"] == s2["delta_bytes"]
    assert s3["generation"] == s2["generation"]


def test_staging_lag_and_state_telemetry(ingester, monkeypatch):
    monkeypatch.setenv("TEMPO_LIVE_ENGINE", "device")
    inst = ingester.instance(TENANT)
    lag0 = TEL.livestage_stats()["lag_count"]
    for tid, tr in make_traces(5, seed=8, n_spans=3):
        _push_trace(inst, tid, tr)
    inst.search_live(SearchRequest(limit=10))
    st = TEL.livestage_stats()
    assert st["lag_count"] >= lag0 + 5
    assert st["slots"].get("live", 0) == 5
    routing = TEL.routing_counts()
    assert any(layer == "search_live" and engine == "device"
               for (layer, engine, _r) in routing)


def test_traceql_decode_cached_when_unchanged(ingester, monkeypatch):
    """Satellite regression: repeated TraceQL live searches on an
    unchanged trace must not re-run combine_traces over every segment
    (the decoded trace is cached alongside the search index)."""
    import tempo_tpu.services.ingester as ing_mod

    inst = ingester.instance(TENANT)
    for tid, tr in make_traces(6, seed=21, n_spans=4):
        _push_trace(inst, tid, tr)
    req = SearchRequest(query='{ resource.service.name = "db" }', limit=50)
    monkeypatch.setenv("TEMPO_LIVE_ENGINE", "index")
    inst.search_live(req)  # builds index + cached decode
    calls = []
    orig = ing_mod.segment_to_trace
    monkeypatch.setattr(ing_mod, "segment_to_trace",
                        lambda seg: calls.append(1) or orig(seg))
    inst.search_live(req)
    assert not calls, "unchanged live traces were re-decoded"
    # a new segment invalidates exactly that trace
    tid0 = next(iter(inst.live))
    _push_trace(inst, tid0, make_trace(3, trace_id=tid0, n_spans=2))
    inst.search_live(req)
    assert len(calls) == len(inst.live[tid0].segments)


def test_compaction_rebuild_after_churn(ingester, monkeypatch):
    """Repeated push->flush churn retires most slots; the stager must
    compact its tails and keep answering identically."""
    inst = ingester.instance(TENANT)
    monkeypatch.setenv("TEMPO_LIVE_ENGINE", "device")
    for round_i in range(4):
        for tid, tr in make_traces(15, seed=100 + round_i, n_spans=3):
            _push_trace(inst, tid, tr)
        inst.search_live(SearchRequest(limit=5))  # stage this round
        inst.cut_complete_traces(force=True)
        inst.cut_block_if_ready(force=True)
        inst.search_live(SearchRequest(limit=5))  # observe retirement
    eng = inst.live_engine
    assert eng.stager.dead_slots <= eng.stager.n_slots  # compacted at least once
    assert eng.stager.n_slots < 60  # 4x15 pushed; dead rounds were reclaimed
    monkeypatch.delenv("TEMPO_LIVE_ENGINE")
    for tid, tr in make_traces(10, seed=777, n_spans=3):
        _push_trace(inst, tid, tr)
    _assert_engines_identical(inst, monkeypatch)


def test_concurrent_push_and_search_no_slot_thrash(ingester, monkeypatch):
    """Concurrent pushes + searches must never retire-and-restage a
    live trace: the engine serializes the groups snapshot with the
    stager reconcile, so a stale snapshot can't reach refresh after a
    newer one (dead slots only ever come from real cut/flush)."""
    monkeypatch.setenv("TEMPO_LIVE_ENGINE", "device")
    inst = ingester.instance(TENANT)
    stop = threading.Event()
    errors: list = []

    def pusher(seed):
        rng = random.Random(seed)
        try:
            while not stop.is_set():
                tid = make_trace_id(rng)
                _push_trace(inst, tid, make_trace(rng, trace_id=tid, n_spans=2))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def searcher():
        try:
            while not stop.is_set():
                inst.search_live(SearchRequest(limit=10))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = ([threading.Thread(target=pusher, args=(i,)) for i in range(2)]
               + [threading.Thread(target=searcher) for _ in range(3)])
    for t in threads:
        t.start()
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(10)
    assert not errors, errors
    assert inst.live_engine.stager.dead_slots == 0
    _assert_engines_identical(inst, monkeypatch)


# ------------------------------------------------------------ streaming


@pytest.fixture()
def pipeline(tmp_path):
    from tempo_tpu.ring.ring import InMemoryKV, Lifecycler, Ring
    from tempo_tpu.services.distributor import Distributor
    from tempo_tpu.services.frontend import Frontend
    from tempo_tpu.services.querier import Querier

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dw")), backend=MemBackend())
    wal = WAL(str(tmp_path / "w"))
    ov = Overrides()
    ing = Ingester(wal, db, ov, IngesterConfig(max_trace_idle_s=0.0,
                                               max_block_age_s=0.0))
    kv = InMemoryKV()
    lc = Lifecycler(kv, "ing", "i0")
    lc.join()
    ring = Ring(kv, "ing", replication_factor=1)
    clients = {lc.desc.addr: ing}
    dist = Distributor(ring, clients.__getitem__, ov)
    q = Querier(db, ring, clients.__getitem__)
    fe = Frontend(q, n_workers=4)
    yield db, ing, dist, q, fe
    fe.stop()
    db.close()


def test_stream_first_partial_before_slowest_shard(pipeline):
    """Acceptance: stream=true delivers a newest-first partial BEFORE
    full query completion -- the ingester leg lands while a backend
    shard is still running."""
    db, ing, dist, q, fe = pipeline
    traces = make_traces(20, seed=5, n_spans=4)
    for tid, tr in traces[:10]:
        dist.push(TENANT, tr.resource_spans)
    ing.sweep_all(force=True)  # 10 traces into a backend block
    for tid, tr in traces[10:]:
        dist.push(TENANT, tr.resource_spans)  # 10 stay live

    slow_done = threading.Event()
    orig = q.search_blocks

    def slow_search_blocks(tenant, metas, req):
        time.sleep(0.6)
        slow_done.set()
        return orig(tenant, metas, req)

    q.search_blocks = slow_search_blocks
    events = []
    for ev in fe.search_stream(TENANT, SearchRequest(limit=100)):
        events.append((slow_done.is_set(), ev))
    assert len(events) >= 2
    first_slow_seen, first = events[0]
    assert first_slow_seen is False, "first partial waited for the slowest shard"
    assert first["done"] is False and first["jobsCompleted"] < first["jobsTotal"]
    assert first["traces"], "the ingester partial carries the newest data"
    # partials are newest-first
    starts = [int(t["startTimeUnixNano"]) for t in first["traces"]]
    assert starts == sorted(starts, reverse=True)
    done_flag, final = events[-1]
    assert done_flag and final["done"] is True
    assert final["jobsCompleted"] == final["jobsTotal"]
    assert len(final["traces"]) == 20
    # the final streamed body matches the blocking response exactly
    blocking = fe.search(TENANT, SearchRequest(limit=100))
    assert final["traces"] == [t.to_dict() for t in blocking.traces]


def test_stream_failed_shard_degrades_not_fails(pipeline):
    db, ing, dist, q, fe = pipeline
    traces = make_traces(12, seed=6, n_spans=3)
    for tid, tr in traces[:6]:
        dist.push(TENANT, tr.resource_spans)
    ing.sweep_all(force=True)
    for tid, tr in traces[6:]:
        dist.push(TENANT, tr.resource_spans)

    def broken(tenant, metas, req):
        raise ValueError("shard poisoned")  # non-retryable

    q.search_blocks = broken
    out = list(fe.search_stream(TENANT, SearchRequest(limit=100)))
    assert out[-1]["done"] is True
    assert len(out[-1]["traces"]) == 6  # ingester leg still answered


def test_stream_http_chunked_sse(tmp_path):
    """End to end over HTTP: /api/search?stream=sse emits chunked SSE
    events, final event identical to the blocking response body."""
    import http.client
    import json as _json

    from tempo_tpu.services.app import App, AppConfig

    app = App(AppConfig(target="all", http_port=0,
                        storage_path=str(tmp_path / "data"),
                        enable_generator=False))
    app.start()
    app.serve_http(background=True)
    try:
        port = app.http_server.server_address[1]
        for tid, tr in make_traces(8, seed=9, n_spans=3):
            app.distributor.push("single-tenant", tr.resource_spans)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/api/search?limit=50&stream=sse")
        r = conn.getresponse()
        assert r.status == 200
        assert r.headers["Content-Type"] == "text/event-stream"
        body = r.read().decode()
        events = [_json.loads(line[len("data: "):])
                  for line in body.split("\n") if line.startswith("data: ")]
        assert events and events[-1]["done"] is True
        assert len(events[-1]["traces"]) == 8
        conn.close()
    finally:
        app.stop()
