"""Device cost observability plane (util/costmodel + util/costledger).

Covers the acceptance surface end to end: XLA program-cost capture on
the CPU backend (skip-gated -- some backends return no cost analysis),
EXACT comm-walker byte counts on a synthetic shard_map program against
the documented ring model, HBM-ledger reconciliation vs the staged
cache's and live stager's own accounting, CostLedger round-trip +
corrupt-artifact fallback, ledger-backed `auto` find routing and
live-engine crossover seeding (env override wins), the struct-node
budget replication fix, and the /status/cost + /metrics surfaces of a
running app.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from tempo_tpu.util import costledger
from tempo_tpu.util.costmodel import COST, collective_comm_bytes
from tempo_tpu.util.kerneltel import TEL

TENANT = "cost-t"


@pytest.fixture(autouse=True)
def _fresh_plane():
    TEL.reset()  # also resets COST (launch/program tables)
    costledger.reset_for_tests()
    yield
    TEL.reset()
    costledger.reset_for_tests()


def _padded_filter_eval():
    """One tiny filter-kernel launch (padded to the 1024 floor)."""
    from tempo_tpu.ops.device import PAD_I32, pad_rows
    from tempo_tpu.ops.filter import Cond, Operands, T_SPAN, eval_block

    N, NB = 64, 1024
    cols = {
        "span.trace_sid": pad_rows(np.zeros(N, np.int32), NB, PAD_I32),
        "span.dur_us": pad_rows(np.arange(N, dtype=np.int32), NB, PAD_I32),
        "trace.span_off": pad_rows(np.asarray([0, N], np.int32), NB + 1,
                                   np.int32(N)),
    }
    conds = (Cond(target=T_SPAN, col="span.dur_us", op="ge"),)
    ops = Operands.build([(0, 10, 0, 0.0, 0.0)])
    return eval_block((("cond", 0), conds), cols, ops, N, 1, NB, NB, NB)


# ------------------------------------------------------- program capture


def test_cost_capture_filter_on_cpu():
    """A new filter compile lands a background cost-analysis row keyed
    (op, bucket): FLOPs + bytes accessed from XLA itself, peak temp
    from memory_analysis."""
    _padded_filter_eval()
    assert COST.drain(30), "cost capture worker did not drain"
    table = COST.program_table()
    row = table.get(("filter", "1024"))
    assert row is not None, sorted(table)
    if row["error"]:
        pytest.skip(f"cost analysis unavailable on this backend: {row['error']}")
    assert row["flops"] > 0
    assert row["bytes_accessed"] > 0
    assert row["launches"] >= 1
    # second launch of the same program: cache hit, no new capture, but
    # the launch counter moves
    _padded_filter_eval()
    assert COST.program_table()[("filter", "1024")]["launches"] >= 2


def test_reset_releases_pending_captures():
    """reset() with capture specs still queued must release their
    pending counts -- a wedged counter would make every later drain()
    (and /status/cost) wait its full timeout forever."""
    from tempo_tpu.util.costmodel import ProgramSpec

    COST.enqueue("x", "1", ProgramSpec(None, (), {}, None, 1))
    COST.reset()
    assert COST.drain(5.0), "drain wedged after reset with queued captures"
    # the worker itself survives a broken spec (whichever side of the
    # race it landed on) and keeps serving later captures
    _padded_filter_eval()
    assert COST.drain(30)
    assert ("filter", "1024") in COST.program_table()


def test_comm_walker_exact_bytes_on_synthetic_shard_map():
    """The documented ring model, checked to the byte on a hand-built
    shard_map program over the 8-device mesh (dp=2 x sp=4)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from tempo_tpu.parallel.mesh import make_mesh, smap

    mesh = make_mesh(8)  # dp 2 x sp 4
    k = mesh.shape["sp"]
    groups = mesh.devices.size // k  # independent sp-groups (= dp)
    assert (k, groups) == (4, 2)

    def local(x):
        g = jax.lax.all_gather(x, "sp", axis=0, tiled=True)
        s = jax.lax.psum(x, "sp")
        r = jax.lax.psum_scatter(x, "sp", scatter_dimension=0, tiled=True)
        return g.sum() + s.sum() + r.sum()

    fn = jax.jit(smap(local, mesh, in_specs=(P("sp"),), out_specs=P()))
    x = jax.ShapeDtypeStruct((16, 8), np.dtype(np.float32))  # shard (4, 8)
    jaxpr = jax.make_jaxpr(fn)(x)
    comm = collective_comm_bytes(jaxpr, dict(mesh.shape), mesh.devices.size)
    shard_bytes = 4 * 8 * 4  # (4, 8) f32 per sp-shard
    full_bytes = 16 * 8 * 4  # gathered (16, 8) f32
    assert comm == {
        "all_gather": full_bytes * (k - 1) * groups,       # 3072
        "psum": 2 * shard_bytes * (k - 1) * groups,        # 1536
        "reduce_scatter": shard_bytes * (k - 1) * groups,  # 768
    }


def test_comm_walker_counts_struct_all_gathers():
    """Cross-check of the struct budget term on the SHRUNK program:
    one (bit-packed) lhs-mask all_gather per struct node, plus one
    hoisted parent + validity gather pair per launch when any '>>'/'~'
    node needs the replicated parent table ('>' runs off the local
    parent column) -- the replication _stacked_words_est prices."""
    import jax

    from tempo_tpu.db.search import _count_struct_nodes
    from tempo_tpu.ops.filter import Cond, T_SPAN, normalize_tree
    from tempo_tpu.parallel.mesh import make_mesh
    from tempo_tpu.parallel.search import make_sharded_search

    mesh = make_mesh(8)
    conds = (Cond(target=T_SPAN, col="span.name_id", op="eq"),
             Cond(target=T_SPAN, col="span.name_id", op="eq"))
    one = ("struct", ">", ("cond", 0), ("cond", 1))
    two = ("struct", ">>", one, ("cond", 1))
    assert _count_struct_nodes(one) == 1
    assert _count_struct_nodes(two) == 2

    def count_gathers(tree):
        names = ("span.name_id", "span.parent_idx", "span.trace_sid",
                 "trace.span_off")
        fn = make_sharded_search(mesh, normalize_tree(tree, conds), conds,
                                 tuple(sorted(names)), 8, 32, 1, 8)
        avals = [jax.ShapeDtypeStruct(s, np.dtype(np.int32)) for s in
                 [(8, 2, 3), (8, 2, 2), (8,)]]
        col_avals = []
        for n in sorted(names):
            shape = (8, 9) if n == "trace.span_off" else (
                (8, 8) if n.startswith("trace.") else (8, 32))
            col_avals.append(jax.ShapeDtypeStruct(shape, np.dtype(np.int32)))
        # float operands ride aval slot 1 as f32
        avals[1] = jax.ShapeDtypeStruct((8, 2, 2), np.dtype(np.float32))
        jaxpr = jax.make_jaxpr(fn)(*avals, *col_avals)

        def walk(jx):
            n = 0
            for eqn in jx.eqns:
                if eqn.primitive.name == "all_gather":
                    n += 1
                for v in eqn.params.values():
                    if hasattr(v, "eqns"):
                        n += walk(v)
                    elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                        n += walk(v.jaxpr)
            return n

        return walk(jaxpr.jaxpr)

    # '>' alone: just its packed lhs mask
    assert count_gathers(one) == 1
    # '>' nested under '>>': two per-node masks + the once-per-launch
    # hoisted pid + packed-validity pair
    assert count_gathers(two) == 4


def test_struct_budget_scales_with_node_count(monkeypatch):
    """The pre-IO stacked estimate grows per additional struct node --
    the regression the eval_shard budget fix closes (one node used to
    price a whole chain). Post-shrink pricing: S_b*sp per node (the
    replicated mask) + 4*S_b*sp once when the added node is a '>>'/'~'
    (the hoisted parent/validity tables and closure temps). With the
    TEMPO_STRUCT_PACK=0 escape hatch the budget must price the legacy
    triple-gather program (6*S_b*sp per node) -- what will actually
    run on device."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import (
        SearchRequest,
        _plan_for_block,
        _stacked_words_est,
    )
    from tempo_tpu.util.testdata import make_traces

    import tempfile

    db = TempoDB(TempoDBConfig(wal_path=tempfile.mkdtemp(prefix="cost-w")),
                 backend=MemBackend())
    db.write_block(TENANT, make_traces(30, seed=5, n_spans=6))
    blk = db.open_block(db.blocklist.metas(TENANT)[0])

    def est_for(query):
        p = _plan_for_block(blk, SearchRequest(query=query))
        assert p.has_struct and not p.prune
        from tempo_tpu.ops.filter import required_columns

        needed = [n for n in required_columns(p.conds) + list(p.extra_cols)
                  if not n.startswith("span@")]
        return _stacked_words_est([(blk, p)], needed, p.tree, sp=4,
                                  S_b=4096, NT_b=1024, attr_b={})

    e1 = est_for('{ name = "GET /api" } > { true }')
    e2 = est_for('{ name = "GET /api" } > { true } >> { name = "db.query" }')
    # the added '>>' node: one more replicated mask + the hoisted tables
    assert e2 - e1 == (1 + 4) * 4096 * 4
    monkeypatch.setenv("TEMPO_STRUCT_PACK", "0")
    l1 = est_for('{ name = "GET /api" } > { true }')
    l2 = est_for('{ name = "GET /api" } > { true } >> { name = "db.query" }')
    assert l2 - l1 == 6 * 4096 * 4  # legacy: lm/pid/valid + temps per node
    assert l1 - e1 == 5 * 4096 * 4  # one '>' node: 6x legacy vs 1x packed
    db.close()


# ------------------------------------------------------------ HBM ledger


def test_hbm_ledger_reconciles_staged_and_livestage(tmp_path):
    """The ledger's components must equal the subsystems' own books:
    staged_cache bytes == ops/stage's LRU accounting, livestage bytes ==
    the stagers' resident device arrays."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.ops.livestage import LiveStager, stager_device_bytes
    from tempo_tpu.ops.stage import stage_block, staged_cache_stats
    from tempo_tpu.util.testdata import make_traces

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w")),
                 backend=MemBackend())
    db.write_block(TENANT, make_traces(40, seed=7, n_spans=5))
    blk = db.open_block(db.blocklist.metas(TENANT)[0])
    staged = stage_block(blk, ["span.dur_us", "trace.start_ms"])
    assert staged.cols

    hbm = COST.hbm_snapshot()
    st = staged_cache_stats()
    assert hbm["components"]["staged_cache"]["bytes"] == st["bytes"] > 0
    assert hbm["accounted_bytes"] >= st["bytes"]

    # livestage component: a stager with resident device columns
    stager = LiveStager()
    stager._dev = {"alive": np.zeros(64, np.int32)}  # stand-in resident col
    total, n = stager_device_bytes()
    assert total >= stager.device_bytes() == 64 * 4
    hbm2 = COST.hbm_snapshot()
    assert hbm2["components"]["livestage"]["bytes"] == total
    assert hbm2["components"]["livestage"]["stagers"] == n
    db.close()


# ------------------------------------------------------------ CostLedger


def test_cost_ledger_roundtrip_and_atomic_publish(tmp_path):
    path = str(tmp_path / "ledger.json")
    led = costledger.CostLedger(path)
    led.update("find", winner="device", host_s=0.01, device_s=0.002)
    assert led.publish()
    # a fresh loader sees exactly what was published
    led2 = costledger.CostLedger(path)
    e = led2.get("find")
    assert e["winner"] == "device" and e["device_s"] == 0.002
    assert e["measured_at_unix"] > 0
    assert led2.load_error == ""
    # updates merge rather than replace
    led2.update("find", crossover_rows=123.0)
    assert led2.get("find")["winner"] == "device"
    assert led2.get("find")["crossover_rows"] == 123.0


def test_cost_ledger_corrupt_artifact_falls_back_empty(tmp_path, capsys):
    path = tmp_path / "ledger.json"
    path.write_text("{not json")
    led = costledger.CostLedger(str(path))
    assert led.load_error
    assert led.entries() == {}
    assert "unreadable" in capsys.readouterr().err
    # wrong shape is also corrupt, not a crash
    path.write_text(json.dumps({"entries": [1, 2]}))
    led = costledger.CostLedger(str(path))
    assert led.load_error and led.entries() == {}
    # the next publish rewrites the artifact whole and recovers
    led.update("find", winner="host")
    assert led.publish()
    assert costledger.CostLedger(str(path)).get("find")["winner"] == "host"


# ---------------------------------------------------- ledger-backed find


def _two_tiny_blocks(tmp_path):
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.util.testdata import make_traces

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w")),
                 backend=MemBackend())
    ids = []
    for seed in (1, 2):
        traces = make_traces(32, seed=seed, n_spans=3)
        db.write_block(TENANT, traces)
        ids += [tid for tid, _ in traces]
    blocks = [db.open_block(m) for m in db.blocklist.metas(TENANT)]
    return db, blocks, ids


def test_find_auto_policy_routes_from_ledger(tmp_path, monkeypatch):
    """auto on one chip: no ledger entry = the host default
    (single_chip_rtt); a committed device winner = device with reason
    ledger_crossover; TEMPO_FIND_MODE env still wins over everything.
    Results are bit-identical on every path."""
    from tempo_tpu.block import schema as S
    from tempo_tpu.ops import find as find_mod

    costledger.configure(str(tmp_path / "ledger.json"))
    monkeypatch.setattr(find_mod, "_n_devices", lambda: 1)
    db, blocks, ids = _two_tiny_blocks(tmp_path)
    q = np.asarray([S.trace_id_to_codes(ids[0].rjust(16, b"\x00")),
                    S.trace_id_to_codes(ids[-1].rjust(16, b"\x00"))], np.int32)

    def routed(mode):
        r0 = TEL.routing_counts()
        out = find_mod.lookup_ids_blocks_cached(blocks, q, mode=mode)
        r1 = TEL.routing_counts()
        hit = [k for k, n in r1.items() if k[0] == "find" and n > r0.get(k, 0)]
        assert len(hit) == 1, hit
        return out, hit[0]

    base, key = routed("auto")
    assert key[1:] == ("host", "single_chip_rtt")

    costledger.ledger().update(costledger.KEY_FIND, winner="device")
    dev, key = routed("auto")
    assert key[1:] == ("device", "ledger_crossover")
    np.testing.assert_array_equal(dev, base)

    costledger.ledger().update(costledger.KEY_FIND, winner="host")
    host, key = routed("auto")
    assert key[1:] == ("host", "ledger_crossover")
    np.testing.assert_array_equal(host, base)

    # a committed crossover_rows beats the binary winner: routing
    # compares THIS batch's id rows (64 here) against it
    costledger.ledger().update(costledger.KEY_FIND, crossover_rows=1.0)
    dev2, key = routed("auto")
    assert key[1:] == ("device", "ledger_crossover")
    np.testing.assert_array_equal(dev2, base)
    costledger.ledger().update(costledger.KEY_FIND, crossover_rows=1e9)
    _, key = routed("auto")
    assert key[1:] == ("host", "ledger_crossover")

    monkeypatch.setenv("TEMPO_FIND_MODE", "host")
    _, key = routed("device")  # env beats even an explicit caller mode
    assert key[1:] == ("host", "forced")
    db.close()


def test_calibrate_find_commits_ledger_entry(tmp_path):
    from tempo_tpu.ops.find import calibrate_find

    costledger.configure(str(tmp_path / "ledger.json"))
    db, blocks, _ = _two_tiny_blocks(tmp_path)
    idx = blocks[0].trace_index["trace.id_codes"]
    q = np.asarray(idx[:8], np.int32)
    entry = calibrate_find(blocks, q, repeats=1)
    assert entry["winner"] in ("host", "device")
    assert entry["host_s"] > 0 and entry["device_s"] > 0
    assert entry["rows"] == sum(
        b.trace_index["trace.id_codes"].shape[0] for b in blocks)
    # persisted: a fresh loader (new process stand-in) sees the race
    fresh = costledger.CostLedger(str(tmp_path / "ledger.json"))
    assert fresh.get(costledger.KEY_FIND)["winner"] == entry["winner"]
    db.close()


# ----------------------------------------------- live-engine ledger seed


def test_live_engine_seeds_from_ledger_env_wins(tmp_path, monkeypatch):
    from tempo_tpu.db.live_engine import LiveEngine

    costledger.configure(str(tmp_path / "ledger.json"))
    costledger.ledger().update(costledger.KEY_LIVE_SEARCH,
                               host_s_per_row=1e-6, device_fixed_s=0.01)
    monkeypatch.delenv("TEMPO_LIVE_CROSSOVER_ROWS", raising=False)
    eng = LiveEngine(instance=None)
    assert eng._host_s_per_row == 1e-6
    assert eng._dev_fixed_s == 0.01
    assert eng.crossover_rows() == pytest.approx(10000.0)
    assert eng._route(20000)[0] == "device"
    assert eng._route(100) == ("host", "tiny_head")

    # env seed wins: ledger values must NOT preload the EMAs
    monkeypatch.setenv("TEMPO_LIVE_CROSSOVER_ROWS", "123")
    eng2 = LiveEngine(instance=None)
    assert eng2._host_s_per_row is None and eng2._dev_fixed_s is None
    assert eng2.crossover_rows() == 123.0

    # a purely ledger-seeded engine must NOT re-publish (a restart loop
    # would keep refreshing measured_at_unix on rates it never measured)
    monkeypatch.delenv("TEMPO_LIVE_CROSSOVER_ROWS", raising=False)
    eng3 = LiveEngine(instance=None)
    eng3.persist_crossover()
    assert costledger.CostLedger(
        str(tmp_path / "ledger.json")).get(costledger.KEY_LIVE_SEARCH) is None

    # write-back: measured EMAs persist for the next process
    eng._observe_engine("host", 1000, 0.002)
    eng._observe_engine("device", 1000, 0.05)
    eng.persist_crossover()
    fresh = costledger.CostLedger(str(tmp_path / "ledger.json"))
    e = fresh.get(costledger.KEY_LIVE_SEARCH)
    assert e["host_s_per_row"] > 0 and e["device_fixed_s"] > 0
    assert e["crossover_rows"] > 0


def test_host_rate_seed_from_ledger(tmp_path, monkeypatch):
    from tempo_tpu.db import search as search_mod

    costledger.configure(str(tmp_path / "ledger.json"))
    costledger.ledger().update(costledger.KEY_BLOCK_SCAN,
                               host_rate_bps=9.9e9)
    monkeypatch.setattr(search_mod, "_HOST_RATE_SEEDED", False)
    monkeypatch.setattr(search_mod, "_HOST_RATE_BPS", 1.5e9)
    search_mod.seed_host_rate_from_ledger()
    assert search_mod._HOST_RATE_BPS == 9.9e9
    # idempotent: a second call (another TempoDB) never re-seeds over
    # the EMA the process has been learning since
    search_mod._note_host_rate(100 << 20, 0.01)
    learned = search_mod._HOST_RATE_BPS
    search_mod.seed_host_rate_from_ledger()
    assert search_mod._HOST_RATE_BPS == learned


# --------------------------------------------------- app status surfaces


def test_status_cost_endpoint_and_metrics_families(tmp_path):
    """Drive the filter, find, timeseries and mesh-search programs, then
    read /status/cost off a running app: per-(op,bucket) rows with
    FLOPs/bytes (+ utilization fields once measured calls exist),
    per-collective comm bytes for the mesh program, the HBM ledger and
    the ledger/compile-cache sections; /metrics still passes the strict
    OpenMetrics parse with the new families present."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest
    from tempo_tpu.ops.filter import Operands
    from tempo_tpu.ops.stage import stage_block
    from tempo_tpu.ops.timeseries import eval_timeseries_device
    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.util.testdata import make_traces

    from test_observability import _free_port, parse_openmetrics_strict

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "w")),
                 backend=MemBackend())
    for seed in (1, 2):
        db.write_block(TENANT, make_traces(24, seed=seed, n_spans=4))
    metas = db.blocklist.metas(TENANT)
    req = SearchRequest(tags={"k8s.cluster.name": "prod"}, limit=5)
    for _ in range(3):
        db.search_blocks(TENANT, metas, req)  # 8 cpu devices -> mesh path
    _padded_filter_eval()  # the single-chip filter kernel
    blk = db.open_block(metas[0])
    # find: batched device bisection
    from tempo_tpu.ops.find import lookup_ids_blocks

    lookup_ids_blocks([blk.trace_index["trace.id_codes"]],
                      np.asarray(blk.trace_index["trace.id_codes"][:4],
                                 np.int32))
    # timeseries: one fused device fold over a staged block
    staged = stage_block(blk, ["span.start_ms"], cache=False)
    eval_timeseries_device((None, ()), staged, Operands.build([]),
                           gid=np.zeros(staged.n_spans, np.int32),
                           val=None, vpres=None, t0_rel_ms=0, step_ms=1000,
                           n_buckets=4, n_groups=1)
    assert COST.drain(60)

    cfg = AppConfig(
        storage_path=str(tmp_path / "store"), http_port=_free_port(),
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=9999, max_block_age_s=9999,
                                flush_check_period_s=9999))
    app = App(cfg)
    try:
        app.start()
        app.serve_http(background=True)
        base = f"http://127.0.0.1:{cfg.http_port}"
        with urllib.request.urlopen(base + "/status/cost", timeout=10) as r:
            cost = json.load(r)
        ops_seen = {p["op"] for p in cost["programs"]}
        assert {"filter", "find", "timeseries", "mesh_search"} <= ops_seen, ops_seen
        for p in cost["programs"]:
            if p["op"] == "filter":
                assert p["flops"] > 0 and p["bytes_accessed"] > 0
        mesh_rows = [p for p in cost["programs"] if p["op"] == "mesh_search"]
        assert any(p.get("comm_bytes_per_launch") for p in mesh_rows)
        assert any(c["op"] == "mesh_search" and c["bytes_total"] > 0
                   for c in cost["comm"])
        assert "staged_cache" in cost["hbm"]["components"]
        assert "entries" in cost["ledger"]
        assert {"enabled", "dir", "disk_hits"} <= set(cost["compile_cache"])

        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        fams = parse_openmetrics_strict(text)
        assert "tempo_program_flops" in fams
        assert "tempo_program_bytes_accessed" in fams
        assert "tempo_mesh_comm_bytes" in fams
        assert "tempo_hbm_bytes" in fams
    finally:
        app.stop()
        db.close()


def test_compile_cache_counts_disk_hits(tmp_path):
    """TEMPO_COMPILE_CACHE_DIR: enabling the persistent cache registers
    the jax.monitoring listener; clearing the in-process jit caches and
    re-running the same program must deserialize from disk and count a
    hit -- the counter that splits restart-warm compiles from fresh
    XLA work."""
    import jax

    from tempo_tpu.util import costmodel

    assert costmodel.enable_compile_cache(str(tmp_path / "cc"))
    try:
        h0 = costmodel.compile_cache_stats()["disk_hits"]

        @jax.jit
        def f(x):
            return x * 3 + 1

        f(np.arange(8, dtype=np.float32))
        jax.clear_caches()  # a restart stand-in: jit cache gone, disk not
        f(np.arange(8, dtype=np.float32))
        st = costmodel.compile_cache_stats()
        assert st["enabled"] and st["dir"]
        assert st["disk_hits"] > h0, st
    finally:
        # tmp_path is reaped: the rest of the suite must not keep
        # reading a vanishing cache dir
        costmodel.disable_compile_cache()
        assert not costmodel.compile_cache_stats()["enabled"]
