"""vtpu block round-trip: build from random traces -> find every id ->
materialized traces equal the originals (the reference's
create-then-find-all property tests, tempodb_test.go TestCompleteBlock)."""

import numpy as np
import pytest

from tempo_tpu.backend import LocalBackend, MemBackend
from tempo_tpu.block import build_block_from_traces, open_block
from tempo_tpu.block.bloom import ShardedBloom
from tempo_tpu.block.colio import AxisChunks, ColumnPack, pack_columns
from tempo_tpu.block.dictionary import DictBuilder, Dictionary
from tempo_tpu.util.testdata import make_trace, make_traces
from tempo_tpu.wire.combine import combine_traces

TENANT = "single-tenant"


def _canon(t):
    """Canonical span map for comparison."""
    out = {}
    for res, scope, sp in t.all_spans():
        out[sp.span_id] = (
            sp.name,
            sp.kind,
            sp.start_unix_nano,
            sp.end_unix_nano,
            sp.status_code,
            sp.status_message,
            tuple(sorted((k, repr(v)) for k, v in sp.attrs.items())),
            tuple(sorted((k, repr(v)) for k, v in res.attrs.items())),
            scope.name,
            tuple((e.name, e.time_unix_nano, tuple(sorted(e.attrs.items()))) for e in sp.events),
            sp.parent_span_id,
        )
    return out


def test_dictionary_roundtrip():
    db = DictBuilder()
    codes = {s: db.code(s) for s in ["zeta", "alpha", "alpha", "mid"]}
    d, remap = db.finalize()
    assert d.strings == sorted(set(["zeta", "alpha", "mid"]))
    assert d.string(remap[codes["alpha"]]) == "alpha"
    d2 = Dictionary.from_bytes(d.to_bytes())
    assert d2.strings == d.strings
    assert d2.lookup("alpha") >= 0
    assert d2.lookup("nope") == -1
    lo, hi = d2.prefix_range("m")
    assert [d2.string(i) for i in range(lo, hi)] == ["mid"]


def test_colio_chunked_roundtrip():
    ax = AxisChunks([0, 3, 5])
    cols = {
        "a": np.arange(5, dtype=np.int32),
        "b": np.arange(10, dtype=np.float32).reshape(5, 2),
        "solo": np.arange(7, dtype=np.int64),
    }
    blob = pack_columns(cols, {"x": ax}, {"a": "x", "b": "x"})
    p = ColumnPack.from_bytes(blob)
    assert set(p.names()) == {"a", "b", "solo"}
    np.testing.assert_array_equal(p.read("a"), cols["a"])
    np.testing.assert_array_equal(p.read("b"), cols["b"])
    np.testing.assert_array_equal(p.read("solo"), cols["solo"])
    np.testing.assert_array_equal(p.read_groups("a", [1]), cols["a"][3:5])
    np.testing.assert_array_equal(p.read_groups("b", [0]), cols["b"][0:3])
    with pytest.raises(ValueError):
        p.read_groups("solo", [0])


def test_bloom():
    bl = ShardedBloom.for_estimated_items(1000)
    ids = [bytes([i % 256, i // 256]) + b"\x00" * 14 for i in range(500)]
    bl.add_many(ids)
    assert all(bl.test(t) for t in ids)
    misses = sum(bl.test(b"\xff" * 14 + bytes([i % 256, i // 256])) for i in range(1000))
    assert misses < 50  # ~1% fp target


@pytest.mark.parametrize("backend_kind", ["mem", "local"])
def test_block_roundtrip(tmp_path, backend_kind):
    backend = MemBackend() if backend_kind == "mem" else LocalBackend(str(tmp_path))
    traces = make_traces(30, seed=42, n_spans=10)
    meta = build_block_from_traces(backend, TENANT, traces, row_group_spans=64)
    assert meta.total_traces == 30
    assert meta.total_spans == 300
    assert len(meta.row_groups) >= 2  # forced small row groups

    blk = open_block(backend, TENANT, meta.block_id)
    for tid, original in traces:
        got = blk.find_trace_by_id(tid)
        assert got is not None, tid.hex()
        assert _canon(got) == _canon(combine_traces([original]))

    # absent ids don't match
    assert blk.find_trace_by_id(b"\x00" * 16) is None
    assert blk.find_trace_by_id(b"\xff" * 16) is None


def test_block_meta_pruning():
    backend = MemBackend()
    traces = make_traces(10, seed=7)
    meta = build_block_from_traces(backend, TENANT, traces)
    assert meta.may_contain_id(traces[0][0].hex())
    assert not meta.may_contain_id("00" * 16)
    start_s = meta.start_time_unix_nano // 10**9
    assert meta.overlaps_time(start_s - 10, start_s + 10)
    assert not meta.overlaps_time(start_s - 1000, start_s - 500)


def test_block_selective_io():
    """find-by-id must NOT read the whole data object."""
    backend = MemBackend()
    traces = make_traces(200, seed=11, n_spans=12)
    meta = build_block_from_traces(backend, TENANT, traces, row_group_spans=256)
    blk = open_block(backend, TENANT, meta.block_id)
    tid = traces[50][0]
    assert blk.find_trace_by_id(tid) is not None
    total = meta.size_bytes
    assert blk.pack.bytes_read < total * 0.7, (blk.pack.bytes_read, total)


def test_complex_attr_fidelity():
    backend = MemBackend()
    t = make_trace(1, n_spans=1)
    sp = next(t.all_spans())[2]
    sp.attrs = {"arr": [1, "two", False], "blob": b"\x00\xff", "big": 2**40, "neg": -(2**40), "pi": 3.141592653589793}
    tid = sp.trace_id
    meta = build_block_from_traces(backend, TENANT, [(tid, t)])
    blk = open_block(backend, TENANT, meta.block_id)
    got = blk.find_trace_by_id(tid)
    sp2 = next(got.all_spans())[2]
    assert sp2.attrs == sp.attrs


def test_versioned_encoding_dispatch(tmp_path):
    """Readers open blocks through the version registry; unknown
    versions fail loudly instead of misparsing
    (tempodb/encoding/versioned.go:17-46 analog)."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.block.builder import build_block_from_traces
    from tempo_tpu.block.reader import BackendBlock
    from tempo_tpu.block.versioned import (
        UnknownVersion,
        open_block_versioned,
        register_encoding,
        supported_versions,
    )
    from tempo_tpu.util.testdata import make_traces

    backend = MemBackend()
    meta = build_block_from_traces(backend, "t", sorted(make_traces(5, seed=1, n_spans=2)))
    blk = open_block_versioned(backend, meta)
    assert isinstance(blk, BackendBlock)
    assert "vtpu1" in supported_versions()

    meta.version = "vtpu9"
    with pytest.raises(UnknownVersion):
        open_block_versioned(backend, meta)

    # a newly registered format dispatches without touching callers
    class V9:
        def __init__(self, backend, meta):
            self.meta = meta

    register_encoding("vtpu9", V9)
    assert isinstance(open_block_versioned(backend, meta), V9)


@pytest.mark.parametrize("shim", [False, True], ids=["native", "no-native"])
@pytest.mark.parametrize("codec", ["zstd", "gzip", "lzma", "raw", "snappy", "lz4"])
def test_codec_matrix_roundtrip(codec, shim, monkeypatch):
    """Every registered codec roundtrips through pack/read -- with the
    native library present AND in shim mode (no shared library, zstd
    through the zlib shim, snappy/lz4 through the pure-Python
    blockcodecs) -- and the reader dispatches on the per-chunk codec
    (mixed backends are fine)."""
    import numpy as np

    from tempo_tpu.block.colio import AxisChunks, ColumnPack, pack_columns

    if shim:
        import threading

        import tempo_tpu.block.colio as colio
        import tempo_tpu.native as native
        from tempo_tpu.util import zstdshim

        monkeypatch.setattr(colio, "zstandard", zstdshim)
        # a REAL ZstdDecompressor cached by an earlier native-mode case
        # must not decode this case's shim (zlib) frames
        monkeypatch.setattr(colio, "_DCTX_LOCAL", threading.local())
        monkeypatch.setattr(native, "_LIB", None)
        monkeypatch.setattr(native, "_TRIED", True)
        assert not native.available()

    rng = np.random.default_rng(5)
    cols = {
        "a.vals": np.zeros(50_000, dtype=np.int32),  # compressible
        "a.rand": rng.integers(0, 2**31, size=50_000, dtype=np.int32),
        "b.small": np.arange(10, dtype=np.int64),
    }
    axes = {"rows": AxisChunks([0, 20_000, 50_000])}
    data = pack_columns(cols, axes, {"a.vals": "rows", "a.rand": "rows"}, codec=codec)
    pack = ColumnPack.from_bytes(data)
    for name, arr in cols.items():
        assert (pack.read(name) == arr).all(), (codec, name)
    assert (pack.read_groups("a.vals", [1]) == cols["a.vals"][20_000:]).all()
    # read_all fast path decodes the matrix too
    out = ColumnPack.from_bytes(data).read_all()
    for name, arr in cols.items():
        assert (out[name] == arr).all(), (codec, name)
    # the coalesced cold-read plan (plan_fetch -> fetch -> decode, the
    # stream pipeline's stages) decodes the matrix too
    pk = ColumnPack.from_bytes(data)
    pk.warm_columns(list(cols))
    for name, arr in cols.items():
        assert (pk.read(name) == arr).all(), (codec, name)


@pytest.mark.parametrize("codec", ["snappy", "lz4"])
def test_speed_codec_cross_decode(codec):
    """Native-compressed chunks decode through the pure-Python
    decompressors and vice versa: both sides implement the same public
    block formats, so blocks written on either kind of image stay
    readable on the other."""
    import numpy as np

    import tempo_tpu.native as native
    from tempo_tpu.block import blockcodecs as bc

    if not native.available():
        pytest.skip("native library not built")
    py_c, py_d = ((bc.snappy_compress, bc.snappy_decompress) if codec == "snappy"
                  else (bc.lz4_compress, bc.lz4_decompress))
    rng = np.random.default_rng(11)
    payloads = [
        b"",
        b"a" * 5,
        b"ab" * 4000,                      # long periodic runs
        bytes(rng.integers(0, 256, size=70_000, dtype=np.uint8)),  # entropy
        np.zeros(130_000, np.uint8).tobytes(),                     # one run
        bytes(rng.integers(0, 3, size=50_000, dtype=np.uint8)),    # low card
    ]
    native_out = native.block_compress_chunks(codec, payloads)
    assert native_out is not None
    for raw, comp in zip(payloads, native_out):
        # native -> python decode
        assert py_d(comp, len(raw)) == raw
    # python (fallback) compressors -> native decode. Call the module-
    # level fallback bodies directly: block_compress_chunks would route
    # back to native.
    import tempo_tpu.native as n

    lib, tried = n._LIB, n._TRIED
    try:
        n._LIB, n._TRIED = None, True
        py_out = [py_c(raw) for raw in payloads]
    finally:
        n._LIB, n._TRIED = lib, tried
    back = native.block_decompress_chunks(codec, py_out, [len(r) for r in payloads])
    assert back is not None and list(back) == payloads


def test_const_chunks():
    """Constant chunks store ONE row (codec "const") and tile back on
    every read path; fully-constant columns come back as stride-0
    broadcast views under read_all(broadcast_const=True), and
    broadcast inputs write as const without materializing."""
    import numpy as np

    from tempo_tpu.block.colio import AxisChunks, ColumnPack, pack_columns

    rng = np.random.default_rng(9)
    n = 60_000
    mixed = rng.integers(0, 2**31, size=n, dtype=np.int32)
    mixed[20_000:40_000] = 7  # exactly one const chunk in a mixed column
    cols = {
        "a.const": np.full(n, -1, dtype=np.int32),
        "a.mixed": mixed,
        "a.rand": rng.integers(0, 2**31, size=n, dtype=np.int32),
        "a.wide": np.broadcast_to(
            np.arange(8, dtype=np.uint8), (n, 8)),  # stride-0 input
        "solo.const": np.zeros(30_000, dtype=np.float64),
    }
    axes = {"rows": AxisChunks([0, 20_000, 40_000, n])}
    ca = {k: "rows" for k in cols if k.startswith("a.")}
    data = pack_columns(cols, axes, ca)
    pack = ColumnPack.from_bytes(data)

    # footer marks the right chunks const; const columns cost ~one row
    stats = {s["name"]: s for s in pack.column_stats()}
    assert stats["a.const"]["codecs"] == ["const"]
    assert stats["a.const"]["stored"] == 3 * 4
    assert stats["a.wide"]["codecs"] == ["const"]
    assert "const" in stats["a.mixed"]["codecs"] and len(stats["a.mixed"]["codecs"]) > 1
    assert stats["solo.const"]["codecs"] == ["const"]

    for name, arr in cols.items():
        assert (pack.read(name) == arr).all(), name
    assert (pack.read_groups("a.mixed", [1, 2]) == mixed[20_000:]).all()

    # read_all: materialized by default, broadcast views on request
    out = ColumnPack.from_bytes(data).read_all()
    for name, arr in cols.items():
        assert (out[name] == arr).all(), name
    bc = ColumnPack.from_bytes(data).read_all(broadcast_const=True)
    for name, arr in cols.items():
        assert (bc[name] == arr).all(), name
    assert bc["a.const"].strides[0] == 0
    assert bc["a.wide"].strides[0] == 0
    assert bc["a.mixed"].strides[0] != 0  # only fully-const columns

    # chunk-join fallback path (no native) tiles const chunks too
    p2 = ColumnPack.from_bytes(data)
    chunks_meta = p2._cols["a.const"]["chunks"]
    raw = p2._chunks(chunks_meta)
    assert (np.frombuffer(raw, np.int32) == cols["a.const"]).all()


def test_concurrent_chunk_reads_thread_safety():
    """Concurrent cold reads of many zstd chunks from many threads:
    zstd contexts are per-thread (a shared context intermittently
    corrupts; this reproduced ~1-in-4 on a pooled read of 10 blocks)."""
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from tempo_tpu.block.colio import AxisChunks, ColumnPack, pack_columns

    rng = np.random.default_rng(3)
    cols = {f"c.x{i}": rng.integers(0, 50, size=200_000, dtype=np.int32)
            for i in range(12)}
    axes = {"rows": AxisChunks(list(range(0, 200_001, 20_000)))}
    data = pack_columns(cols, axes, {n: "rows" for n in cols})
    for _ in range(6):
        pack = ColumnPack.from_bytes(data)  # fresh cache: all reads cold

        def read_one(name):
            return name, pack.read(name)

        with ThreadPoolExecutor(max_workers=12) as ex:
            for name, arr in ex.map(read_one, list(cols)):
                assert (arr == cols[name]).all(), name


def test_corrupt_block_fails_loudly(tmp_path):
    """Bit-flipped or truncated block bytes must surface as clean Python
    exceptions (zstd/codec/magic errors), never wrong data or a native
    crash -- the storage layer's poison-input contract."""
    import pytest as _pytest

    from tempo_tpu.backend import LocalBackend
    from tempo_tpu.block import open_block
    from tempo_tpu.block.colio import ColumnPack

    backend = LocalBackend(str(tmp_path))
    traces = make_traces(30, seed=17, n_spans=5)
    meta = build_block_from_traces(backend, TENANT, traces)
    path = tmp_path / TENANT / meta.block_id / "data.vtpu"
    good = path.read_bytes()

    def fresh(data: bytes):
        path.write_bytes(data)
        return open_block(backend, TENANT, meta.block_id)

    # bad magic
    with _pytest.raises(Exception) as ei:
        fresh(good[:-4] + b"NOPE").pack.names()
    assert "magic" in str(ei.value).lower()

    # truncated mid-data: the footer vanishes entirely
    with _pytest.raises(Exception):
        ColumnPack.from_bytes(good[: len(good) // 2])

    # flip bytes INSIDE a compressed chunk: decode must raise, not
    # return garbage (zstd frames carry integrity checks)
    corrupt = bytearray(good)
    for off in range(64, 200):
        corrupt[off] ^= 0xFF
    blk = fresh(bytes(corrupt))
    with _pytest.raises(Exception):
        for name in blk.pack.names():
            blk.pack.read(name)

    # restore: the same reader path works again on good bytes
    blk = fresh(good)
    for name in blk.pack.names():
        blk.pack.read(name)


def test_mixed_version_blocks_read_and_compact(tmp_path):
    """vtpu1 (JSON footer) and vtpu2 (binary footer) blocks coexist: both
    open through the versioned seam, search/find work on each, and a
    compaction over MIXED v1+v2 inputs produces a current-version output
    with every trace intact -- the forward-compat story in anger
    (reference: tempodb/encoding/versioned.go's two coexisting
    encodings)."""
    from tempo_tpu.backend import MemBackend
    from tempo_tpu.block.builder import BlockBuilder, write_block
    from tempo_tpu.block.versioned import CURRENT_VERSION, open_block_versioned
    from tempo_tpu.db.compactor import CompactionJob, CompactorConfig, compact
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire import segment

    backend = MemBackend()
    batches = [sorted(make_traces(12, seed=s, n_spans=4)) for s in (81, 82)]
    metas = []
    for version, batch in zip(("vtpu1", "vtpu2"), batches):
        b = BlockBuilder("t")
        for tid, t in batch:
            b.add_trace(tid, t)
        metas.append(write_block(backend, b.finalize(), version=version))
    assert metas[0].version == "vtpu1" and metas[1].version == "vtpu2"

    # both versions read: find every trace through the versioned opener
    for meta, batch in zip(metas, batches):
        blk = open_block_versioned(backend, meta)
        for tid, t in batch:
            sid = blk.find_trace_sid(tid)
            assert sid >= 0
            got = blk.materialize_traces([sid])[0]
            assert got.span_count() == t.span_count()

    # mixed-input compaction: disable the concat shortcut so the real
    # columnar merge crosses the version seam
    cfg = CompactorConfig(concat_small_input_bytes=0)
    res = compact(backend, CompactionJob("t", metas), cfg)
    assert res.new_blocks and res.traces_out == 24
    out = res.new_blocks[0]
    assert out.version == CURRENT_VERSION
    blk = open_block_versioned(backend, out)
    for batch in batches:
        for tid, t in batch:
            sid = blk.find_trace_sid(tid)
            assert sid >= 0
            assert blk.materialize_traces([sid])[0].span_count() == t.span_count()


def test_convert_block_cli(tmp_path):
    """tempo-cli convert-block rewrites a block across versions (the
    reference's cmd-convert-block role)."""
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.block.builder import BlockBuilder, write_block
    from tempo_tpu.cli.__main__ import main as cli_main
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.util.testdata import make_traces

    backend = LocalBackend(str(tmp_path / "store"))
    traces = sorted(make_traces(8, seed=83, n_spans=3))
    b = BlockBuilder("t")
    for tid, t in traces:
        b.add_trace(tid, t)
    meta = write_block(backend, b.finalize(), version="vtpu1")
    assert meta.version == "vtpu1"

    cli_main(["--backend.path", str(tmp_path / "store"),
              "convert-block", "t", meta.block_id, "--to", "vtpu2"])

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "wal")), backend=backend)
    db.poll_now()
    metas = [m for m in db.blocklist.metas("t") if not m.compacted_at_unix]
    assert len(metas) == 1 and metas[0].version == "vtpu2"
    for tid, t in traces:
        got = db.find_trace_by_id("t", tid)
        assert got is not None and got.span_count() == t.span_count()
    db.close()
