"""End-to-end query timelines: hierarchical self-tracing with
context propagation, remote-leg and batch-mate span parenting, the
bounded shipping queue, per-query cost attribution, OpenMetrics
exemplars, live-head TraceQL metrics, and the tracing-on == tracing-off
differential (results bit-identical, overhead bounded).
"""

import json
import socket
import threading
import time
import urllib.parse
import urllib.request

import pytest

from tempo_tpu.services.selftrace import RemoteSpanRecorder, SelfTracer
from tempo_tpu.util.kerneltel import TEL


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    TEL.reset()
    yield


def _spans_of(shipped):
    return [sp for rs in shipped for ss in rs.scope_spans for sp in ss.spans]


# ----------------------------------------------------- hierarchical spans


def test_nested_spans_parent_via_contextvar():
    """span() nests under the ambient parent; child() attaches under
    the innermost open span; outside any span both hang off the root."""
    shipped = []
    st = SelfTracer(lambda tenant, rss: shipped.extend(rss))
    with st.trace("root-op") as t:
        with t.span("outer") as outer:
            with t.span("inner"):
                t.child("leaf", 1.0, 2.0)  # ambient parent = inner
        t.child("flat", 3.0, 4.0)  # no open span: parent = root
    st.flush()
    spans = {sp.name: sp for sp in _spans_of(shipped)}
    assert set(spans) == {"root-op", "outer", "inner", "leaf", "flat"}
    root = spans["root-op"]
    assert spans["outer"].parent_span_id == root.span_id
    assert spans["inner"].parent_span_id == spans["outer"].span_id
    assert spans["leaf"].parent_span_id == spans["inner"].span_id
    assert spans["flat"].parent_span_id == root.span_id
    assert all(sp.trace_id == root.trace_id for sp in spans.values())
    assert outer.span_id == spans["outer"].span_id


def test_remote_recorder_grafts_spans_and_cost():
    """A RemoteSpanRecorder's spans graft into the originating trace
    with their remote parents intact, and its cost rides along as root
    cost attrs -- the wire round trip without the wire."""
    shipped = []
    st = SelfTracer(lambda tenant, rss: shipped.extend(rss))
    with st.trace("op", {"tenant": "t1"}) as t:
        job_sid = t.child("job:search_blocks", 1.0, 2.0)
        ctx = t.wire_context(job_sid)
        rec = RemoteSpanRecorder(ctx["trace_id"], ctx["parent_span_id"],
                                 worker_id="w-9")
        rec.child("block:abcd1234", 1.2, 1.8, {"engine": "device"})
        rec.add_cost("device_ms", 12.5)
        t.add_remote_spans(rec.to_wire())
    st.flush()
    spans = {sp.name: sp for sp in _spans_of(shipped)}
    blk = spans["block:abcd1234"]
    assert blk.parent_span_id == job_sid
    assert blk.attrs["querier"] == "w-9"
    assert spans["op"].attrs["cost.device_ms"] == 12.5
    assert "__cost__" not in spans


# ------------------------------------------------- bounded shipping queue


def test_bounded_queue_drops_whole_traces_with_counter():
    """A stalled distributor bounds memory: traces past queue_max drop
    (counted locally + in kerneltel), and the survivors still ship once
    the shipper unblocks."""
    release = threading.Event()
    shipped = []

    def slow_push(tenant, rss):
        release.wait(10.0)
        shipped.extend(rss)

    st = SelfTracer(slow_push, queue_max=2)
    for _ in range(6):
        with st.trace("op"):
            pass
    assert st.traces_dropped >= 3  # 1 in flight + 2 queued survive at most
    release.set()
    st.flush(timeout_s=10.0)
    stats = TEL.selftrace_stats()
    assert stats.get("dropped", 0) >= 3
    assert stats.get("shipped", 0) == st.spans_emitted > 0
    assert len(shipped) + st.traces_dropped == 6


# ------------------------------------------------ remote-leg propagation


def _mk_db(tmp_path, n=12):
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
    from tempo_tpu.util.testdata import make_traces

    db = TempoDB(TempoDBConfig(
        backend={"backend": "local", "path": str(tmp_path / "store")},
        wal_path=str(tmp_path / "wal")))
    meta = db.write_block("t1", make_traces(n, seed=21, n_spans=4))
    return db, meta


def test_remote_querier_leg_parents_under_job_span(tmp_path):
    """The wire round trip: a dispatcher-only frontend leases a job to a
    'remote' worker; the worker's engine spans (recorded against the
    wire (trace_id, parent_span_id)) come back with the result and land
    UNDER the frontend's job span in one tree."""
    from tempo_tpu.db.search import SearchRequest
    from tempo_tpu.services.frontend import Frontend
    from tempo_tpu.services.querier import Querier
    from tempo_tpu.services.worker import execute_job

    db, meta = _mk_db(tmp_path)
    querier = Querier(db, None, lambda a: None)
    fe = Frontend(querier, n_workers=0)
    shipped = []
    fe.self_tracer = SelfTracer(lambda tenant, rss: shipped.extend(rss))
    out = {}

    def run_search():
        out["resp"] = fe.search(
            "t1", SearchRequest(tags={"service.name": "db"}, limit=5))

    t = threading.Thread(target=run_search, daemon=True)
    t.start()
    deadline = time.monotonic() + 30.0
    polled_traces = 0
    while t.is_alive() and time.monotonic() < deadline:
        job = fe.poll_job(wait_s=0.2, worker_id="w1")
        if not job:
            continue
        ctx = job.get("trace")
        rec = None
        if ctx:
            rec = RemoteSpanRecorder(ctx["trace_id"], ctx["parent_span_id"],
                                     worker_id="w1")
            polled_traces += 1
        token = TEL.set_active_trace(rec) if rec is not None else None
        try:
            res = execute_job(querier, job["tenant"], job["kind"],
                              job["payload"])
        finally:
            if token is not None:
                TEL.reset_active_trace(token)
        fe.complete_job(job["id"], True, result=res,
                        self_spans=rec.to_wire() if rec is not None else None)
    t.join(timeout=10.0)
    assert not t.is_alive() and "resp" in out
    assert polled_traces > 0, "no wire job carried a trace context"
    fe.self_tracer.flush()
    fe.stop()
    db.close()
    spans = _spans_of(shipped)
    by_id = {sp.span_id: sp for sp in spans}
    remote = [sp for sp in spans if sp.attrs.get("querier") == "w1"]
    assert remote, f"no remote spans in {[sp.name for sp in spans]}"
    job_spans = {sp.span_id for sp in spans if sp.name.startswith("job:")}
    for sp in remote:
        # every remote span's ancestry passes through a frontend job span
        cur = sp
        seen = set()
        while cur.parent_span_id and cur.parent_span_id in by_id:
            if cur.parent_span_id in job_spans:
                break
            assert cur.span_id not in seen
            seen.add(cur.span_id)
            cur = by_id[cur.parent_span_id]
        assert cur.parent_span_id in job_spans, \
            f"remote span {sp.name} not under a job span"
    # queue-wait child exists under a job span
    qw = [sp for sp in spans if sp.name == "queue-wait"]
    assert qw and all(sp.parent_span_id in job_spans for sp in qw)


def test_batch_window_mate_parents_correctly():
    """A window mate riding the lead's fused launch gets a span in ITS
    OWN trace, under its own job span, naming the lead trace -- the
    batch-propagation contract."""
    from tempo_tpu.db.search import SearchResponse
    from tempo_tpu.services.frontend import Frontend, _Job, attach_trace

    fe = Frontend.__new__(Frontend)  # no workers/queue needed
    fe.stats_jobs_local = 0
    st = SelfTracer(lambda tenant, rss: None)

    def batch_fn(group):
        return [SearchResponse() for _ in group]

    with st.trace("lead-op") as ta, st.trace("mate-op") as tb:
        lead = _Job(kind="search_blocks", payload={}, fn=None, args=(),
                    batch_key=("k",), batch_fn=batch_fn)
        mate = _Job(kind="search_blocks", payload={}, fn=None, args=(),
                    batch_key=("k",), batch_fn=batch_fn)
        attach_trace([lead], ta)
        attach_trace([mate], tb)
        fe._execute_batch([("t1", lead), ("t2", mate)])
        assert lead.done.is_set() and mate.done.is_set()
        rides = [s for s in tb.spans if s[0] == "batch:ride"]
        assert len(rides) == 1
        name, t0, t1, attrs, sid, pid = rides[0]
        assert pid == mate.span_id  # under the MATE's job span
        assert attrs["lead_trace"] == ta.trace_id.hex()
        assert attrs["occupancy"] == 2
        # the lead's trace carries no ride marker (it ran the launch)
        assert not [s for s in ta.spans if s[0] == "batch:ride"]


# ------------------------------------------- differential + overhead


def test_tracing_on_off_results_bit_identical(tmp_path):
    """The observability plane must not change results: identical
    search/find responses with the tracer attached and detached."""
    from tempo_tpu.db.search import SearchRequest
    from tempo_tpu.services.frontend import Frontend
    from tempo_tpu.services.querier import Querier

    db, meta = _mk_db(tmp_path)
    querier = Querier(db, None, lambda a: None)
    fe = Frontend(querier, n_workers=2)
    req = SearchRequest(tags={"service.name": "db"}, limit=10)
    tid = bytes.fromhex(db.open_block(meta).search_index["trace.id"][0]
                        .tobytes().hex())

    def dump(resp):
        return [(t.trace_id, t.start_time_unix_nano, t.duration_ms,
                 t.root_service_name) for t in resp.traces]

    off_search = dump(fe.search("t1", req))
    off_find = fe.find_trace_by_id("t1", tid)
    fe.self_tracer = SelfTracer(lambda tenant, rss: None)
    on_search = dump(fe.search("t1", req))
    on_find = fe.find_trace_by_id("t1", tid)
    assert on_search == off_search and off_search
    assert (off_find is None) == (on_find is None)
    if off_find is not None:
        from tempo_tpu.wire import otlp_json

        assert otlp_json.dumps(on_find) == otlp_json.dumps(off_find)
    fe.stop()
    db.close()


def test_tracing_overhead_under_5_percent(tmp_path):
    """Span capture is two clock reads + a locked append: the warm
    batched-search microbench must not regress measurably with a trace
    parked. Medians over interleaved runs, retried to damp CI noise."""
    from tempo_tpu.db.search import SearchRequest
    import statistics

    db, meta = _mk_db(tmp_path, n=64)
    req = SearchRequest(tags={"service.name": "db"}, limit=10)
    for _ in range(3):
        db.search("t1", req)  # warm: staging + compiles
    st = SelfTracer(lambda tenant, rss: None)

    def run_once(traced: bool) -> float:
        if traced:
            with st.trace("bench") as t:
                token = TEL.set_active_trace(t)
                t0 = time.perf_counter()
                try:
                    db.search("t1", req)
                finally:
                    TEL.reset_active_trace(token)
                return time.perf_counter() - t0
        t0 = time.perf_counter()
        db.search("t1", req)
        return time.perf_counter() - t0

    last_ratio = None
    for _attempt in range(4):  # retry: wall-clock CI noise, not a loop
        offs, ons = [], []
        for _ in range(15):
            offs.append(run_once(False))
            ons.append(run_once(True))
        last_ratio = statistics.median(ons) / statistics.median(offs)
        if last_ratio < 1.05:
            break
    db.close()
    assert last_ratio < 1.05, f"tracing overhead {last_ratio:.3f}x"


# --------------------------------------------- live-head TraceQL metrics


def test_live_metrics_visible_and_matches_blocks(tmp_path):
    """ROADMAP #4 follow-up: unflushed spans are visible to TraceQL
    metrics through the ingester's exact host-twin leg, and the live
    series equal the blocks-only series after the same data flushes --
    the differential that proves the two paths share one bucket/fold
    definition."""
    from tempo_tpu.backend import MemBackend
    from tempo_tpu.db.metrics_exec import align_params, to_prometheus
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
    from tempo_tpu.db.wal import WAL
    from tempo_tpu.services.ingester import Ingester, IngesterConfig
    from tempo_tpu.services.overrides import Overrides
    from tempo_tpu.services.querier import Querier
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire.segment import segment_for_write

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dw")),
                 backend=MemBackend())
    ing = Ingester(WAL(str(tmp_path / "w")), db, Overrides(),
                   IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                  flush_check_period_s=9999))
    traces = make_traces(10, seed=31, n_spans=5)
    lo_ns = min(tr.time_range_nanos()[0] for _, tr in traces)
    hi_ns = max(tr.time_range_nanos()[1] for _, tr in traces)
    for tid, tr in traces:
        lo, hi = tr.time_range_nanos()
        s, e = lo // 10**9, hi // 10**9 + 1
        ing.push_segments("t1", [(tid, s, e, segment_for_write(tr, s, e))])

    class _Ring:
        def healthy_instances(self):
            class _D:
                addr = "inproc"
            return [_D()]

    querier = Querier(db, _Ring(), lambda addr: ing)
    req = align_params('{ resource.service.name = "db" } | rate() '
                       "by(resource.service.name)",
                       lo_ns / 1e9 - 60, hi_ns / 1e9 + 60, 30)
    live = to_prometheus(querier.metrics_query_range("t1", req))
    assert live["data"]["result"], "live spans invisible to metrics"
    # flush everything to blocks; the live head drains
    ing.flush_all()
    db.poll_now()
    assert not ing.instance("t1").live and not ing.instance("t1").cut
    blocks = to_prometheus(querier.metrics_query_range("t1", req))
    assert blocks == live
    # and a value fold agrees too (duration scaling shared)
    req2 = align_params("{ true } | avg_over_time(duration)",
                        lo_ns / 1e9 - 60, hi_ns / 1e9 + 60, 30)
    blocks2 = to_prometheus(querier.metrics_query_range("t1", req2))
    assert blocks2["data"]["result"]
    db.close()


# ----------------------------------------------------- HTTP end-to-end


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture(scope="function")
def traced_app(tmp_path, monkeypatch):
    # these tests certify the execution-path timeline (queue-wait,
    # stage, kernel spans); a result-cache hit legitimately has none
    # of those, so repeats must keep executing
    monkeypatch.setenv("TEMPO_RESULT_CACHE", "0")
    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire import otlp_json

    cfg = AppConfig(
        storage_path=str(tmp_path / "store"),
        http_port=_free_port(),
        multitenancy=True,
        self_tracing_tenant="self",
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    base = f"http://127.0.0.1:{cfg.http_port}"
    for _, tr in make_traces(24, seed=17, n_spans=5):
        urllib.request.urlopen(urllib.request.Request(
            base + "/v1/traces", data=otlp_json.dumps(tr).encode(),
            headers={"Content-Type": "application/json",
                     "X-Scope-OrgID": "t1"}), timeout=10)
    app.ingester.flush_all()
    app.db.poll_now()
    yield app, base
    app.stop()


def test_e2e_timeline_has_stage_spans_and_cost(traced_app):
    """The acceptance path: concurrent searches against the dev app
    yield self-traces whose union covers queue-wait, batch-window,
    stream fetch/decompress/upload, kernel-exec (compile attr) and
    verify spans; root spans carry cost.* attrs; /status/kernels
    aggregates per-tenant costs; the trace renders through the
    system's own find path via `tempo-cli self-trace`."""
    from tempo_tpu.wire import otlp_json

    app, base = traced_app
    # the float-attr leg plans conservatively -> exact-verify runs
    q = urllib.parse.quote(
        '{ resource.service.name = "db" && span.latency.weight >= 0.0 }')

    # a second, batcher-ELIGIBLE shape (no float tables): concurrent
    # copies coalesce through the admission window -> batch spans
    q2 = urllib.parse.quote(
        '{ resource.service.name = "db" && span.http.status_code >= 0 }')

    def hit(qq=q):
        urllib.request.urlopen(urllib.request.Request(
            base + f"/api/search?q={qq}&limit=10",
            headers={"X-Scope-OrgID": "t1"}), timeout=60)

    hit()  # cold: stream fetch/decompress + verify
    hit(q2)  # warm the batchable shape past the promotion threshold
    threads = ([threading.Thread(target=hit) for _ in range(2)]
               + [threading.Thread(target=hit, args=(q2,)) for _ in range(3)])
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    app.frontend.self_tracer.flush()

    logged = [x for x in TEL.slow_queries(50)
              if x["op"] == "search" and x["self_trace_id"]]
    assert logged, "slow-query log lost the self-trace ids"
    names = set()
    root_attrs = []
    for entry in logged:
        with urllib.request.urlopen(urllib.request.Request(
                base + f"/api/traces/{entry['self_trace_id']}",
                headers={"X-Scope-OrgID": "self"}), timeout=30) as r:
            tr = otlp_json.loads(r.read())
        for _, _, sp in tr.all_spans():
            names.add(sp.name.split(":")[0] if sp.name.startswith(
                ("block", "batch", "stream")) else sp.name)
            if sp.name == "frontend.search":
                root_attrs.append(sp.attrs)
    required = {"frontend.search", "job:search_blocks", "queue-wait",
                "qos-admit", "merge", "stream", "verify", "block"}
    assert required <= names, f"missing {required - names} in {sorted(names)}"
    assert "batch-window" in names or "batch" in names
    # per-query cost record on the root span
    costed = [a for a in root_attrs if any(k.startswith("cost.") for k in a)]
    assert costed, f"no cost.* root attrs in {root_attrs}"
    assert any("cost.device_ms" in a or "cost.bytes_scanned" in a
               for a in costed)
    # per-tenant aggregation in kerneltel
    with urllib.request.urlopen(base + "/status/kernels", timeout=10) as r:
        status = json.loads(r.read())
    assert status["query_costs"].get("t1", {}).get("queries", 0) >= 1
    assert status["selftrace"].get("shipped", 0) > 0

    # the dogfood render: tempo-cli self-trace latest via the system's
    # own find path
    import contextlib
    import io

    from tempo_tpu.cli.__main__ import main as cli_main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cli_main(["self-trace", "latest", "--target", base])
    rendered = buf.getvalue()
    assert "frontend.search" in rendered
    assert "queue-wait" in rendered
    assert "ms @+" in rendered  # timeline offsets


def test_metrics_exemplars_pass_strict_parse(traced_app):
    """/metrics keeps passing the strict OpenMetrics parse AND >= 3
    latency histogram families carry self-trace exemplar ids."""
    from test_observability import parse_openmetrics_strict

    app, base = traced_app
    q = urllib.parse.quote('{ resource.service.name = "db" }')
    for _ in range(3):
        urllib.request.urlopen(urllib.request.Request(
            base + f"/api/search?q={q}&limit=5",
            headers={"X-Scope-OrgID": "t1"}), timeout=60)
    mq = urllib.parse.quote("{ true } | rate()")
    urllib.request.urlopen(urllib.request.Request(
        base + f"/api/metrics/query_range?q={mq}&start=1&end=3600&step=60",
        headers={"X-Scope-OrgID": "t1"}), timeout=60)
    with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
        text = r.read().decode()
    fams = parse_openmetrics_strict(text)
    assert fams.get("tempo_selftrace_spans") == "counter"
    assert fams.get("tempo_query_cost") == "counter"
    ex_fams = {ln.split("{")[0][:-len("_bucket")]
               for ln in text.splitlines()
               if "# {trace_id=" in ln and "_bucket{" in ln}
    assert len(ex_fams) >= 3, f"exemplars only on {sorted(ex_fams)}"
    assert "tempo_frontend_query_duration_seconds" in ex_fams
