"""Chaos plane (PR 14): deterministic fault injection across every
IO/device seam, plus the resilience armor it forces -- per-query retry
budgets, the backend circuit breaker with half-open recovery, deadline
propagation, jittered worker backoff, and hedge telemetry.

The acceptance matrix lives here too:
  (a) transient backend 5xx -- masked (availability SLO ok, retry
      counters show the absorption);
  (b) sustained backend partition -- burn-rate verdict flips within one
      evaluation window, the breaker opens, then half-open-recovers
      after the rule expires;
  (c) faults-off differential -- an armed-but-empty plane is
      bit-identical to an unarmed process, with zero added launches.
"""

import json
import os
import time
import urllib.request

import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.backend.base import BackendError
from tempo_tpu.chaos import ChaosBackend, plane
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.db.search import SearchRequest, response_to_dict
from tempo_tpu.util import breaker as breaker_mod
from tempo_tpu.util.kerneltel import TEL
from tempo_tpu.util.testdata import make_traces
from tempo_tpu.wire import otlp_json

TENANT = "single-tenant"


def _db(tmp_path, backend=None, name="wal"):
    cfg = TempoDBConfig(wal_path=str(tmp_path / name))
    return TempoDB(cfg, backend=backend or MemBackend())


# ------------------------------------------------------------ the plane


def test_rule_parsing_validation_and_spec_forms(tmp_path):
    rules, seed = plane.parse_rules(
        {"seed": 9, "rules": [{"site": "backend.*", "action": "latency"}]})
    assert seed == 9 and rules[0].site == "backend.*"
    with pytest.raises(ValueError):
        plane.parse_rules([{"site": "no.such.site"}])
    with pytest.raises(ValueError):
        plane.parse_rules([{"site": "backend.read", "action": "explode"}])
    with pytest.raises(ValueError):
        plane.parse_rules([{"site": "backend.read", "frobnicate": 1}])
    with pytest.raises(ValueError):
        plane.parse_rules([{"site": "backend.read", "p": 1.5}])
    # data-shaped actions must be able to reach a capable site: a rule
    # that could only ever no-op is a lying drill, rejected up front
    with pytest.raises(ValueError):
        plane.parse_rules([{"site": "backend.write", "action": "corrupt"}])
    with pytest.raises(ValueError):
        plane.parse_rules([{"site": "backend.read", "action": "drop"}])

    # spec forms: inline JSON and a rules file path
    p = plane.configure_spec('[{"site": "wal.fsync", "action": "error"}]')
    assert p.rules[0].site == "wal.fsync"
    f = tmp_path / "rules.json"
    f.write_text(json.dumps({"seed": 3, "rules": [
        {"site": "gossip.sync", "action": "drop"}]}))
    p = plane.configure_spec(str(f))
    assert p.seed == 3 and p.rules[0].action == "drop"
    plane.clear()
    assert not plane.is_active()


def test_env_activation(monkeypatch):
    monkeypatch.setenv(plane.ENV, '[{"site": "backend.read", '
                                  '"action": "error"}]')
    plane.reset_for_tests()  # forget the lazy env check
    assert plane.is_active()
    assert plane.status()["enabled"]
    plane.reset_for_tests()


def test_seeded_replay_is_byte_identical():
    rules = [{"site": "backend.read", "action": "error", "p": 0.3}]

    def run(seed):
        plane.configure(rules, seed=seed)
        be = ChaosBackend(MemBackend())
        be.inner.write("t", "b", "o", b"payload")
        for _ in range(200):
            try:
                be.read("t", "b", "o")
            except BackendError:
                pass
        return plane.active().injection_log()

    log1 = run(7)
    log2 = run(7)
    assert log1 == log2 and len(log1) > 20  # replay is exact
    log3 = run(8)
    assert log3 != log1  # the seed is the stream


def test_backend_seam_actions():
    be = ChaosBackend(MemBackend())
    be.inner.write("t", "b", "data.vtpu", b"0123456789")

    plane.configure([{"site": "backend.read_range", "action": "truncate",
                      "frac": 0.5}])
    assert be.read_range("t", "b", "data.vtpu", 0, 10) == b"01234"

    plane.configure([{"site": "backend.read", "action": "corrupt"}])
    corrupted = be.read("t", "b", "data.vtpu")
    assert corrupted != b"0123456789" and len(corrupted) == 10

    plane.configure([{"site": "backend.read", "action": "latency",
                      "latency_s": 0.05}])
    t0 = time.perf_counter()
    assert be.read("t", "b", "data.vtpu") == b"0123456789"
    assert time.perf_counter() - t0 >= 0.05

    # nth trigger: exactly every 2nd call errors
    plane.configure([{"site": "backend.read", "action": "error", "nth": 2}])
    outcomes = []
    for _ in range(6):
        try:
            be.read("t", "b", "data.vtpu")
            outcomes.append("ok")
        except BackendError:
            outcomes.append("err")
    assert outcomes == ["ok", "err"] * 3

    # injected-fault telemetry reached the kerneltel exposition
    lines = TEL.metrics_lines()
    assert any("tempo_chaos_injected_total" in ln for ln in lines)
    st = plane.status()
    assert st["injected_total"] >= 3 and st["recent_injections"]

    # drop on a write seam = the write is silently LOST
    plane.configure([{"site": "backend.write", "action": "drop"}])
    be.write("t", "b", "ghost", b"never lands")
    plane.clear()
    from tempo_tpu.backend.base import DoesNotExist

    with pytest.raises(DoesNotExist):
        be.read("t", "b", "ghost")


def test_wal_torn_append_and_fsync_fault(tmp_path):
    from tempo_tpu.db.wal import WAL
    from tempo_tpu.wire import segment

    # 3rd append torn mid-record: replay must truncate it away cleanly
    plane.configure([{"site": "wal.append", "action": "truncate",
                      "nth": 3, "frac": 0.4}])
    wal = WAL(str(tmp_path))
    blk = wal.new_block("t1")
    for tid, t in make_traces(3, seed=2):
        blk.append(tid, 1, 2, segment.segment_for_write(t, 1, 2))
    blk.close()
    plane.clear()
    replayed = wal.rescan_blocks()
    assert not replayed[0].clean
    assert len(replayed[0].records) == 2

    # fsync fault: the stable write fails loudly, not silently
    plane.configure([{"site": "wal.fsync", "action": "error"}])
    blk2 = wal.new_block("t2")
    tid, t = make_traces(1, seed=3)[0]
    blk2.append(tid, 1, 2, segment.segment_for_write(t, 1, 2))
    with pytest.raises(OSError):
        blk2.flush(sync=True)
    plane.clear()


def test_gossip_partition_and_heal():
    from tempo_tpu.ring.ring import InstanceDesc, InstanceState
    from tempo_tpu.transport.gossip import GossipKV

    a = GossipKV("127.0.0.1:0", interval_s=3600)
    b = GossipKV("127.0.0.1:0", seeds=[a.addr], interval_s=3600)
    try:
        a.update("ring", InstanceDesc(
            instance_id="i1", addr="x", state=InstanceState.ACTIVE,
            tokens=[1], heartbeat_ts=time.time()))
        # partition b -> a: outbound syncs to a's addr are dropped
        plane.configure([{"site": "gossip.sync", "action": "drop",
                          "key": a.addr}])
        assert b.sync_once(a.addr) is False
        assert "i1" not in b.get_all("ring")
        # heal: the same sync converges in one round trip
        plane.clear()
        assert b.sync_once(a.addr) is True
        assert "i1" in b.get_all("ring")
    finally:
        a.close()
        b.close()


def test_device_launch_shim():
    from tempo_tpu.chaos.plane import ChaosCompileError, ChaosDeviceOOM

    plane.configure([{"site": "device.launch", "action": "error",
                      "error": "compile_failure", "key": "filter"}])
    with pytest.raises(ChaosCompileError):
        TEL.record_launch("filter", ("chaos-shim-test", 1), 1024)
    # other ops untouched (key match)
    assert isinstance(TEL.record_launch("reduce", ("chaos-shim-test", 2),
                                        1024), bool)
    plane.configure([{"site": "device.launch", "action": "error",
                      "error": "device_oom"}])
    with pytest.raises(ChaosDeviceOOM):
        TEL.record_launch("filter", ("chaos-shim-test", 3), 1024)
    plane.clear()
    assert isinstance(TEL.record_launch("filter", ("chaos-shim-test", 4),
                                        1024), bool)


def test_rpc_client_tap():
    from tempo_tpu.transport.client import HTTPIngesterClient, TransportError

    c = HTTPIngesterClient("http://127.0.0.1:1")  # nothing listens
    plane.configure([{"site": "rpc.client", "action": "drop"}])
    with pytest.raises(TransportError) as ei:
        c.search("t", SearchRequest(tags={"a": "b"}))
    assert "black-holed" in str(ei.value)
    plane.clear()


# ----------------------------------------------- faults-off differential


def _build_store(tmp_path, name):
    db = _db(tmp_path, name=f"wal-{name}")
    db.cfg.compaction.min_input_blocks = 2
    all_traces = make_traces(24, seed=12, n_spans=5)
    db.write_block(TENANT, all_traces[:12])
    db.write_block(TENANT, all_traces[12:])
    return db, all_traces


def _exercise(db, all_traces):
    """search + find + compact; returns (wire-comparable outputs,
    launches)."""
    TEL.reset()
    l0 = TEL.launch_count()
    req = SearchRequest(tags={"service.name": "db"}, limit=10)
    resp1 = response_to_dict(db.search(TENANT, req))
    db.compact_once(TENANT)
    db.poll_now()
    resp2 = response_to_dict(db.search(TENANT, req))
    tid, _ = all_traces[3]
    found = db.find_trace_by_id(TENANT, tid)
    return (resp1, resp2, otlp_json.dumps(found),
            TEL.launch_count() - l0)


def test_faults_off_differential_bit_identical(tmp_path):
    """Acceptance (c): an ARMED process with no matching rules produces
    byte-identical outputs to an unarmed one, at the same launch count
    -- the taps are provably free when idle."""
    plane.clear()  # unarmed leg (taps are `is None` checks)
    db1, traces1 = _build_store(tmp_path, "off")
    out_off = _exercise(db1, traces1)
    db1.close()

    # armed leg: plane active (backend wrapper interposed) but no rule
    # matches anything this run touches
    plane.configure([{"site": "gossip.sync", "action": "drop",
                      "key": "10.255.255.1:*"}], seed=1)
    db2, traces2 = _build_store(tmp_path, "on")
    assert isinstance(db2.backend, ChaosBackend)
    out_on = _exercise(db2, traces2)
    db2.close()
    plane.clear()

    assert out_off[:3] == out_on[:3]  # bit-identical outputs
    assert out_off[3] == out_on[3]  # zero added launches
    assert plane.status()["enabled"] is False


# --------------------------------------------------- resilience hardening


def _frontend(tmp_path, **kw):
    from tempo_tpu.services.frontend import Frontend
    from tempo_tpu.services.querier import Querier

    db = _db(tmp_path, name=f"wal-fe-{len(os.listdir(tmp_path))}")
    q = Querier(db, None, lambda addr: None, workers=2)
    fe = Frontend(q, n_workers=kw.pop("n_workers", 2),
                  hedge_after_s=kw.pop("hedge_after_s", 0.0), **kw)
    return fe, db


def test_retry_budget_caps_the_storm(tmp_path, monkeypatch):
    """A dying backend used to cost jobs x MAX_RETRIES extra load; the
    per-query budget makes the worst case additive."""
    from tempo_tpu.services.frontend import _Job

    monkeypatch.setenv("TEMPO_RETRY_BUDGET", "2")
    fe, db = _frontend(tmp_path)
    try:
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            raise BackendError("down")

        jobs = [_Job(kind="search_blocks", payload={}, fn=boom, args=())
                for _ in range(4)]
        fe._run_jobs("t", jobs, timeout=10.0)
        assert all(j.error is not None for j in jobs)
        # 4 first tries + exactly the budgeted 2 retries
        assert calls["n"] == 6
        st = TEL.retry_stats()
        assert st.get("retry") == 2
        assert st.get("budget_exhausted", 0) >= 1
        assert "retries" in TEL.snapshot()
    finally:
        fe.stop()
        db.close()


def test_hedge_telemetry_win(tmp_path):
    """A stuck job's hedge twin wins: tempo_hedge_total{outcome="win"}
    ticks and the job span carries the outcome."""
    from tempo_tpu.services.frontend import _Job

    fe, db = _frontend(tmp_path, hedge_after_s=0.05)
    try:
        state = {"calls": 0}

        def slow_then_fast():
            state["calls"] += 1
            if state["calls"] == 1:
                time.sleep(1.0)  # the stuck original
            return "r"

        job = _Job(kind="search_blocks", payload={}, fn=slow_then_fast,
                   args=())
        fe._run_jobs("t", [job], timeout=10.0)
        assert job.result == "r" and job.error is None
        assert job.hedged and job.hedge_outcome == "win"
        assert TEL.hedge_stats().get("win", 0) >= 1
        assert "hedging" in TEL.snapshot()
        assert any("tempo_hedge_total" in ln for ln in TEL.metrics_lines())
    finally:
        fe.stop()
        db.close()


def test_deadline_skips_local_execution(tmp_path):
    from tempo_tpu.services.frontend import _Job

    fe, db = _frontend(tmp_path, n_workers=0)
    try:
        ran = {"n": 0}

        def fn():
            ran["n"] += 1

        job = _Job(kind="search_blocks", payload={}, fn=fn, args=())
        job.deadline_unix = time.time() - 1.0
        fe._execute_one("t", job)
        assert ran["n"] == 0 and job.cancelled and job.done.is_set()
        # the skip surfaces as a shard TIMEOUT, never a silent partial
        # (find/metrics raise on it; search degrades)
        assert isinstance(job.error, TimeoutError)
    finally:
        fe.stop()
        db.close()


def test_deadline_rides_wire_job_and_worker_skips(tmp_path):
    """The frontend stamps the caller deadline on pulled wire jobs; a
    worker that receives an already-dead job posts a non-retryable
    deadline error instead of scanning."""
    from tempo_tpu.services import worker as worker_mod
    from tempo_tpu.services.frontend import _Job

    fe, db = _frontend(tmp_path, n_workers=0)
    try:
        job = _Job(kind="search_blocks", payload={"block_ids": []},
                   fn=lambda: None, args=())
        job.deadline_unix = time.time() + 30.0
        fe.queue.enqueue("t", job)
        wire = fe.poll_job(wait_s=1.0, worker_id="w1")
        # RELATIVE remaining budget on the wire (clock-skew immune)
        assert wire and wire["deadline_in_s"] == pytest.approx(30.0,
                                                               abs=2.0)

        # worker side: a stub frontend hands out a job whose deadline
        # already passed; execute_job must never run
        executed = {"n": 0}
        posted = []

        w = worker_mod.QuerierWorker.__new__(worker_mod.QuerierWorker)
        w.querier = None
        w.token = ""
        w.poll_wait_s = 0.01
        w.worker_id = "w-dead"
        w.jobs_executed = w.jobs_failed = 0
        import threading

        w._stop = threading.Event()
        dead_job = {"id": "j1", "kind": "search_blocks", "tenant": "t",
                    "payload": {}, "deadline_in_s": -5.0}

        def fake_post(addr, path, payload, timeout):
            posted.append((path, payload))
            if path == "/internal/jobs/poll":
                if len(posted) > 1:
                    w._stop.set()
                return dict(dead_job)
            return {}

        w._post = fake_post
        monkey_exec = worker_mod.execute_job

        def counting_exec(*a, **k):
            executed["n"] += 1
            return monkey_exec(*a, **k)

        worker_mod.execute_job = counting_exec
        try:
            w._loop("http://stub")
        finally:
            worker_mod.execute_job = monkey_exec
        results = [p for path, p in posted if path == "/internal/jobs/result"]
        assert executed["n"] == 0
        assert results and results[0]["ok"] is False
        assert "deadline" in results[0]["error"]
        assert results[0]["retryable"] is False
    finally:
        fe.stop()
        db.close()


def test_worker_backoff_is_jittered_exponential(monkeypatch):
    """Frontend down: poll failures back off exponentially (capped) and
    a successful poll resets the clock -- no 1 Hz thundering herd."""
    import random as random_mod
    import threading

    from tempo_tpu.services.worker import QuerierWorker

    monkeypatch.setattr(random_mod, "random", lambda: 1.0)  # kill jitter
    w = QuerierWorker.__new__(QuerierWorker)
    w.querier = None
    w.token = ""
    w.poll_wait_s = 0.01
    w.worker_id = "w-flap"
    w.jobs_executed = w.jobs_failed = 0

    waits = []
    fails = {"n": 0}

    class FakeStop:
        def is_set(self):
            return len(waits) >= 8

        def wait(self, t):
            waits.append(t)
            return False

    w._stop = FakeStop()

    def flapping_post(addr, path, payload, timeout):
        fails["n"] += 1
        if fails["n"] == 6:  # one successful poll mid-flap
            return None  # empty poll = success, resets backoff
        raise OSError("connection refused")

    w._post = flapping_post
    w._loop("http://flap")
    # 0.5 1 2 4 5 (cap) ... then reset after the success ... 0.5 1 ...
    assert waits[:5] == [0.5, 1.0, 2.0, 4.0, 5.0]
    assert waits[5:7] == [0.5, 1.0]  # the reset after one good poll


def test_ingester_leg_breaker_sheds_and_reports(tmp_path):
    """A remote ingester leg that keeps failing is shed fast (degraded
    coverage, like the existing failed-leg tolerance) and shows up in
    the breaker registry."""
    from tempo_tpu.ring.ring import InMemoryKV, Lifecycler, Ring
    from tempo_tpu.services.querier import Querier

    kv = InMemoryKV()
    lc = Lifecycler(kv, "ingester-ring", "remote-1",
                    addr="http://127.0.0.1:1")  # nothing listens
    lc.start()
    db = _db(tmp_path, name="wal-leg")
    from tempo_tpu.transport.client import HTTPIngesterClient

    q = Querier(db, Ring(kv, "ingester-ring"),
                lambda addr: HTTPIngesterClient(addr, timeout=0.2),
                workers=2)
    try:
        br = breaker_mod.get_breaker("ingester:http://127.0.0.1:1",
                                     min_volume=3, error_rate=0.5,
                                     open_s=60.0, window_s=60.0)
        for _ in range(4):
            q.search_recent("t", SearchRequest(tags={"a": "b"}))
        assert br.state == "open"
        # open leg: search_recent still answers (degraded, shed fast)
        t0 = time.perf_counter()
        q.search_recent("t", SearchRequest(tags={"a": "b"}))
        assert time.perf_counter() - t0 < 0.15  # no timeout paid
        assert "ingester:http://127.0.0.1:1" in breaker_mod.breakers_snapshot()
    finally:
        db.close()


# ------------------------------------------------------ acceptance matrix


def _mk_app(tmp_path, **cfg_kw):
    import socket

    from tempo_tpu.services.app import App, AppConfig
    from tempo_tpu.services.ingester import IngesterConfig

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cfg = AppConfig(storage_path=str(tmp_path / "store"), http_port=port,
                    compaction_cycle_s=9999,
                    ingester=IngesterConfig(flush_check_period_s=9999),
                    **cfg_kw)
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    return app, f"http://127.0.0.1:{port}"


def _seed_blocks(app, n=24):
    traces = make_traces(n, seed=21, n_spans=4)
    app.db.write_block(TENANT, traces[: n // 2])
    app.db.write_block(TENANT, traces[n // 2:])
    app.db.poll_now()
    return traces


def _drop_reader_caches(app):
    with app.db._cache_lock:
        app.db._block_cache.clear()


def test_matrix_a_transient_faults_masked(tmp_path, monkeypatch):
    """Acceptance (a): 5%-ish backend 5xx on data reads -- queries keep
    succeeding (retries + shard degradation absorb the faults), the
    read-availability SLO stays ok, and the retry/injection counters
    prove faults actually flowed."""
    monkeypatch.setenv("TEMPO_RETRY_BUDGET", "64")
    # the drill repeats one query to force backend reads; the result
    # cache would serve the repeats without touching the backend
    monkeypatch.setenv("TEMPO_RESULT_CACHE", "0")
    plane.configure([], seed=5)  # arm BEFORE the app builds its backend
    app, base = _mk_app(tmp_path)
    try:
        _seed_blocks(app)
        app.slo.evaluate()  # baseline SLO sample
        plane.configure(
            [{"site": "backend.read*", "action": "error", "p": 0.05,
              "key": "*/data.vtpu"}], seed=5)
        req = SearchRequest(tags={"service.name": "db"}, limit=10)
        for _ in range(12):
            _drop_reader_caches(app)
            resp = app.frontend.search(TENANT, req)
            assert resp is not None  # degraded-at-worst, never an error
        plane_status = plane.status()
        st = TEL.retry_stats()
        slo = app.slo.evaluate()
        assert plane_status["injected_total"] > 0
        assert st.get("retry", 0) > 0  # the masking, visible
        av = slo["objectives"]["read-availability"]
        assert av["verdict"] == "ok", av
        assert av["bad_total"] == 0
        # the whole surface is served over HTTP too
        chaos_http = json.load(urllib.request.urlopen(
            base + "/status/chaos", timeout=10))
        assert chaos_http["enabled"] and chaos_http["injected_total"] > 0
        assert "breakers" in chaos_http and "retries" in chaos_http
    finally:
        plane.clear()
        app.stop()


def test_matrix_b_partition_trips_breaker_then_recovers(tmp_path,
                                                        monkeypatch):
    """Acceptance (b): a sustained backend partition flips the
    burn-rate verdict within one evaluation window and opens the
    circuit breaker; when the rule expires, half-open probes close it
    and reads succeed again."""
    monkeypatch.setenv("TEMPO_BREAKER_MIN_VOLUME", "4")
    monkeypatch.setenv("TEMPO_BREAKER_OPEN_S", "0.3")
    monkeypatch.setenv("TEMPO_BREAKER_PROBES", "1")
    # the drill repeats one by-id lookup to drive the breaker; the
    # result cache would serve the repeats without touching the backend
    monkeypatch.setenv("TEMPO_RESULT_CACHE", "0")
    plane.configure([], seed=2)
    app, _base = _mk_app(tmp_path)
    try:
        traces = _seed_blocks(app)
        app.slo.evaluate()  # window-opening sample, everything green
        tid = traces[2][0]
        assert app.frontend.find_trace_by_id(TENANT, tid) is not None

        # ---- the partition: every backend read fails for ~1.2 s
        plane.configure([{"site": "backend.read*", "action": "error",
                          "for_s": 1.2}], seed=2)
        req = SearchRequest(tags={"service.name": "db"}, limit=10)
        for _ in range(3):
            _drop_reader_caches(app)
            app.frontend.search(TENANT, req)  # shards fail -> breaker food
        errors = 0
        for _ in range(4):
            _drop_reader_caches(app)
            try:
                app.frontend.find_trace_by_id(TENANT, tid)
            except Exception:
                errors += 1
        assert errors >= 1
        br = app.frontend.backend_breaker
        assert br.state == "open", br.snapshot()
        slo = app.slo.evaluate()  # ONE evaluation window later
        av = slo["objectives"]["read-availability"]
        assert av["verdict"] == "critical", av
        assert av["burn_rates"]["5m"] > 14.4

        # ---- the rule expires; half-open probes must recover the leg
        time.sleep(1.4)  # past for_s AND past open_s
        _drop_reader_caches(app)
        for _ in range(4):
            app.frontend.search(TENANT, req)  # probe traffic
            if br.state == "closed":
                break
        assert br.state == "closed", br.snapshot()
        to_states = [t["to"] for t in br.snapshot()["transitions"]]
        assert to_states[-3:] == ["open", "half_open", "closed"] or \
            to_states[-2:] == ["half_open", "closed"], to_states
        got = app.frontend.find_trace_by_id(TENANT, tid)
        assert got is not None  # the read path healed
        assert any("tempo_circuit_breaker_state" in ln
                   for ln in TEL.metrics_lines())
    finally:
        plane.clear()
        app.stop()


def test_vulture_under_chaos_stays_green(tmp_path, monkeypatch):
    """The PR-11 loop closed: the continuous-verification prober runs a
    full cycle WHILE transient faults are being injected into the
    backend data path -- every probe family still passes (the armor
    masks the faults), and the injection counters prove chaos was
    live."""
    from tempo_tpu.vulture import Vulture, VultureConfig

    monkeypatch.setenv("TEMPO_RETRY_BUDGET", "64")
    plane.configure([], seed=11)
    app, base = _mk_app(tmp_path)
    try:
        _seed_blocks(app)  # flushed blocks for search coverage
        plane.configure(
            [{"site": "backend.read*", "action": "error", "p": 0.04,
              "key": "*/data.vtpu"}], seed=11)
        v = Vulture(VultureConfig(
            push_url=base, query_url=base, visibility_timeout_s=10.0,
            retry_interval_s=0.05, spans_per_trace=3, batch_ids=3,
            flush_every=0, seed=4))  # live families; cold probes use an
        # unretried fresh reader by design and get their own matrix legs
        results = v.cycle()
        assert Vulture.ok(results), [
            (r.family, r.outcome, r.detail) for r in results
            if r.outcome != "ok"]
        assert v.status()["slo"]["verdict"] == "ok"
    finally:
        plane.clear()
        app.stop()


def test_soak_chaos_flag_reports_injections(tmp_path, monkeypatch):
    """soak --chaos against an in-process armed app: the run stays ok
    and the report carries the injection/retry evidence."""
    import io
    from contextlib import redirect_stdout

    import soak

    monkeypatch.setenv("TEMPO_RETRY_BUDGET", "64")
    plane.configure([], seed=1)
    app, base = _mk_app(tmp_path)
    try:
        _seed_blocks(app)
        # the default spec's shape, key-restricted to data objects so
        # the UNRETRIED fresh-reader legs (bloom probes of unrelated
        # blocks) stay deterministic inside tier-1
        plane.configure(
            [{"site": "backend.read*", "action": "error", "p": 0.05,
              "key": "*/data.vtpu"},
             {"site": "rpc.client", "action": "latency",
              "latency_s": 0.005, "p": 0.1}], seed=1)
        # reader-cache churn so soak searches keep paying backend reads
        # (a warm block cache would serve the whole soak injection-free)
        import threading

        stop_churn = threading.Event()

        def churn():
            while not stop_churn.wait(0.2):
                _drop_reader_caches(app)

        churner = threading.Thread(target=churn, daemon=True)
        churner.start()
        buf = io.StringIO()
        try:
            with redirect_stdout(buf):
                rc = soak.main(["--target", base, "--duration", "3",
                                "--writers", "1", "--readers", "1",
                                "--chaos"])
        finally:
            stop_churn.set()
            churner.join(timeout=5)
        report = json.loads(buf.getvalue())
        assert rc == 0, report
        assert report["ok"]
        assert report["chaos"]["enabled"]
        assert report["chaos"]["injected_total"] > 0
    finally:
        plane.clear()
        app.stop()


# ------------------------------------------------------- runtime control


def test_internal_chaos_endpoint_and_cli(tmp_path, capsys):
    """POST /internal/chaos swaps rules at runtime; the CLI validates a
    rules file, lists sites, and injects/clears against a live app."""
    from tempo_tpu.cli.__main__ import main as cli_main

    plane.configure([], seed=0)  # armed, empty
    app, base = _mk_app(tmp_path)
    try:
        # CLI: sites + validate
        cli_main(["chaos", "sites"])
        out = capsys.readouterr().out
        assert "backend.read" in out and "device.launch" in out
        rules_file = tmp_path / "rules.json"
        rules_file.write_text(json.dumps({"seed": 6, "rules": [
            {"site": "rpc.client", "action": "latency",
             "latency_s": 0.01}]}))
        cli_main(["chaos", "validate", str(rules_file)])
        assert json.loads(capsys.readouterr().out)["seed"] == 6
        bad = tmp_path / "bad.json"
        bad.write_text('[{"site": "nope"}]')
        with pytest.raises(SystemExit):
            cli_main(["chaos", "validate", str(bad)])
        capsys.readouterr()

        # CLI: inject against the live app, observe, clear
        cli_main(["chaos", "inject", base, "--rules", str(rules_file)])
        injected = json.loads(capsys.readouterr().out)
        assert injected["enabled"] and injected["rules"][0]["site"] == "rpc.client"
        assert plane.is_active() and plane.active().seed == 6
        cli_main(["chaos", "status", base])
        assert json.loads(capsys.readouterr().out)["enabled"]
        cli_main(["chaos", "inject", base, "--clear"])
        assert json.loads(capsys.readouterr().out)["enabled"] is False
        assert not plane.is_active()

        # bad rules 400 at the endpoint
        import urllib.error

        req = urllib.request.Request(
            base + "/internal/chaos",
            data=json.dumps({"rules": [{"site": "nope"}]}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
    finally:
        plane.clear()
        app.stop()


# ------------------------------------------------------------ AOT warmup


def test_warmup_corpus_and_run(tmp_path):
    """First compiles land in the CostLedger corpus; run_warmup replays
    it through the canonical builders (and the app flag surfaces the
    report)."""
    from tempo_tpu.util import costledger, warmup

    led_path = str(tmp_path / "ledger.json")
    costledger.configure(led_path)
    warmup.reset_for_tests()
    try:
        # a real first compile records its (op, bucket) pair durably
        TEL.reset()
        warmup._warm_filter(1024)
        pairs = warmup.corpus()
        assert ["filter", "1024"] in [list(p) for p in pairs], pairs
        on_disk = json.loads(open(led_path).read())
        assert on_disk["entries"]["compile_corpus"]["pairs"]

        # replaying the corpus compiles without error and reports it
        report = warmup.run_warmup()
        assert ["filter", "1024"] in report["warmed"]
        assert not report["errors"]
    finally:
        costledger.reset_for_tests()
        warmup.reset_for_tests()


def test_warmup_app_flag(tmp_path, monkeypatch):
    """--warmup.shapes: the app compiles the corpus before serving and
    /status/chaos carries the report."""
    from tempo_tpu.util import costledger, warmup

    # the env pin keeps App.__init__ from repointing the ledger at
    # <storage>/cost_ledger.json (operator-aimed env wins by contract)
    monkeypatch.setenv(costledger.LEDGER_ENV, str(tmp_path / "ledger.json"))
    costledger.configure(str(tmp_path / "ledger.json"))
    warmup.reset_for_tests()
    costledger.ledger().update(warmup.CORPUS_KEY,
                               pairs=[["filter", "1024"], ["nosuch", "64"]])
    app, base = _mk_app(tmp_path, warmup_shapes=True)
    try:
        assert app.warmup_report is not None
        assert ["filter", "1024"] in app.warmup_report["warmed"]
        assert ["nosuch", "64"] in app.warmup_report["skipped"]
        st = json.load(urllib.request.urlopen(base + "/status/chaos",
                                              timeout=10))
        assert st["warmup"]["warmed"]
    finally:
        app.stop()
        costledger.reset_for_tests()
        warmup.reset_for_tests()
