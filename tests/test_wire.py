"""Wire model round-trip tests (model <-> OTLP proto <-> OTLP JSON, segments)."""

import random

import pytest

from tempo_tpu.util.hashing import bloom_hashes, fnv1a_32, fnv1a_64, ring_token
from tempo_tpu.util.testdata import make_trace, make_traces
from tempo_tpu.util.traceid import InvalidTraceID, parse_trace_id, trace_id_to_hex
from tempo_tpu.wire import combine, otlp_json, otlp_pb, segment
from tempo_tpu.wire.model import Span, Trace


def test_fnv_known_vectors():
    # published FNV-1a test vectors
    assert fnv1a_32(b"") == 0x811C9DC5
    assert fnv1a_32(b"a") == 0xE40C292C
    assert fnv1a_64(b"") == 0xCBF29CE484222325
    assert fnv1a_64(b"a") == 0xAF63DC4C8601EC8C


def test_ring_token_stable():
    t1 = ring_token("tenant-a", b"\x01" * 16)
    assert t1 == ring_token("tenant-a", b"\x01" * 16)
    assert t1 != ring_token("tenant-b", b"\x01" * 16)
    assert 0 <= t1 < 2**32


def test_bloom_hashes_in_range():
    hs = bloom_hashes(b"trace-id-bytes", k=5, m_bits=1024)
    assert len(hs) == 5
    assert all(0 <= h < 1024 for h in hs)


def test_trace_id_parse():
    assert parse_trace_id("0102") == b"\x00" * 14 + b"\x01\x02"
    assert trace_id_to_hex(b"\x01\x02") == "00" * 14 + "0102"
    with pytest.raises(InvalidTraceID):
        parse_trace_id("zz")
    with pytest.raises(InvalidTraceID):
        parse_trace_id("ab" * 17)


def _spans_by_id(t: Trace) -> dict:
    return {sp.span_id: sp for _, _, sp in t.all_spans()}


def test_otlp_pb_roundtrip():
    t = make_trace(7, n_spans=20)
    data = otlp_pb.encode_trace(t)
    t2 = otlp_pb.decode_trace(data)
    a, b = _spans_by_id(t), _spans_by_id(t2)
    assert set(a) == set(b)
    for sid, sp in a.items():
        sp2 = b[sid]
        assert sp2.name == sp.name
        assert sp2.start_unix_nano == sp.start_unix_nano
        assert sp2.end_unix_nano == sp.end_unix_nano
        assert sp2.kind == sp.kind
        assert sp2.status_code == sp.status_code
        assert sp2.attrs == sp.attrs
        assert len(sp2.events) == len(sp.events)
    # resource attrs preserved
    assert t2.resource_spans[0].resource.attrs == t.resource_spans[0].resource.attrs


def test_otlp_pb_value_types():
    t = make_trace(3, n_spans=1)
    sp = next(t.all_spans())[2]
    sp.attrs = {"s": "x", "b_t": True, "b_f": False, "i": -42, "f": 2.5, "by": b"\x00\x01", "arr": ["a", 1]}
    t2 = otlp_pb.decode_trace(otlp_pb.encode_trace(t))
    sp2 = next(t2.all_spans())[2]
    assert sp2.attrs == sp.attrs
    assert sp2.attrs["b_f"] is False


def test_otlp_json_roundtrip():
    t = make_trace(11, n_spans=12)
    s = otlp_json.dumps(t)
    t2 = otlp_json.loads(s)
    a, b = _spans_by_id(t), _spans_by_id(t2)
    assert set(a) == set(b)
    for sid in a:
        assert a[sid].attrs == b[sid].attrs
        assert a[sid].name == b[sid].name


def test_segment_roundtrip_and_fastrange():
    t = make_trace(5, n_spans=6)
    seg = segment.segment_for_write(t, 100, 200)
    assert segment.segment_fast_range(seg) == (100, 200)
    t2 = segment.segment_to_trace(seg)
    assert _spans_by_id(t2).keys() == _spans_by_id(t).keys()

    obj = segment.segments_to_object([seg, segment.segment_for_write(t, 50, 150)])
    assert segment.object_fast_range(obj) == (50, 200)
    t3 = segment.object_to_trace(obj)
    # same spans after dedupe
    assert _spans_by_id(t3).keys() == _spans_by_id(t).keys()


def test_combine_dedupes_replicas():
    rng = random.Random(9)
    t = make_trace(rng, n_spans=10)
    import copy

    t_copy = copy.deepcopy(t)
    combined = combine.combine_traces([t, t_copy])
    assert combined.span_count() == 10


def test_combine_merges_disjoint():
    tid = b"\xaa" * 16
    t1 = make_trace(1, trace_id=tid, n_spans=4)
    t2 = make_trace(2, trace_id=tid, n_spans=5)
    combined = combine.combine_traces([t1, t2])
    assert combined.span_count() == 9
    assert combined.trace_id() == tid


def test_make_traces_sorted_unique():
    traces = make_traces(20, seed=3)
    ids = [tid for tid, _ in traces]
    assert ids == sorted(ids)
    assert len(set(ids)) == 20


def test_binary_frames_roundtrip_and_overhead():
    """The internal data plane's frame envelope (transport/frames.py):
    lossless round-trip and <5% framing overhead on realistic segment
    batches (VERDICT r3 item 8; replaces JSON+base64's 33% tax)."""
    import os as _os

    from tempo_tpu.transport import frames
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire import segment

    batch = []
    for tid, t in make_traces(50, seed=3, n_spans=8):
        batch.append((tid, 100, 200, segment.segment_for_write(t, 100, 200)))
    body = frames.encode_push("tenant-1", batch)
    tenant, got = frames.decode_push(body)
    assert tenant == "tenant-1" and got == [
        (tid.rjust(16, b"\x00")[:16], s, e, seg) for tid, s, e, seg in batch
    ]
    payload = sum(len(seg) for _, _, _, seg in batch)
    # overhead vs raw segment bytes (compressible bodies may come out
    # SMALLER than the payload thanks to whole-body zstd)
    assert len(body) < payload * 1.05, (len(body), payload)

    # incompressible segments still stay under the envelope budget
    rnd = [( _os.urandom(16), 1, 2, _os.urandom(4096)) for _ in range(64)]
    body2 = frames.encode_push("t", rnd)
    payload2 = sum(len(s) for _, _, _, s in rnd)
    assert len(body2) < payload2 * 1.05
    assert frames.decode_push(body2)[1] == rnd

    # trace blobs: generator forward path
    traces = [t for _, t in make_traces(5, seed=4, n_spans=3)]
    tb = frames.encode_traces("t2", traces)
    t2, got_traces = frames.decode_traces(tb)
    assert t2 == "t2" and len(got_traces) == 5
    assert got_traces[0].span_count() == traces[0].span_count()
