"""Tiered cache plane tests (result cache + host chunk pool).

Tier A (services/resultcache): the frontend result cache must be
invisible to correctness -- a cache-on frontend answers every query
with the same payload a cache-off frontend computes fresh, across
pushes, flushes and compactions (the generation pair does the
invalidation); incremental extension (cached immutable prefix + tail
re-execution) must equal a full fresh execution; a hit must run zero
device launches and never reach the executor.

Tier B (ops/chunkpool): a demote -> restage round trip must rebuild
the StagedBlock bit-identically under every codec, serve it without
touching the backend read path, and keep the pool inside its
compressed-byte budget with consistent counters.

Differential corpora are pushed with now-stamped spans: the cache's
documented arrival model is "spans arrive within the live window of
their start time" -- backdated arrivals into an already-cached
historical range are accepted staleness, bounded by the TTL, and are
NOT what these tests exercise.
"""

import time

import numpy as np
import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.block import build_block_from_traces, open_block
from tempo_tpu.db.metrics_exec import align_params
from tempo_tpu.db.metrics_exec import response_to_dict as metrics_to_dict
from tempo_tpu.db.search import SearchRequest, response_to_dict
from tempo_tpu.ops import chunkpool
from tempo_tpu.ops.filter import Cond, required_columns
from tempo_tpu.ops.stage import stage_block
from tempo_tpu.util.testdata import make_traces
from tempo_tpu.wire import otlp_pb

TENANT = "t"


@pytest.fixture(autouse=True)
def _clean_chunkpool():
    chunkpool.clear()
    yield
    chunkpool.clear()


def _mk_app(tmp_path, name):
    from tempo_tpu.services.app import App, AppConfig, IngesterConfig

    cfg = AppConfig(
        target="all", http_port=0, storage_path=str(tmp_path / name),
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    return app


def _canon_search(resp) -> list:
    """The result content, order-normalized; inspected* telemetry is
    execution cost, not result, and legitimately differs between a
    cached answer and a fresh scan."""
    return sorted(response_to_dict(resp)["traces"], key=lambda t: t["traceID"])


def _canon_metrics(resp) -> dict:
    d = metrics_to_dict(resp)
    return {
        "fn": d["fn"], "start_ms": d["start_ms"], "step_ms": d["step_ms"],
        "n_buckets": d["n_buckets"], "label_names": d["label_names"],
        "series": sorted(d["series"], key=lambda s: tuple(s["labels"])),
    }


# ---------------------------------------------------- Tier A: result cache
def test_result_cache_differential_on_off(tmp_path, monkeypatch):
    """Cache-on and cache-off frontends fed the identical
    push/flush/compact interleaving answer every query identically at
    every checkpoint -- with the cache-on app asked twice, so both the
    store path and the hit/extend path are compared."""
    monkeypatch.setenv("TEMPO_RESULT_CACHE", "1")
    on = _mk_app(tmp_path, "on")
    monkeypatch.setenv("TEMPO_RESULT_CACHE", "0")
    off = _mk_app(tmp_path, "off")
    try:
        assert on.frontend.result_cache is not None
        assert off.frontend.result_cache is None
        t_on, t_off = on.tenant_of({}), off.tenant_of({})
        seed = [0]

        def push(n):
            seed[0] += 1
            now_ns = time.time_ns()
            for _, tr in make_traces(n, seed=100 + seed[0], n_spans=4,
                                     base_time_ns=now_ns):
                blob = otlp_pb.encode_trace(tr)
                on.distributor.push_raw(t_on, blob)
                off.distributor.push_raw(t_off, blob)

        def flush():
            for app, ten in ((on, t_on), (off, t_off)):
                app.ingester.flush_all()
                app.db.poll_now()

        def compact():
            for app, ten in ((on, t_on), (off, t_off)):
                app.db.cfg.compaction.min_input_blocks = 2
                app.db.compact_once(ten)
                app.db.poll_now()

        grid0 = (int(time.time()) // 5) * 5 - 300

        def check():
            now = int(time.time())
            sreqs = [
                SearchRequest(query="{ true }", limit=500),
                SearchRequest(query="{ true }", start=now - 300, end=now + 5,
                              limit=500),
            ]
            for req in sreqs:
                fresh = _canon_search(off.frontend.search(t_off, req))
                first = _canon_search(on.frontend.search(t_on, req))
                again = _canon_search(on.frontend.search(t_on, req))
                assert first == fresh
                assert again == fresh
            mreq = align_params("{ true } | count_over_time()",
                                grid0, now + 5, 5.0)
            mfresh = _canon_metrics(off.frontend.metrics_query_range(t_off, mreq))
            mfirst = _canon_metrics(on.frontend.metrics_query_range(t_on, mreq))
            magain = _canon_metrics(on.frontend.metrics_query_range(t_on, mreq))
            assert mfirst == mfresh
            assert magain == mfresh

        push(6); check()
        flush(); check()
        push(6); check()
        flush(); check()
        compact(); check()
        rc = on.frontend.result_cache
        # the repeats were served by the cache, and the mutation
        # checkpoints actually invalidated (not just missed)
        assert rc.stats_hits >= 1
        assert rc.stats_invalidations >= 1
    finally:
        on.stop()
        off.stop()


def test_extension_matches_fresh_execution(tmp_path, monkeypatch):
    """A moving now-edge repeat (cached immutable prefix + re-executed
    tail) must equal a full fresh execution, for search and metrics."""
    monkeypatch.setenv("TEMPO_RESULT_CACHE", "1")
    monkeypatch.setenv("TEMPO_RESULT_CACHE_LIVE_WINDOW_S", "2.0")
    app = _mk_app(tmp_path, "ext")
    try:
        tenant = app.tenant_of({})
        rc = app.frontend.result_cache
        # batch A: stamped 30s back, flushed to the backend -- the
        # immutable prefix content
        for _, tr in make_traces(10, seed=1, n_spans=4,
                                 base_time_ns=time.time_ns() - 30 * 10**9):
            app.distributor.push_raw(tenant, otlp_pb.encode_trace(tr))
        app.ingester.flush_all()
        app.db.poll_now()

        t1 = int(time.time())
        sreq1 = SearchRequest(query="{ true }", start=t1 - 60, end=t1, limit=500)
        app.frontend.search(tenant, sreq1)  # miss: stores exact + prefix
        mreq1 = align_params("{ true } | count_over_time()",
                             t1 - 300, t1, 5.0)
        app.frontend.metrics_query_range(tenant, mreq1)

        # batch B: now-stamped, lives in the ingester head -- only the
        # tail slice can see it
        for _, tr in make_traces(8, seed=2, n_spans=4,
                                 base_time_ns=time.time_ns()):
            app.distributor.push_raw(tenant, otlp_pb.encode_trace(tr))

        ext0 = rc.stats_extensions
        t2 = int(time.time()) + 1
        sreq2 = SearchRequest(query="{ true }", start=t1 - 60, end=t2, limit=500)
        got = _canon_search(app.frontend.search(tenant, sreq2))
        want = _canon_search(app.frontend._search_exec(tenant, sreq2))
        assert got == want
        assert any(True for _ in got), "extension corpus not searchable"

        mreq2 = align_params("{ true } | count_over_time()",
                             t1 - 300, t2 + 5, 5.0)
        mgot = _canon_metrics(app.frontend.metrics_query_range(tenant, mreq2))
        mwant = _canon_metrics(app.frontend._metrics_exec(tenant, mreq2))
        assert mgot == mwant
        assert rc.stats_extensions > ext0, \
            "repeat did not take the extension path"
    finally:
        app.stop()


def test_generation_invalidation(tmp_path, monkeypatch):
    """Push (live gen), flush+poll and compaction (blocklist gen) must
    each invalidate, with fresh data visible immediately after."""
    monkeypatch.setenv("TEMPO_RESULT_CACHE", "1")
    app = _mk_app(tmp_path, "gen")
    try:
        tenant = app.tenant_of({})
        rc = app.frontend.result_cache

        def push(n, seed):
            tids = []
            for tid, tr in make_traces(n, seed=seed, n_spans=4,
                                       base_time_ns=time.time_ns()):
                app.distributor.push_raw(tenant, otlp_pb.encode_trace(tr))
                tids.append(tid)
            return tids

        tids = push(6, 11)
        req = SearchRequest(query="{ true }", limit=500)
        r1 = app.frontend.search(tenant, req)
        h0 = rc.stats_hits
        r2 = app.frontend.search(tenant, req)
        assert rc.stats_hits == h0 + 1
        assert _canon_search(r2) == _canon_search(r1)

        # by-id rides the same generations
        b0 = rc.stats_hits
        tr1 = app.frontend.find_trace_by_id(tenant, tids[0])
        tr2 = app.frontend.find_trace_by_id(tenant, tids[0])
        assert tr1 is not None and tr2 == tr1
        assert rc.stats_hits == b0 + 1

        # push -> live generation bump: the new trace must be visible
        inv0 = rc.stats_invalidations
        new_tids = push(2, 12)
        r3 = app.frontend.search(tenant, req)
        assert new_tids[0].hex() in {t["traceID"] for t in _canon_search(r3)}
        assert rc.stats_invalidations > inv0

        # flush + poll -> blocklist generation bump; the trace set is
        # unchanged (same corpus, different placement -- presentation
        # fields like rootTraceName are leg-dependent), entry re-keyed
        def ids(resp):
            return sorted((t["traceID"], t["startTimeUnixNano"])
                          for t in response_to_dict(resp)["traces"])

        inv1 = rc.stats_invalidations
        app.ingester.flush_all()
        app.db.poll_now()
        r4 = app.frontend.search(tenant, req)
        assert ids(r4) == ids(r3)
        assert rc.stats_invalidations > inv1

        # second block, then compaction -> blocklist generation bump
        push(2, 13)
        app.ingester.flush_all()
        app.db.poll_now()
        r5 = app.frontend.search(tenant, req)
        inv2 = rc.stats_invalidations
        app.db.cfg.compaction.min_input_blocks = 2
        assert app.db.compact_once(tenant), "compaction did not run"
        app.db.poll_now()
        r6 = app.frontend.search(tenant, req)
        assert ids(r6) == ids(r5)
        assert rc.stats_invalidations > inv2
    finally:
        app.stop()


def test_result_cache_hit_zero_work(tmp_path, monkeypatch):
    """An exact hit is answered entirely at the cache layer: zero
    device launches, and the executor is provably never entered."""
    from tempo_tpu.util.kerneltel import TEL

    monkeypatch.setenv("TEMPO_RESULT_CACHE", "1")
    app = _mk_app(tmp_path, "zero")
    try:
        tenant = app.tenant_of({})
        for _, tr in make_traces(8, seed=3, n_spans=4,
                                 base_time_ns=time.time_ns()):
            app.distributor.push_raw(tenant, otlp_pb.encode_trace(tr))
        req = SearchRequest(query="{ true }", limit=500)
        r1 = app.frontend.search(tenant, req)
        assert r1.traces

        def boom(*a, **k):
            raise AssertionError("cache hit reached the executor")

        monkeypatch.setattr(app.frontend, "_search_exec", boom)
        l0 = TEL.launch_count()
        r2 = app.frontend.search(tenant, req)
        assert TEL.launch_count() - l0 == 0
        assert _canon_search(r2) == _canon_search(r1)
    finally:
        app.stop()


def test_result_cache_kill_switch(tmp_path, monkeypatch):
    """TEMPO_RESULT_CACHE=0 skips construction entirely -- every
    request executes fresh through the pre-cache path."""
    monkeypatch.setenv("TEMPO_RESULT_CACHE", "0")
    app = _mk_app(tmp_path, "off2")
    try:
        assert app.frontend.result_cache is None
        tenant = app.tenant_of({})
        for _, tr in make_traces(4, seed=4, n_spans=4,
                                 base_time_ns=time.time_ns()):
            app.distributor.push_raw(tenant, otlp_pb.encode_trace(tr))
        req = SearchRequest(query="{ true }", limit=500)
        r1 = app.frontend.search(tenant, req)
        r2 = app.frontend.search(tenant, req)
        assert r1.traces and _canon_search(r2) == _canon_search(r1)
    finally:
        app.stop()


# ---------------------------------------------------- Tier B: chunk pool
def _block(n_traces=120, seed=5):
    backend = MemBackend()
    traces = make_traces(n_traces, seed=seed, n_spans=10)
    meta = build_block_from_traces(backend, TENANT, traces, row_group_spans=256)
    return backend, meta, open_block(backend, TENANT, meta.block_id)


_NEEDED = required_columns((Cond(target="res", col="res.service_id", op="eq"),))


@pytest.mark.parametrize("codec", ["none", "lz4", "snappy", "zstd"])
def test_chunkpool_roundtrip_bit_identity(codec, monkeypatch):
    """demote -> restage rebuilds the StagedBlock bit-identically
    under every codec: same columns, same dtypes/shapes/bytes, same
    padded-shape metadata."""
    monkeypatch.setenv("TEMPO_CHUNK_CACHE", "1")
    monkeypatch.setenv("TEMPO_CHUNK_CACHE_CODEC", codec)
    monkeypatch.setenv("TEMPO_CHUNK_CACHE_MIN_REUSE", "1")
    _, meta, blk = _block()
    staged = stage_block(blk, _NEEDED)
    ref = {k: np.asarray(v).copy() for k, v in staged.cols.items()}
    shape_ref = (staged.n_spans, staged.n_traces, staged.n_res,
                 staged.n_spans_b, staged.n_traces_b, staged.n_res_b,
                 staged.span_base)
    key = (tuple(_NEEDED), None)
    assert chunkpool.demote(meta.block_id, key, staged)
    got = chunkpool.restage(meta.block_id, key)
    assert got is not None
    assert set(got.cols) == set(ref)
    for name in ref:
        arr = np.asarray(got.cols[name])
        assert arr.dtype == ref[name].dtype
        np.testing.assert_array_equal(arr, ref[name])
    assert (got.n_spans, got.n_traces, got.n_res, got.n_spans_b,
            got.n_traces_b, got.n_res_b, got.span_base) == shape_ref
    assert chunkpool.stats()["codec"] == codec


def test_chunkpool_restage_skips_backend_read(monkeypatch):
    """A fresh reader staging a pooled entry must be served from the
    pool: the backend read/decode/assemble path is provably never
    entered."""
    monkeypatch.setenv("TEMPO_CHUNK_CACHE", "1")
    monkeypatch.setenv("TEMPO_CHUNK_CACHE_CODEC", "none")
    monkeypatch.setenv("TEMPO_CHUNK_CACHE_MIN_REUSE", "1")
    backend, meta, blk = _block()
    staged = stage_block(blk, _NEEDED)
    ref = {k: np.asarray(v).copy() for k, v in staged.cols.items()}
    key = (tuple(_NEEDED), None)
    assert chunkpool.demote(meta.block_id, key, staged)

    def boom(*a, **k):
        raise AssertionError("restage fell through to the backend read path")

    monkeypatch.setattr("tempo_tpu.ops.stage.read_stage_columns", boom)
    h0 = chunkpool.stats()["hits"]
    fresh_blk = open_block(backend, TENANT, meta.block_id)
    warm = stage_block(fresh_blk, _NEEDED)
    assert chunkpool.stats()["hits"] == h0 + 1
    for name in ref:
        np.testing.assert_array_equal(np.asarray(warm.cols[name]), ref[name])


def test_chunkpool_budget_and_admission(monkeypatch):
    """The pool stays inside its compressed-byte budget (LRU-oldest
    evicted, counters consistent) and the per-entry/reuse admission
    gates reject what they should."""
    monkeypatch.setenv("TEMPO_CHUNK_CACHE", "1")
    monkeypatch.setenv("TEMPO_CHUNK_CACHE_CODEC", "none")
    monkeypatch.setenv("TEMPO_CHUNK_CACHE_MIN_REUSE", "1")
    key = (tuple(_NEEDED), None)
    blocks = []
    for i in range(4):
        _, meta, blk = _block(n_traces=60, seed=20 + i)
        blocks.append((meta, stage_block(blk, _NEEDED, cache=False)))

    # size one entry, then budget for two-and-a-half of them
    s0 = chunkpool.stats()
    assert chunkpool.demote(blocks[0][0].block_id, key, blocks[0][1])
    one = chunkpool.stats()["compressed_bytes"]
    assert one > 0
    monkeypatch.setenv("TEMPO_CHUNK_CACHE_BUDGET", str(one * 5 // 2))
    for meta, staged in blocks[1:]:
        assert chunkpool.demote(meta.block_id, key, staged)
    st = chunkpool.stats()
    assert st["compressed_bytes"] <= one * 5 // 2
    assert st["entries"] == 2
    assert st["demotions"] - s0["demotions"] == 4
    assert st["evictions"] - s0["evictions"] == 2
    # LRU order: the oldest two went, the newest two stayed
    assert not chunkpool.probe(blocks[0][0].block_id, key)
    assert not chunkpool.probe(blocks[1][0].block_id, key)
    assert chunkpool.probe(blocks[2][0].block_id, key)
    assert chunkpool.probe(blocks[3][0].block_id, key)

    # per-entry admission cap: an oversized entry is refused
    chunkpool.clear()
    raw = sum(int(np.asarray(a).nbytes) for a in blocks[0][1].cols.values())
    monkeypatch.setenv("TEMPO_CHUNK_CACHE_MAX_ENTRY", str(raw // 2))
    assert not chunkpool.demote(blocks[0][0].block_id, key, blocks[0][1])
    assert chunkpool.stats()["entries"] == 0
    monkeypatch.delenv("TEMPO_CHUNK_CACHE_MAX_ENTRY")

    # reuse admission: one staging is not worth host RAM at MIN_REUSE=2
    monkeypatch.setenv("TEMPO_CHUNK_CACHE_MIN_REUSE", "2")
    assert not chunkpool.demote(blocks[0][0].block_id, key, blocks[0][1])
    chunkpool.note_stage(blocks[0][0].block_id, key)
    chunkpool.note_stage(blocks[0][0].block_id, key)
    assert chunkpool.demote(blocks[0][0].block_id, key, blocks[0][1])


def test_chunk_cache_kill_switch(monkeypatch):
    """TEMPO_CHUNK_CACHE=0 restores discard-on-evict exactly: nothing
    is admitted, probed or restaged."""
    monkeypatch.setenv("TEMPO_CHUNK_CACHE", "0")
    _, meta, blk = _block(n_traces=40, seed=30)
    staged = stage_block(blk, _NEEDED, cache=False)
    key = (tuple(_NEEDED), None)
    d0 = chunkpool.stats()["demotions"]
    assert not chunkpool.demote(meta.block_id, key, staged)
    st = chunkpool.stats()
    assert not st["enabled"]
    assert st["entries"] == 0 and st["demotions"] == d0
    assert not chunkpool.probe(meta.block_id, key)
    assert chunkpool.restage(meta.block_id, key) is None
