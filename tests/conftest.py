"""Test harness config.

Forces JAX onto the host CPU platform with 8 virtual devices BEFORE jax
is imported anywhere, so multi-chip sharding tests (shard_map over a
Mesh) run without TPU hardware. Mirrors the driver's dryrun environment.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def rng():
    import random

    return random.Random(1234)
