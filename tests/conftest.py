"""Test harness config.

Forces JAX onto the host CPU platform with 8 virtual devices BEFORE jax
is imported anywhere, so multi-chip sharding tests (shard_map over a
Mesh) run without TPU hardware. Mirrors the driver's dryrun environment.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the environment ships an 'axon' TPU plugin that re-registers itself even
# when JAX_PLATFORMS=cpu is set pre-import; the config update after import
# is authoritative (verified: 8 CpuDevice, no axon)
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def rng():
    import random

    return random.Random(1234)


@pytest.fixture(autouse=True)
def _isolate_resilience_plane():
    """The chaos plane and the circuit-breaker registry are process-wide
    singletons (by design: one state for /status, /metrics and every
    seam). Between tests they must not leak -- a breaker opened by one
    test's injected failures would shed another test's shard jobs."""
    from tempo_tpu.chaos import plane
    from tempo_tpu.util import breaker

    plane.reset_for_tests()
    breaker.reset_for_tests()
    yield
    plane.reset_for_tests()
    breaker.reset_for_tests()


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: multi-process / long-running e2e tests")
    config.addinivalue_line(
        "markers",
        "fleet: fleet-topology e2e (replication / quorum / rolling restart)")
