"""Multi-chip sharding tests on the 8-virtual-device CPU mesh.

Each sharded kernel is checked against its single-device oracle
(ops/find.py, ops/bloom_ops.py, numpy) to prove the collectives combine
results identically to the host-side merge they replace."""

import numpy as np
import pytest

from tempo_tpu.block import schema as S
from tempo_tpu.block.bloom import ShardedBloom
from tempo_tpu.ops.device import bucket, pad_rows
from tempo_tpu.ops.filter import Cond, Operands, T_RES, T_SPAN
from tempo_tpu.ops.find import lookup_ids
from tempo_tpu.parallel import (
    distributed_query_step,
    make_mesh,
    sharded_bloom_union,
    sharded_find,
    sharded_search,
)
from tempo_tpu.util.testdata import make_traces


@pytest.fixture(scope="module")
def mesh():
    m = make_mesh(8)
    assert m.shape == {"dp": 2, "sp": 4}
    return m


def _id_codes(traces):
    return np.asarray(
        sorted(S.trace_id_to_codes(tid) for tid, _ in traces), dtype=np.int32
    )


def test_sharded_find_matches_per_block(mesh):
    rng = np.random.default_rng(7)
    blocks = []
    all_ids = []
    for b in range(5):  # deliberately not a multiple of 8 -> pad blocks
        traces = make_traces(30 + 7 * b, seed=b, n_spans=1)
        codes = _id_codes(traces)
        blocks.append(codes)
        all_ids.extend(map(tuple, codes))
    # queries: every 3rd real id + 4 misses
    queries = np.asarray(all_ids[::3], dtype=np.int32)
    misses = np.asarray(
        [S.trace_id_to_codes(bytes([i]) * 16) for i in (1, 2, 254, 255)], dtype=np.int32
    )
    queries = np.concatenate([queries, misses])

    out = sharded_find(mesh, blocks, queries)

    for qi, q in enumerate(queries):
        expected = []
        for bi, codes in enumerate(blocks):
            sid = lookup_ids(codes, q[None, :])[0]
            if sid >= 0:
                expected.append((bi, sid))
        blk, row = out[qi]
        if not expected:
            assert blk == -1 and row == -1
        else:
            assert (blk, row) in expected


def test_sharded_search_matches_oracle(mesh):
    rng = np.random.default_rng(3)
    dp, sp = 2, 4
    B, S_rows, NT, R = 4, 64, 16, 8
    cols = {
        "span.trace_sid": rng.integers(0, NT, size=(B, S_rows)).astype(np.int32),
        "span.dur_us": rng.integers(0, 1000, size=(B, S_rows)).astype(np.int32),
        "span.res_idx": rng.integers(0, R, size=(B, S_rows)).astype(np.int32),
        "res.service_id": rng.integers(0, 4, size=(B, R)).astype(np.int32),
    }
    n_spans = np.asarray([64, 50, 64, 3], dtype=np.int32)

    conds = (
        Cond(target=T_SPAN, col="span.dur_us", op="ge"),
        Cond(target=T_RES, col="res.service_id", op="eq"),
    )
    tree = ("and", ("cond", 0), ("cond", 1))
    operands = Operands.build([(0, 500, 0, 0.0, 0.0), (0, 2, 0, 0.0, 0.0)])

    tm, sc = sharded_search(mesh, tree, conds, operands, cols, n_spans, nt=NT)

    for b in range(B):
        valid = np.arange(S_rows) < n_spans[b]
        m1 = cols["span.dur_us"][b] >= 500
        m2 = cols["res.service_id"][b][cols["span.res_idx"][b]] == 2
        sm = m1 & m2 & valid
        counts = np.bincount(cols["span.trace_sid"][b][sm], minlength=NT)[:NT]
        np.testing.assert_array_equal(sc[b], counts)
        np.testing.assert_array_equal(tm[b], counts > 0)


def test_sharded_search_trace_cond_and_table(mesh):
    """Trace-axis conds inside the tree + dictionary-table (regex-style)
    predicates work on the sharded path."""
    rng = np.random.default_rng(9)
    from tempo_tpu.ops.filter import T_TRACE

    B, S_rows, NT = 2, 32, 8
    cols = {
        "span.trace_sid": rng.integers(0, NT, size=(B, S_rows)).astype(np.int32),
        "span.name_id": rng.integers(0, 6, size=(B, S_rows)).astype(np.int32),
        "trace.dur_us": rng.integers(0, 100, size=(B, NT)).astype(np.int32),
    }
    n_spans = np.asarray([32, 20], dtype=np.int32)
    conds = (
        Cond(target=T_SPAN, col="span.name_id", op="intable"),
        Cond(target=T_TRACE, col="trace.dur_us", op="ge"),
    )
    tree = ("and", ("cond", 0), ("cond", 1))
    table = np.asarray([0, 1, 0, 1, 0, 0], dtype=np.uint8)  # codes 1,3 match
    operands = Operands.build(
        [(0, 0, 0, 0.0, 0.0), (0, 40, 0, 0.0, 0.0)], tables={0: table}
    )
    tm, sc = sharded_search(mesh, tree, conds, operands, cols, n_spans, nt=NT)
    for b in range(B):
        valid = np.arange(S_rows) < n_spans[b]
        sm = np.isin(cols["span.name_id"][b], [1, 3]) & valid
        counts = np.bincount(cols["span.trace_sid"][b][sm], minlength=NT)[:NT]
        expected_tm = (counts > 0) & (cols["trace.dur_us"][b] >= 40)
        np.testing.assert_array_equal(tm[b], expected_tm)
        np.testing.assert_array_equal(sc[b], np.where(expected_tm, counts, 0))


def test_sharded_bloom_union(mesh):
    blooms = []
    all_ids = []
    for k in range(5):
        bl = ShardedBloom(4)
        ids = [bytes([k, i]) + b"\x00" * 14 for i in range(20)]
        bl.add_many(ids)
        all_ids.extend(ids)
        blooms.append(bl)
    u = sharded_bloom_union(mesh, blooms)
    for tid in all_ids:
        assert u.test(tid)
    # oracle: numpy OR
    expected = np.zeros_like(blooms[0].words)
    for b in blooms:
        expected |= b.words
    np.testing.assert_array_equal(u.words, expected)


def test_distributed_query_step_one_jit(mesh):
    """The composed step compiles and runs as a single jitted program."""
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    B, T, Q, S_rows, NT, R = 8, 32, 8, 32, 8, 4
    K, NS, W = 8, 2, 16

    ids = np.sort(rng.integers(0, 100, size=(B, T, 4)).astype(np.int32), axis=1)
    for b in range(B):
        ids[b] = ids[b][np.lexsort(ids[b].T[::-1])]
    n_valid = np.full((B,), T, dtype=np.int32)
    queries = ids[:, 0, :][:Q].copy()

    cols = {
        "span.trace_sid": rng.integers(0, NT, size=(B, S_rows)).astype(np.int32),
        "span.dur_us": rng.integers(0, 100, size=(B, S_rows)).astype(np.int32),
    }
    n_spans = np.full((B,), S_rows, dtype=np.int32)
    conds = (Cond(target=T_SPAN, col="span.dur_us", op="ge"),)
    tree = ("cond", 0)
    operands = Operands.build([(0, 50, 0, 0.0, 0.0)])
    blooms = rng.integers(0, 2**32, size=(K, NS, W), dtype=np.uint32)

    names = tuple(sorted(cols))
    step = distributed_query_step(mesh, tree, conds, names, B, T, Q, S_rows, R, NT, K, NS, W)
    hits, tm, sc, bu = step(
        jnp.asarray(ids), jnp.asarray(n_valid), jnp.asarray(queries),
        jnp.asarray(operands.ints), jnp.asarray(operands.floats),
        jnp.asarray(n_spans),
        tuple(jnp.asarray(cols[n]) for n in names),
        jnp.asarray(blooms),
    )
    assert hits.shape == (Q, 2)
    assert np.asarray(tm).shape == (B, NT)
    expected_union = np.zeros((NS, W), dtype=np.uint32)
    for k in range(K):
        expected_union |= blooms[k]
    np.testing.assert_array_equal(np.asarray(bu), expected_union)


def test_graft_dryrun_multichip_entry():
    """Run the exact entry the driver invokes (__graft_entry__.dryrun_multichip)
    on the virtual 8-device CPU mesh, so a driver-side failure reproduces here."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    try:
        import __graft_entry__ as graft

        graft.dryrun_multichip(8)
    finally:
        sys.path.pop(0)


def test_graft_entry_compiles():
    import sys
    from pathlib import Path

    import jax

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    try:
        import __graft_entry__ as graft

        fn, args = graft.entry()
        sids, mask, counts = jax.jit(fn)(*args)
        assert sids.shape[0] == args[1].shape[0]
    finally:
        sys.path.pop(0)
