"""Multi-chip sharding tests on the 8-virtual-device CPU mesh.

Each sharded kernel is checked against its single-device oracle
(ops/find.py, ops/bloom_ops.py, numpy) to prove the collectives combine
results identically to the host-side merge they replace."""

import numpy as np
import pytest

from tempo_tpu.block import schema as S
from tempo_tpu.block.bloom import ShardedBloom
from tempo_tpu.ops.device import bucket, pad_rows
from tempo_tpu.ops.filter import Cond, Operands, T_RES, T_SPAN
from tempo_tpu.ops.find import lookup_ids
from tempo_tpu.parallel import (
    distributed_query_step,
    make_mesh,
    sharded_bloom_union,
    sharded_find,
    sharded_search,
)
from tempo_tpu.util.testdata import make_traces


@pytest.fixture(scope="module")
def mesh():
    m = make_mesh(8)
    assert m.shape == {"dp": 2, "sp": 4}
    return m


def _id_codes(traces):
    return np.asarray(
        sorted(S.trace_id_to_codes(tid) for tid, _ in traces), dtype=np.int32
    )


def test_sharded_find_matches_per_block(mesh):
    rng = np.random.default_rng(7)
    blocks = []
    all_ids = []
    for b in range(5):  # deliberately not a multiple of 8 -> pad blocks
        traces = make_traces(30 + 7 * b, seed=b, n_spans=1)
        codes = _id_codes(traces)
        blocks.append(codes)
        all_ids.extend(map(tuple, codes))
    # queries: every 3rd real id + 4 misses
    queries = np.asarray(all_ids[::3], dtype=np.int32)
    misses = np.asarray(
        [S.trace_id_to_codes(bytes([i]) * 16) for i in (1, 2, 254, 255)], dtype=np.int32
    )
    queries = np.concatenate([queries, misses])

    out = sharded_find(mesh, blocks, queries)

    for qi, q in enumerate(queries):
        expected = []
        for bi, codes in enumerate(blocks):
            sid = lookup_ids(codes, q[None, :])[0]
            if sid >= 0:
                expected.append((bi, sid))
        blk, row = out[qi]
        if not expected:
            assert blk == -1 and row == -1
        else:
            assert (blk, row) in expected


def test_sharded_search_matches_oracle(mesh):
    rng = np.random.default_rng(3)
    dp, sp = 2, 4
    B, S_rows, NT, R = 4, 64, 16, 8
    cols = {
        "span.trace_sid": rng.integers(0, NT, size=(B, S_rows)).astype(np.int32),
        "span.dur_us": rng.integers(0, 1000, size=(B, S_rows)).astype(np.int32),
        "span.res_idx": rng.integers(0, R, size=(B, S_rows)).astype(np.int32),
        "res.service_id": rng.integers(0, 4, size=(B, R)).astype(np.int32),
    }
    n_spans = np.asarray([64, 50, 64, 3], dtype=np.int32)

    conds = (
        Cond(target=T_SPAN, col="span.dur_us", op="ge"),
        Cond(target=T_RES, col="res.service_id", op="eq"),
    )
    tree = ("and", ("cond", 0), ("cond", 1))
    operands = Operands.build([(0, 500, 0, 0.0, 0.0), (0, 2, 0, 0.0, 0.0)])

    tm, sc = sharded_search(mesh, tree, conds, operands, cols, n_spans, nt=NT)

    for b in range(B):
        valid = np.arange(S_rows) < n_spans[b]
        m1 = cols["span.dur_us"][b] >= 500
        m2 = cols["res.service_id"][b][cols["span.res_idx"][b]] == 2
        sm = m1 & m2 & valid
        counts = np.bincount(cols["span.trace_sid"][b][sm], minlength=NT)[:NT]
        np.testing.assert_array_equal(sc[b], counts)
        np.testing.assert_array_equal(tm[b], counts > 0)


def test_sharded_search_trace_cond_and_table(mesh):
    """Trace-axis conds inside the tree + dictionary-table (regex-style)
    predicates work on the sharded path."""
    rng = np.random.default_rng(9)
    from tempo_tpu.ops.filter import T_TRACE

    B, S_rows, NT = 2, 32, 8
    cols = {
        "span.trace_sid": rng.integers(0, NT, size=(B, S_rows)).astype(np.int32),
        "span.name_id": rng.integers(0, 6, size=(B, S_rows)).astype(np.int32),
        "trace.dur_us": rng.integers(0, 100, size=(B, NT)).astype(np.int32),
    }
    n_spans = np.asarray([32, 20], dtype=np.int32)
    conds = (
        Cond(target=T_SPAN, col="span.name_id", op="intable"),
        Cond(target=T_TRACE, col="trace.dur_us", op="ge"),
    )
    tree = ("and", ("cond", 0), ("cond", 1))
    table = np.asarray([0, 1, 0, 1, 0, 0], dtype=np.uint8)  # codes 1,3 match
    operands = Operands.build(
        [(0, 0, 0, 0.0, 0.0), (0, 40, 0, 0.0, 0.0)], tables={0: table}
    )
    tm, sc = sharded_search(mesh, tree, conds, operands, cols, n_spans, nt=NT)
    for b in range(B):
        valid = np.arange(S_rows) < n_spans[b]
        sm = np.isin(cols["span.name_id"][b], [1, 3]) & valid
        counts = np.bincount(cols["span.trace_sid"][b][sm], minlength=NT)[:NT]
        expected_tm = (counts > 0) & (cols["trace.dur_us"][b] >= 40)
        np.testing.assert_array_equal(tm[b], expected_tm)
        np.testing.assert_array_equal(sc[b], np.where(expected_tm, counts, 0))


def test_sharded_search_generic_attr_matches_oracle(mesh):
    """Generic sattr/rattr conds ({span.foo = "bar"} over the attr
    tables) run on the mesh: attr rows shard over sp, owner aggregation
    stitches across shard cuts with psum_scatter/psum. Checked against
    the numpy oracle on raggedy per-span attr counts that straddle the
    4-way sp split."""
    rng = np.random.default_rng(5)
    from tempo_tpu.ops.device import PAD_I32
    from tempo_tpu.ops.filter import T_RATTR, T_SATTR

    B, S_rows, NT, R = 2, 32, 8, 4
    A, RA = 64, 16  # sattr / rattr row buckets (multiples of sp=4)
    n_spans = np.asarray([32, 21], dtype=np.int32)

    cols = {
        "span.trace_sid": rng.integers(0, NT, size=(B, S_rows)).astype(np.int32),
        "span.res_idx": rng.integers(0, R, size=(B, S_rows)).astype(np.int32),
        "sattr.key_id": np.full((B, A), PAD_I32, np.int32),
        "sattr.vtype": np.full((B, A), PAD_I32, np.int32),
        "sattr.str_id": np.full((B, A), PAD_I32, np.int32),
        "sattr.off": np.zeros((B, S_rows + 1), np.int32),
        "rattr.key_id": np.full((B, RA), PAD_I32, np.int32),
        "rattr.vtype": np.full((B, RA), PAD_I32, np.int32),
        "rattr.int32": np.full((B, RA), PAD_I32, np.int32),
        "rattr.off": np.zeros((B, R + 1), np.int32),
    }
    sattr_real = []  # (key, vtype, str_id, owner) per block for the oracle
    rattr_real = []
    for b in range(B):
        counts = rng.integers(0, 4, size=n_spans[b])
        # truncate the tail so the rows fit in A while keeping raggedness
        over = np.cumsum(counts) > A
        counts[over] = 0
        assert counts.sum() > 0
        off = np.zeros(S_rows + 1, np.int32)
        off[1 : n_spans[b] + 1] = np.cumsum(counts)
        off[n_spans[b] + 1 :] = off[n_spans[b]]
        cols["sattr.off"][b] = off
        n_rows = int(off[-1])
        keys = rng.integers(0, 5, size=n_rows).astype(np.int32)
        vts = rng.integers(0, 2, size=n_rows).astype(np.int32)  # str/int mix
        vals = rng.integers(0, 6, size=n_rows).astype(np.int32)
        cols["sattr.key_id"][b, :n_rows] = keys
        cols["sattr.vtype"][b, :n_rows] = vts
        cols["sattr.str_id"][b, :n_rows] = vals
        owners = np.repeat(np.arange(n_spans[b]), counts)
        sattr_real.append((keys, vts, vals, owners))

        rcounts = rng.integers(0, 4, size=R)
        rcounts[np.cumsum(rcounts) > RA] = 0
        roff = np.concatenate([[0], np.cumsum(rcounts)]).astype(np.int32)
        cols["rattr.off"][b] = roff
        rn = int(roff[-1])
        rkeys = rng.integers(0, 3, size=rn).astype(np.int32)
        rvts = np.ones(rn, np.int32)  # int-typed
        rvals = rng.integers(0, 50, size=rn).astype(np.int32)
        cols["rattr.key_id"][b, :rn] = rkeys
        cols["rattr.vtype"][b, :rn] = rvts
        cols["rattr.int32"][b, :rn] = rvals
        rowners = np.repeat(np.arange(R), rcounts)
        rattr_real.append((rkeys, rvts, rvals, rowners))

    conds = (
        Cond(target=T_SATTR, col="str", op="eq"),      # span.foo = code 3
        Cond(target=T_RATTR, col="int", op="ge"),      # resource.bar >= 20
        Cond(target=T_SATTR, col="any", op="exists"),  # span.baz != nil
    )
    tree = ("and", ("cond", 0), ("or", ("cond", 1), ("cond", 2)))
    operands = Operands.build(
        [(2, 3, 0, 0.0, 0.0), (1, 20, 0, 0.0, 0.0), (4, 0, 0, 0.0, 0.0)]
    )
    tm, sc = sharded_search(mesh, tree, conds, operands, cols, n_spans, nt=NT)

    for b in range(B):
        keys, vts, vals, owners = sattr_real[b]
        rkeys, rvts, rvals, rowners = rattr_real[b]
        ns = n_spans[b]
        m0 = np.zeros(S_rows, bool)
        hit0 = (keys == 2) & (vts == 0) & (vals == 3)
        np.logical_or.at(m0, owners[hit0], True)
        rmask = np.zeros(R, bool)
        rhit = (rkeys == 1) & (rvts == 1) & (rvals >= 20)
        np.logical_or.at(rmask, rowners[rhit], True)
        m1 = rmask[cols["span.res_idx"][b]]
        m2 = np.zeros(S_rows, bool)
        np.logical_or.at(m2, owners[keys == 4], True)
        valid = np.arange(S_rows) < ns
        sm = m0 & (m1 | m2) & valid
        counts = np.bincount(cols["span.trace_sid"][b][sm], minlength=NT)[:NT]
        np.testing.assert_array_equal(sc[b], counts, err_msg=f"block {b}")
        np.testing.assert_array_equal(tm[b], counts > 0, err_msg=f"block {b}")


def test_sharded_search_struct_orphans_on_shard_cuts(mesh):
    """The '~' sibling relation's orphan rule (pid == -2 rows are
    mutual siblings when ANY lhs orphan exists) must survive the
    hoisted-gather refactor when orphans land on NON-ZERO sp shards --
    prior oracle coverage only ever placed orphans on shard 0. Checked
    against numpy for all three relations on rows whose parent chains
    and orphans straddle every one of the 4 shard cuts."""
    rng = np.random.default_rng(31)
    B, S_rows, NT = 2, 64, 8  # 4-way sp split: shards of 16 rows
    cols = {
        "span.trace_sid": np.sort(
            rng.integers(0, NT, size=(B, S_rows)).astype(np.int32), axis=1),
        "span.dur_us": rng.integers(0, 100, size=(B, S_rows)).astype(np.int32),
        "span.parent_idx": np.full((B, S_rows), -1, np.int32),
    }
    for b in range(B):
        sid = cols["span.trace_sid"][b]
        prev_same = np.zeros(S_rows, bool)
        prev_same[1:] = sid[1:] == sid[:-1]
        pidx = np.where(prev_same & (rng.random(S_rows) < 0.6),
                        np.arange(S_rows) - 1, -1).astype(np.int32)
        # orphans pinned onto shards 1..3 (rows 16+), never shard 0
        for row in (17, 33, 49, 62):
            pidx[row] = -2
        cols["span.parent_idx"][b] = pidx
    n_spans = np.asarray([64, 52], dtype=np.int32)  # ragged: pads shard 3
    conds = (
        Cond(target=T_SPAN, col="span.dur_us", op="lt"),
        Cond(target=T_SPAN, col="span.dur_us", op="ge"),
    )
    operands = Operands.build([(0, 80, 0, 0.0, 0.0), (0, 20, 0, 0.0, 0.0)])
    for op in (">", ">>", "~"):
        tree = ("struct", op, ("cond", 0), ("cond", 1))
        tm, sc = sharded_search(mesh, tree, conds, operands, cols, n_spans,
                                nt=NT)
        for b in range(B):
            valid = np.arange(S_rows) < n_spans[b]
            lhs = (cols["span.dur_us"][b] < 80) & valid
            rhs = (cols["span.dur_us"][b] >= 20) & valid
            pidx = cols["span.parent_idx"][b]
            has_p = (pidx >= 0) & valid
            safe = np.clip(pidx, 0, S_rows - 1)
            if op == ">":
                rel = has_p & lhs[safe]
            elif op == ">>":
                rel = np.zeros(S_rows, bool)
                for i in range(S_rows):
                    p = pidx[i] if valid[i] else -1
                    while p >= 0:
                        if lhs[p]:
                            rel[i] = True
                            break
                        p = pidx[p]
            else:  # '~'
                cnt = np.zeros(S_rows, np.int32)
                np.add.at(cnt, safe, (lhs & has_p).astype(np.int32))
                sibs = cnt[safe] - (lhs & has_p).astype(np.int32)
                orphan = (pidx == -2) & valid
                rel = (has_p & (sibs > 0)) | (orphan & np.any(lhs & orphan))
            sm = rhs & rel & valid
            counts = np.bincount(cols["span.trace_sid"][b][sm],
                                 minlength=NT)[:NT]
            np.testing.assert_array_equal(sc[b], counts,
                                          err_msg=f"{op} block {b}")
            np.testing.assert_array_equal(tm[b], counts > 0,
                                          err_msg=f"{op} block {b}")


def test_sharded_bloom_union(mesh):
    blooms = []
    all_ids = []
    for k in range(5):
        bl = ShardedBloom(4)
        ids = [bytes([k, i]) + b"\x00" * 14 for i in range(20)]
        bl.add_many(ids)
        all_ids.extend(ids)
        blooms.append(bl)
    u = sharded_bloom_union(mesh, blooms)
    for tid in all_ids:
        assert u.test(tid)
    # oracle: numpy OR
    expected = np.zeros_like(blooms[0].words)
    for b in blooms:
        expected |= b.words
    np.testing.assert_array_equal(u.words, expected)


def test_distributed_query_step_one_jit(mesh):
    """The composed step compiles and runs as a single jitted program."""
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    B, T, Q, S_rows, NT, R = 8, 32, 8, 32, 8, 4
    K, NS, W = 8, 2, 16

    ids = np.sort(rng.integers(0, 100, size=(B, T, 4)).astype(np.int32), axis=1)
    for b in range(B):
        ids[b] = ids[b][np.lexsort(ids[b].T[::-1])]
    n_valid = np.full((B,), T, dtype=np.int32)
    queries = ids[:, 0, :][:Q].copy()

    cols = {
        "span.trace_sid": rng.integers(0, NT, size=(B, S_rows)).astype(np.int32),
        "span.dur_us": rng.integers(0, 100, size=(B, S_rows)).astype(np.int32),
    }
    n_spans = np.full((B,), S_rows, dtype=np.int32)
    conds = (Cond(target=T_SPAN, col="span.dur_us", op="ge"),)
    tree = ("cond", 0)
    operands = Operands.build([(0, 50, 0, 0.0, 0.0)])
    blooms = rng.integers(0, 2**32, size=(K, NS, W), dtype=np.uint32)

    names = tuple(sorted(cols))
    step = distributed_query_step(mesh, tree, conds, names, B, T, Q, S_rows, R, NT, K, NS, W)
    hits, tm, sc, bu = step(
        jnp.asarray(ids), jnp.asarray(n_valid), jnp.asarray(queries),
        jnp.asarray(operands.ints), jnp.asarray(operands.floats),
        jnp.asarray(n_spans),
        tuple(jnp.asarray(cols[n]) for n in names),
        jnp.asarray(blooms),
    )
    assert hits.shape == (Q, 2)
    assert np.asarray(tm).shape == (B, NT)
    expected_union = np.zeros((NS, W), dtype=np.uint32)
    for k in range(K):
        expected_union |= blooms[k]
    np.testing.assert_array_equal(np.asarray(bu), expected_union)


def test_graft_dryrun_multichip_entry():
    """Run the toy correctness leg the driver invokes first
    (__graft_entry__.dryrun_multichip's fast-failure shape) on the
    virtual 8-device CPU mesh, so a driver-side failure reproduces
    here. The default toy-then-scale run is covered (once) by
    test_graft_dryrun_scale_shape."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    try:
        import __graft_entry__ as graft

        graft.dryrun_multichip(8, scale=False)
    finally:
        sys.path.pop(0)


def test_graft_dryrun_scale_shape(capsys):
    """The default (toy-then-scale) dryrun: >= 1M padded span rows per
    chip, ragged per-block sizes, generic-attr conds, a struct-op node,
    the batched (Q>1) multi-query mesh window, the per-chip memory
    budget INCLUDING the batched program's padded Q-axis, and the host
    oracle -- the dryrun stand-in for the 100M-span sharded Find/search
    baseline config. The MULTICHIP artifact tail (scale shape + comm
    walker volume) must be printed and well-formed."""
    import json
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    try:
        import __graft_entry__ as graft

        graft.dryrun_multichip(8, scale=True)
    finally:
        sys.path.pop(0)
    tail_lines = [ln for ln in capsys.readouterr().out.splitlines()
                  if ln.startswith("MULTICHIP_SCALE ")]
    assert tail_lines, "scale dryrun printed no artifact tail"
    tail = json.loads(tail_lines[-1].split(" ", 1)[1])
    assert tail["padded_rows_per_chip"] >= 1_000_000
    assert tail["mq_window_q"] > 1 and tail["struct_op"]
    assert tail["per_chip_bytes"] <= tail["budget_bytes"]
    assert "mesh_step" in tail["comm_bytes_per_launch"]
    assert "mesh_multiquery" in tail["comm_bytes_per_launch"]


def test_graft_dryrun_subprocess_fallback(monkeypatch):
    """When the in-process virtual-device switch is impossible (private
    jax API moved), the dryrun still runs via a fresh subprocess
    configured purely through public env vars."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    try:
        import __graft_entry__ as graft

        monkeypatch.setattr(graft, "_force_virtual_devices", lambda n: False)
        graft.dryrun_multichip(8, scale=False)  # --no-scale flag plumbing
    finally:
        sys.path.pop(0)


def test_graft_entry_compiles():
    import sys
    from pathlib import Path

    import jax

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    try:
        import __graft_entry__ as graft

        fn, args = graft.entry()
        sids, mask, counts = jax.jit(fn)(*args)
        assert sids.shape[0] == args[1].shape[0]
    finally:
        sys.path.pop(0)
