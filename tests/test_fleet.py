"""Fleet-scale serving: RF>=2 replicated writes, quorum/merged reads,
heartbeat prune, the sharded blocklist poller and the /status/fleet
observability surface -- the fast in-process half of the fleet story
(tests/test_fleet_e2e.py drives the same seams as real processes)."""

import time

import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.db.blocklist import Poller
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.db.wal import WAL
from tempo_tpu.fleet.poller_shard import PollerShard
from tempo_tpu.fleet.quorum import (ReadQuorumError, merge_snapshots,
                                    read_quorum_need, segment_digest)
from tempo_tpu.fleet.replication import (REPLICATION_WRITES,
                                         record_write_outcomes,
                                         replication_snapshot)
from tempo_tpu.ring.ring import InMemoryKV, Lifecycler, Ring
from tempo_tpu.services.distributor import Distributor, PushError
from tempo_tpu.services.ingester import Ingester
from tempo_tpu.services.overrides import Overrides
from tempo_tpu.services.querier import Querier
from tempo_tpu.util.testdata import make_traces

TENANT = "t1"


def _counter_delta(before: dict, after: dict) -> dict:
    return {k: after.get(k, 0) - before.get(k, 0)
            for k in set(before) | set(after)
            if after.get(k, 0) != before.get(k, 0)}


def _mk_ingester(tmp_path, name: str, overrides: Overrides):
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / f"{name}-dbw")),
                 backend=MemBackend())
    return db, Ingester(WAL(str(tmp_path / f"{name}-wal")), db, overrides)


def _rf2_cluster(tmp_path, n: int = 2):
    """n in-process ingesters joined to one RF=2 ring."""
    overrides = Overrides()
    kv = InMemoryKV()
    dbs, clients = [], {}
    for i in range(n):
        lc = Lifecycler(kv, "ing", f"ing-{i}")
        lc.join()
        db, ing = _mk_ingester(tmp_path, f"ing-{i}", overrides)
        dbs.append(db)
        clients[lc.desc.addr] = ing
    ring = Ring(kv, "ing", replication_factor=2)
    dist = Distributor(ring, clients.__getitem__, overrides)
    return kv, ring, dist, clients, dbs


# --------------------------------------------------------- write outcomes


def test_record_write_outcomes_classification():
    before = REPLICATION_WRITES.snapshot()
    tally = record_write_outcomes(
        quorum_need={"a": 1, "b": 1, "c": 1},
        ok_count={"a": 2, "b": 1, "c": 0},
        desired=2,
    )
    assert tally == {"quorum": 1, "partial": 1, "failed": 1}
    delta = _counter_delta(before, REPLICATION_WRITES.snapshot())
    assert delta == {'outcome="quorum"': 1, 'outcome="partial"': 1,
                     'outcome="failed"': 1}
    snap = replication_snapshot()
    assert set(snap) <= {"quorum", "partial", "failed"}


def test_rf2_write_lands_on_both_replicas(tmp_path):
    _kv, _ring, dist, clients, dbs = _rf2_cluster(tmp_path)
    before = REPLICATION_WRITES.snapshot()
    traces = make_traces(8, seed=2, n_spans=4)
    for _tid, tr in traces:
        dist.push(TENANT, tr.resource_spans)
    # RF=2 with 2 healthy: every trace is on BOTH ingesters
    for ing in clients.values():
        for tid, _tr in traces:
            assert ing.trace_snapshot(TENANT, tid), (
                f"trace {tid.hex()} missing from a replica")
    delta = _counter_delta(before, REPLICATION_WRITES.snapshot())
    assert delta.get('outcome="quorum"', 0) >= len(traces)
    assert 'outcome="failed"' not in delta
    for db in dbs:
        db.close()


def test_rf2_fast_path_gated_one_replica_down(tmp_path):
    """PR 16's single-healthy-ingester fast path must stay OFF at RF>1:
    with one replica dead the push still succeeds (eventually-consistent
    W=1 at RF=2) and the under-replication is RECORDED as a partial
    outcome -- the fast path would have skipped the bookkeeping."""
    kv, _ring, dist, clients, dbs = _rf2_cluster(tmp_path)
    kv.get_all("ing")["ing-1"].heartbeat_ts = time.time() - 9999
    before = REPLICATION_WRITES.snapshot()
    traces = make_traces(5, seed=3, n_spans=4)
    for _tid, tr in traces:
        dist.push(TENANT, tr.resource_spans)  # quorum met: no PushError
    delta = _counter_delta(before, REPLICATION_WRITES.snapshot())
    assert delta.get('outcome="partial"', 0) >= len(traces)
    assert 'outcome="failed"' not in delta
    # and the survivor really has the data
    live = [ing for addr, ing in clients.items()
            if any(ing.trace_snapshot(TENANT, tid) for tid, _ in traces)]
    assert live
    for db in dbs:
        db.close()


def test_rf2_push_fails_below_write_quorum(tmp_path):
    """Both replicas down-or-failing -> the push must NOT be acked."""
    overrides = Overrides()
    kv = InMemoryKV()
    for i in range(2):
        Lifecycler(kv, "ing", f"ing-{i}").join()
    ring = Ring(kv, "ing", replication_factor=2)

    class Down:
        def push_segments(self, tenant, batch):
            raise OSError("replica down")

    dist = Distributor(ring, (lambda addr: Down()), overrides)
    before = REPLICATION_WRITES.snapshot()
    tid, tr = make_traces(1, seed=4)[0]
    with pytest.raises(PushError):
        dist.push(TENANT, tr.resource_spans)
    delta = _counter_delta(before, REPLICATION_WRITES.snapshot())
    assert delta.get('outcome="failed"', 0) >= 1


# ----------------------------------------------------------- quorum reads


def test_segment_digest_and_merge_snapshots():
    a, b = b"seg-a" * 10, b"seg-b" * 10
    assert segment_digest(a) == segment_digest(a) != segment_digest(b)
    merged = merge_snapshots([
        [(segment_digest(a), a), (segment_digest(b), b)],
        [(segment_digest(a), a)],  # replica copy: same digest, deduped
        [],
    ])
    assert sorted(merged) == sorted([a, b])
    assert merge_snapshots([]) == []


def test_read_quorum_need():
    assert read_quorum_need(2, 1) == 1  # RF=2: one dead replica invisible
    assert read_quorum_need(3, 1) == 2  # RF=3: majority
    assert read_quorum_need(1, 0) == 1
    assert read_quorum_need(0, 0) == 1  # floor


def test_quorum_read_dedupes_replica_copies(tmp_path):
    """RF=2 read fans to both replicas; identical segments must merge to
    ONE copy of each span, not two."""
    _kv, ring, dist, clients, dbs = _rf2_cluster(tmp_path)
    traces = make_traces(6, seed=5, n_spans=5)
    for _tid, tr in traces:
        dist.push(TENANT, tr.resource_spans)
    q = Querier(dbs[0], ring, clients.__getitem__)
    for tid, tr in traces:
        got = q.find_trace_by_id(TENANT, tid)
        assert got is not None
        assert got.span_count() == tr.span_count()  # deduped, not doubled
    for db in dbs:
        db.close()


def test_quorum_read_survives_one_dead_replica(tmp_path):
    _kv, ring, dist, clients, dbs = _rf2_cluster(tmp_path)
    traces = make_traces(4, seed=6, n_spans=4)
    for _tid, tr in traces:
        dist.push(TENANT, tr.resource_spans)

    dead_addr = next(iter(clients))

    class DeadThenLive:
        def __init__(self, addr):
            self.addr = addr

        def __getattr__(self, name):
            inner = clients[self.addr]
            if self.addr == dead_addr:
                def boom(*a, **k):
                    raise OSError("replica SIGKILLed")
                return boom
            return getattr(inner, name)

    q = Querier(dbs[1], ring, lambda addr: DeadThenLive(addr))
    for tid, tr in traces:
        got = q.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == tr.span_count()
    for db in dbs:
        db.close()


def test_quorum_read_raises_below_r(tmp_path):
    """No replica answers -> ReadQuorumError (an OSError: the frontend
    retries the job instead of caching a false 'not found')."""
    overrides = Overrides()
    kv = InMemoryKV()
    for i in range(2):
        Lifecycler(kv, "ing", f"ing-{i}").join()
    ring = Ring(kv, "ing", replication_factor=2)

    class Dead:
        def __getattr__(self, name):
            def boom(*a, **k):
                raise OSError("down")
            return boom

    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dbw")),
                 backend=MemBackend())
    q = Querier(db, ring, lambda addr: Dead())
    tid = make_traces(1, seed=7)[0][0]
    with pytest.raises(ReadQuorumError) as ei:
        q.find_trace_by_id(TENANT, tid)
    assert isinstance(ei.value, OSError)
    db.close()


# ------------------------------------------------------- lifecycler prune


def test_lifecycler_prunes_stale_peer():
    kv = InMemoryKV()
    lc = Lifecycler(kv, "ing", "alive", prune_timeout=1.0)
    lc.join()
    dead = Lifecycler(kv, "ing", "dead")
    dead.join()  # then SIGKILL: no LEAVE record, heartbeat goes stale
    kv.get_all("ing")["dead"].heartbeat_ts = time.time() - 5.0
    assert lc.prune() == ["dead"]
    assert "dead" not in kv.get_all("ing")
    assert "alive" in kv.get_all("ing")  # never prunes itself
    assert lc.prune() == []  # idempotent


def test_lifecycler_prune_disabled_by_default():
    kv = InMemoryKV()
    lc = Lifecycler(kv, "ing", "alive")
    lc.join()
    stale = Lifecycler(kv, "ing", "stale")
    stale.join()
    kv.get_all("ing")["stale"].heartbeat_ts = time.time() - 99999
    assert lc.prune() == []  # prune_timeout=None: opt-in only
    assert "stale" in kv.get_all("ing")


# ------------------------------------------------------ sharded poller


def test_poller_shard_partitions_tenants():
    kv = InMemoryKV()
    for i in range(3):
        Lifecycler(kv, "querier-ring", f"q-{i}").join()
    shards = [PollerShard(Ring(kv, "querier-ring"), f"q-{i}")
              for i in range(3)]
    tenants = [f"tenant-{i}" for i in range(12)]
    for t in tenants:
        owners = [s for s in shards if s.owns(t)]
        assert len(owners) == 1, f"{t} owned by {len(owners)} shards"
    # every member computes the same shard map
    maps = [s.shard_map(tenants) for s in shards]
    assert maps[0] == maps[1] == maps[2]
    st = shards[0].status(tenants)
    assert st["members"] == ["q-0", "q-1", "q-2"]
    assert sorted(st["owned"]) == sorted(
        t for t, o in maps[0].items() if o == "q-0")


def test_poller_shard_empty_ring_owns_everything():
    kv = InMemoryKV()
    shard = PollerShard(Ring(kv, "querier-ring"), "q-solo")
    assert shard.owns("any-tenant")
    assert shard.status(["a", "b"])["members"] == []


def test_poller_non_owner_reads_owner_index(tmp_path):
    backend = MemBackend()
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / "dbw")),
                 backend=backend)
    overrides = Overrides()
    ing = Ingester(WAL(str(tmp_path / "wal")), db, overrides)
    kv = InMemoryKV()
    Lifecycler(kv, "ing", "i0").join()
    dist = Distributor(Ring(kv, "ing"),
                       (lambda addr: ing), overrides)
    for _tid, tr in make_traces(5, seed=8, n_spans=4):
        dist.push(TENANT, tr.resource_spans)
    ing.sweep_all(force=True)  # cut + flush -> backend blocks

    owner = Poller(backend, build_index=True)
    metas, _ = owner.poll()
    assert len(metas[TENANT]) >= 1

    # the non-owner lists NOTHING: it reads the owner's index object
    class NoListBackend:
        def __getattr__(self, name):
            if name == "blocks":
                raise AssertionError("non-owner must not list the backend")
            return getattr(backend, name)

    non_owner = Poller(NoListBackend(), build_index=True)
    non_owner.owns_tenant = lambda tenant: False
    nmetas, _ = non_owner.poll()
    assert ([m.block_id for m in nmetas[TENANT]]
            == [m.block_id for m in metas[TENANT]])
    assert non_owner.last_shard["deferred"] == [TENANT]
    assert owner.last_shard["owned"] == [TENANT]
    db.close()


# -------------------------------------------------- /status/fleet surface


def test_status_fleet_and_queue_depth_metrics(tmp_path):
    from tempo_tpu.services.app import (App, AppConfig, _fleet_status,
                                        _metrics_text)

    app = App(AppConfig(target="all", storage_path=str(tmp_path / "s"),
                        replication_factor=1))
    try:
        app.lifecycler.join()  # register without starting the loops
        for _tid, tr in make_traces(3, seed=9, n_spans=4):
            app.distributor.push(TENANT, tr.resource_spans)
        st = _fleet_status(app)
        assert st["ring"]["replication_factor"] == 1
        assert st["ring"]["write_quorum"] == 1
        assert st["ring"]["healthy"] == 1
        assert st["ring"]["members"][0]["healthy"] is True
        assert "writes" in st["replication"]
        assert st["poller_shard"]["solo"] is True
        assert isinstance(st.get("queue_depths", {}), dict)
        text = _metrics_text(app)
        assert "tempo_query_queue_depth" in text
        assert "tempo_replication_writes_total" in text
    finally:
        app.stop()


def test_fleet_status_quorum_arithmetic():
    from tempo_tpu.services.app import _fleet_status  # noqa: F401

    # the surface mirrors ring.ReplicationSet: RF=2 is the eventually-
    # consistent W=1 special case, RF>=3 is majority
    for rf, want in ((1, 1), (2, 1), (3, 2), (5, 3)):
        assert (1 if rf <= 2 else rf - (rf - 1) // 2) == want
