"""Device-native ingest subsystem (PR-16): columnar WAL v2 codec
roundtrip, crash/corruption replay, legacy-w1 migration, the
randomized push/cut/flush differential proving the columnar path
flushes bit-identical blocks, feature-checkpointed no-decode replay,
and the device block-cut kernels' host-twin parity."""

import os
import random

import numpy as np
import pytest

from tempo_tpu.backend import MemBackend
from tempo_tpu.backend.local import LocalBackend
from tempo_tpu.chaos import plane
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.db.wal import WAL, WAL2Block, WALBlock
from tempo_tpu.ingest import columnar as columnar_mod
from tempo_tpu.ingest.columnar import ColumnarIngest, LiveDict, compute_features
from tempo_tpu.services.ingester import Ingester, IngesterConfig
from tempo_tpu.services.overrides import Overrides
from tempo_tpu.util.kerneltel import TEL
from tempo_tpu.util.testdata import make_traces
from tempo_tpu.wire import segment

TENANT = "t-ingest"


def _seg_batch(traces, start_s=1, end_s=2):
    return [(tid, start_s, end_s, segment.segment_for_write(t, start_s, end_s))
            for tid, t in traces]


def _mk_ing(tmp_path, name, wal_version=None, store=None):
    db = TempoDB(TempoDBConfig(wal_path=str(tmp_path / f"dbwal-{name}")),
                 backend=LocalBackend(str(store)) if store else MemBackend())
    cfg = IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0)
    if wal_version is not None:
        cfg.wal_version = wal_version
    return db, Ingester(WAL(str(tmp_path / f"wal-{name}")), db, Overrides(), cfg)


# --------------------------------------------------------------- codec


def test_wal2_roundtrip_windows_and_features(tmp_path):
    traces = make_traces(6, seed=21, n_spans=4)
    batch = _seg_batch(traces)
    col = ColumnarIngest()
    blk = WAL2Block(str(tmp_path), TENANT)
    blk.append_window(batch[:4])
    blk.append(*batch[4])  # single-entry window via the v1-shaped API
    blk.append_window(batch[5:])
    for *_, seg in batch:
        col.features_for(seg)
    n = blk.flush_features(col.cached, col.dict)
    assert n == len(batch)
    blk.flush(sync=True)
    blk.close()

    records, clean, features, delta = WAL2Block.read_records(blk.path)
    assert clean
    assert [(r.trace_id, r.start_s, r.end_s, r.segment) for r in records] == [
        (tid.rjust(16, b"\x00"), s, e, seg) for tid, s, e, seg in batch]
    assert set(features) == set(range(len(batch)))
    # replayed strings reproduce the features computed at write time
    fresh = LiveDict()
    for i, (_, _, _, seg) in enumerate(batch):
        want = compute_features(seg, fresh)
        kv, names, lo, hi = features[i]
        assert tuple(fresh.string(c) for c in want.kv_codes) == kv
        assert tuple(fresh.string(c) for c in want.name_codes) == names
        assert (lo, hi) == (want.lo_ns, want.hi_ns)
    # the dict delta covers every referenced string, in file-code order
    assert len(delta) == len(set(delta))
    for kv, names, *_ in features.values():
        assert set(kv) <= set(delta) and set(names) <= set(delta)


def test_wal2_torn_tail_truncates_and_reappends(tmp_path):
    traces = make_traces(5, seed=22, n_spans=3)
    batch = _seg_batch(traces)
    blk = WAL2Block(str(tmp_path), TENANT)
    blk.append_window(batch[:3])
    blk.append_window(batch[3:])
    blk.flush(sync=True)
    blk.close()
    # crash mid-append: the second window's frame loses its tail
    with open(blk.path, "r+b") as f:
        f.truncate(os.path.getsize(blk.path) - 7)
    records, clean, features, _ = WAL2Block.read_records(blk.path)
    assert not clean and len(records) == 3 and not features
    # the torn bytes are gone from disk; appends resume cleanly
    blk2 = WAL2Block(str(tmp_path), TENANT,
                     os.path.basename(blk.path).split("+")[0])
    blk2.append_window(batch[3:])
    blk2.flush(sync=True)
    blk2.close()
    records, clean, _, _ = WAL2Block.read_records(blk.path)
    assert clean and len(records) == 5


def test_wal2_crc_corruption_rejects_suffix(tmp_path):
    """A flipped byte anywhere in a record invalidates it AND the
    stream after it (chaos wal.append corrupt seam)."""
    traces = make_traces(6, seed=23, n_spans=3)
    batch = _seg_batch(traces)
    plane.configure([{"site": "wal.append", "action": "corrupt", "nth": 2}])
    try:
        blk = WAL2Block(str(tmp_path), TENANT)
        blk.append_window(batch[:2])
        blk.append_window(batch[2:4])  # corrupted in flight
        blk.append_window(batch[4:])
        blk.flush(sync=True)
        blk.close()
    finally:
        plane.clear()
    records, clean, _, _ = WAL2Block.read_records(blk.path)
    assert not clean
    assert [r.segment for r in records] == [seg for *_, seg in batch[:2]]
    # the truncate-on-read made the prefix durable: a second scan is clean
    records2, clean2, _, _ = WAL2Block.read_records(blk.path)
    assert clean2 and len(records2) == 2


# ----------------------------------------------------------- migration


def test_legacy_w1_wal_migrates_through_replay(tmp_path):
    """An ingester that crashed on a v1 proto WAL replays into a v2
    process: records recover, blocks flush, and the new heads are w2."""
    traces = make_traces(8, seed=24, n_spans=4)
    db1, ing1 = _mk_ing(tmp_path, "old", wal_version="w1")
    ing1.push_segments(TENANT, _seg_batch(traces))
    inst = ing1.instance(TENANT)
    assert isinstance(inst.head, WALBlock) and not isinstance(inst.head, WAL2Block)
    wal_dir = ing1.wal.dir
    assert any(n.endswith("+w1") for n in os.listdir(wal_dir))
    db1.close()  # crash: no cut, no flush

    db2, ing2 = _mk_ing(tmp_path, "new")
    ing2.wal = WAL(wal_dir)
    n = ing2.replay_wal()
    assert n == len(traces)
    for tid, t in traces:
        got = db2.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == t.span_count()
    # the legacy file is consumed; any surviving head is columnar
    assert not any(n_.endswith("+w1") for n_ in os.listdir(wal_dir))
    assert isinstance(ing2.instance(TENANT).head, WAL2Block)
    db2.close()


# -------------------------------------------------------- differential


def _block_objects(store) -> dict[str, bytes]:
    """name -> bytes for the single flushed block under `store`,
    keyed independently of the (random) block id."""
    out = {}
    tenant_dir = os.path.join(str(store), TENANT)
    blocks = os.listdir(tenant_dir)
    assert len(blocks) == 1, blocks
    bdir = os.path.join(tenant_dir, blocks[0])
    for name in os.listdir(bdir):
        with open(os.path.join(bdir, name), "rb") as f:
            out[name] = f.read()
    return out


def test_randomized_replay_differential_bit_identical(tmp_path):
    """The acceptance differential: the same randomized push sequence
    through the legacy proto WAL and the columnar WAL, a crash, then
    replay -- both paths must flush bit-identical block objects. The
    w2 leg checkpoints features before the crash so replay exercises
    the no-proto-decode path too."""
    rng = random.Random(1009)
    traces = make_traces(30, seed=25, n_spans=4)
    entries = _seg_batch(traces)
    # randomized windows with duplicate appends sprinkled in
    pushes = []
    i = 0
    while i < len(entries):
        k = rng.randint(1, 6)
        win = entries[i:i + k]
        if rng.random() < 0.3:
            win = win + [rng.choice(entries[: i + k])]
        pushes.append(win)
        i += k

    stores = {}
    for name, ver in (("w1", "w1"), ("w2", "w2")):
        store = tmp_path / f"store-{name}"
        db, ing = _mk_ing(tmp_path, name, wal_version=ver, store=store)
        for win in pushes:
            ing.push_segments(TENANT, win)
        if ver == "w2":
            # decode features (the live staging refresh normally does
            # this) so the checkpoint has something to write
            inst = ing.instance(TENANT)
            if inst.live_engine is not None:
                inst.live_engine.maybe_refresh()
            else:
                for lt in inst.live.values():
                    for seg in lt.segments:
                        inst.columnar.features_for(seg)
            assert inst.flush_wal_features() > 0
        db.close()  # crash before any cut

        db2, ing2 = _mk_ing(tmp_path, name + "-replay", store=store)
        ing2.wal = WAL(str(tmp_path / f"wal-{name}"))
        ing2.cfg.wal_version = ver  # replay under the same head format
        assert ing2.replay_wal() == sum(len(w) for w in pushes)
        assert ing2.instance(TENANT).blocks_flushed == 1
        stores[name] = _block_objects(store)
        db2.close()

    a, b = stores["w1"], stores["w2"]
    assert set(a) == set(b)
    for name in sorted(a):
        if name == "meta.json":
            continue  # carries the random block id
        assert a[name] == b[name], f"object {name} differs between WAL paths"


def test_feature_checkpoint_replay_skips_proto_decode(tmp_path, monkeypatch):
    """Replay of a feature-checkpointed w2 WAL seeds the columnar cache
    without EVER re-running the feature decode."""
    traces = make_traces(10, seed=26, n_spans=3)
    db1, ing1 = _mk_ing(tmp_path, "seed")
    ing1.push_segments(TENANT, _seg_batch(traces))
    inst1 = ing1.instance(TENANT)
    if inst1.live_engine is not None:
        inst1.live_engine.maybe_refresh()  # decode features once, live
    else:  # staging engine unavailable: decode through the cache directly
        for lt in inst1.live.values():
            for seg in lt.segments:
                inst1.columnar.features_for(seg)
    assert inst1.flush_wal_features() == len(traces)
    wal_dir = ing1.wal.dir
    db1.close()

    calls = {"n": 0}
    real = columnar_mod.compute_features

    def counting(seg, ldict):
        calls["n"] += 1
        return real(seg, ldict)

    monkeypatch.setattr(columnar_mod, "compute_features", counting)
    db2, ing2 = _mk_ing(tmp_path, "seed-replay")
    ing2.wal = WAL(wal_dir)
    assert ing2.replay_wal() == len(traces)
    inst2 = ing2.instance(TENANT)
    assert inst2.columnar.seeded == len(traces)
    assert inst2.columnar.decodes == 0 and calls["n"] == 0
    for tid, t in traces:
        got = db2.find_trace_by_id(TENANT, tid)
        assert got is not None and got.span_count() == t.span_count()
    db2.close()


# ------------------------------------------------------- cut kernels


def test_blockcut_twin_parity():
    from tempo_tpu.block.bloom import ShardedBloom
    from tempo_tpu.ops import blockcut

    rng = np.random.default_rng(31)
    # dictionary remap, -1 padding preserved
    for n in (1, 7, 300):
        remap = rng.permutation(50).astype(np.int32)
        col = rng.integers(-1, 50, size=n).astype(np.int32)
        dev = blockcut.remap_codes_device(col, remap)
        host = blockcut.remap_codes_host(col, remap)
        np.testing.assert_array_equal(np.asarray(dev), host)

    # bloom bit-setting == the host ShardedBloom fold
    tids = [rng.integers(0, 256, size=16, dtype=np.uint8).tobytes()
            for _ in range(64)]
    ref = ShardedBloom.for_estimated_items(len(tids))
    ref.add_many(tids)
    dev = ShardedBloom.for_estimated_items(len(tids))
    dev.words = blockcut.bloom_bits_device(dev.words, tids, dev.shard_bits)
    host = ShardedBloom.for_estimated_items(len(tids))
    host.words = blockcut.bloom_bits_host(host.words, tids, host.shard_bits)
    np.testing.assert_array_equal(np.asarray(dev.words), ref.words)
    np.testing.assert_array_equal(host.words, ref.words)

    # per-row-group min/max/max (block columns are base-relative int32
    # ms / clipped int32 us -- block/builder.py finalize)
    for spans, group in ((1, 1), (9, 4), (257, 64)):
        start_ms = rng.integers(0, 2**31 - 1, size=spans).astype(np.int32)
        dur_us = rng.integers(0, 2**31 - 1, size=spans).astype(np.int32)
        bounds = list(range(0, spans, group)) + [spans]
        dev = blockcut.rowgroup_minmax_device(start_ms, dur_us, bounds)
        host = blockcut.rowgroup_minmax_host(start_ms, dur_us, bounds)
        for d, h in zip(dev, host):
            np.testing.assert_array_equal(np.asarray(d), h)


def test_finalize_engine_differential(tmp_path, monkeypatch):
    """The whole block-finalize path on the device kernels vs the host
    twins: bit-identical objects."""
    from tempo_tpu.block.builder import build_block_from_traces

    traces = make_traces(20, seed=27, n_spans=5)
    objs = {}
    for eng in ("host", "device"):
        monkeypatch.setenv("TEMPO_CUT_ENGINE", eng)
        store = tmp_path / f"fin-{eng}"
        build_block_from_traces(LocalBackend(str(store)), TENANT, traces,
                                block_id="b-fixed")
        objs[eng] = _block_objects(store)
    monkeypatch.delenv("TEMPO_CUT_ENGINE")
    assert set(objs["host"]) == set(objs["device"])
    for name in objs["host"]:
        if name != "meta.json":  # meta carries wall-clock timestamps
            assert objs["host"][name] == objs["device"][name], name


# -------------------------------------------------------- telemetry


def test_ingest_stage_telemetry_and_snapshot(tmp_path):
    base = TEL.ingest_stats()
    traces = make_traces(6, seed=28, n_spans=3)
    db, ing = _mk_ing(tmp_path, "tel")
    ing.push_segments(TENANT, _seg_batch(traces))
    ing.sweep_all(force=True)
    db.close()
    snap = TEL.snapshot()
    assert "ingest" in snap
    stats = snap["ingest"]
    assert stats["windows"] > base["windows"]
    assert stats["window_traces"] >= base["window_traces"] + len(traces)
    for stage in ("wal_append", "cut", "flush"):
        assert stats["stages"][stage]["count"] > \
            base["stages"].get(stage, {}).get("count", 0), stage
        assert stats["stages"][stage]["seconds"] >= 0.0
    # the prometheus leg: per-stage labeled histogram series exist
    lines = "\n".join(TEL.ingest_stage_time.text())
    assert "tempo_ingest_stage_seconds" in lines
    assert 'stage="wal_append"' in lines and 'stage="cut"' in lines
