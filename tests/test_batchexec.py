"""Cross-query batching executor: differential equivalence, launch
accounting, fairness, queue pruning, staged-LRU accounting."""

from __future__ import annotations

import gc
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.db.search import SearchRequest, search_block
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.util.kerneltel import TEL
from tempo_tpu.util.testdata import make_traces

TENANT = "batch-t"


def _mkdb(**over) -> TempoDB:
    cfg = TempoDBConfig(
        wal_path=tempfile.mkdtemp(prefix="tempo-batch-wal"),
        batch_window_ms=over.pop("batch_window_ms", 200.0),
        batch_max=over.pop("batch_max", 16),
        device_promote_touches=over.pop("device_promote_touches", 1),
        **over,
    )
    return TempoDB(cfg, backend=MemBackend())


def _dicts(resp):
    return [{**t.to_dict(), "matchedSpans": t.matched_spans} for t in resp.traces]


# ---------------------------------------------------------- lowering


def test_lower_plan_eligibility():
    from tempo_tpu.db.search import _plan_for_block
    from tempo_tpu.ops.multiquery import lower_plan

    db = _mkdb()
    m = db.write_block(TENANT, make_traces(30, seed=21, n_spans=6))
    blk = db.open_block(m)

    def lowered(q):
        p = _plan_for_block(blk, SearchRequest(query=q))
        return None if p.prune else lower_plan(p)

    # eligible: dedicated-column scalar compares, and/or combinations
    assert lowered('{ name = "db.query" }') is not None
    assert lowered('{ duration > 100ms }') is not None
    assert lowered('{ status = error && kind = server }') is not None
    assert lowered('{ name = "GET /" || duration < 1ms }') is not None
    assert lowered('{ span.http.status_code >= 500 }') is not None
    # span + res mix is eligible (res conds ride span@ materialization)
    assert lowered(
        '{ name = "db.query" && resource.service.name = "auth" }') is not None
    # ineligible: generic attr table, regex, structural relation
    assert lowered('{ span.component = "grpc" }') is None
    assert lowered('{ name =~ "GET.*" }') is None
    assert lowered('{ name = "GET /" } >> { name = "db.query" }') is None


# ------------------------------------------------- differential equivalence


# mix of batcher-eligible and fallback queries; every one must come out
# identical to the sequential single-query engine
_QUERIES = [
    '{ name = "db.query" }',
    '{ name != "render" }',
    '{ duration > 500ms }',
    '{ status = error }',
    '{ kind = server }',
    '{ span.http.method = "GET" && duration > 10ms }',
    '{ span.http.status_code >= 500 }',
    '{ name = "GET /api" || name = "cache.get" }',
    '{ name = "db.query" && resource.service.name = "db" }',
    '{ span.http.status_code = 200 && status != error }',
    # fallback paths (ineligible for the fused kernel)
    '{ span.component = "grpc" }',
    '{ name =~ "GET .*" }',
]


def test_differential_batched_vs_sequential():
    """N random TraceQL queries concurrently through the batcher vs
    sequentially through db/search.py: bit-identical result sets."""
    db = _mkdb()
    m = db.write_block(TENANT, make_traces(120, seed=7, n_spans=8))
    blk = db.open_block(m)
    # limit >= total traces: no truncation, so fallback engines with a
    # different (exact) candidate selection order converge too
    reqs = [SearchRequest(query=q, limit=200) for q in _QUERIES] * 2
    expected = [_dicts(search_block(blk, r)) for r in reqs]
    with ThreadPoolExecutor(len(reqs)) as ex:
        futs = [ex.submit(db.search_blocks, TENANT, [m], r) for r in reqs]
        got = [_dicts(f.result()) for f in futs]
    for q, e, g in zip([r.query for r in reqs], expected, got):
        assert e == g, f"batched != sequential for {q!r}"


def test_batched_launch_reduction_and_identity():
    """16 concurrent identical-shape queries against one staged block:
    >= 8x fewer device launches than the sequential device path, with
    bit-identical results (the ISSUE acceptance criterion)."""
    db = _mkdb()
    m = db.write_block(TENANT, make_traces(150, seed=9, n_spans=8))
    blk = db.open_block(m)
    req = SearchRequest(query='{ name != "zzz" && duration > 1ms }', limit=10)

    from tempo_tpu.db.batchexec import batched_search_block_many

    # warm: stages the block + compiles both fused and sequential programs
    warm = batched_search_block_many(db.batchers.search, [(blk, req, None)],
                                     promote_touches=1)
    assert warm[0] is not None
    seq_ref = search_block(blk, req, mode="device")
    assert _dicts(warm[0]) == _dicts(seq_ref)

    l0 = TEL.launch_count()
    outs = batched_search_block_many(
        db.batchers.search, [(blk, req, None)] * 16, promote_touches=1)
    batched_launches = TEL.launch_count() - l0
    assert all(o is not None for o in outs)
    for o in outs:
        assert _dicts(o) == _dicts(seq_ref)

    l1 = TEL.launch_count()
    for _ in range(16):
        search_block(blk, req, mode="device")
    seq_launches = TEL.launch_count() - l1
    assert batched_launches > 0
    assert seq_launches >= 8 * batched_launches, (
        f"batched={batched_launches} sequential={seq_launches}")

    # the same 16 queries from real concurrent threads also coalesce
    stats0 = TEL.batch_stats().get("search", {})
    with ThreadPoolExecutor(16) as ex:
        futs = [ex.submit(db.search_blocks, TENANT, [m], req)
                for _ in range(16)]
        for f in futs:
            assert _dicts(f.result()) == _dicts(seq_ref)
    stats1 = TEL.batch_stats()["search"]
    assert stats1["max_occupancy"] >= 2  # threads actually shared launches
    assert stats1["queries"] > stats0.get("queries", 0)


def test_find_batched_equivalence():
    """Concurrent trace-by-ID lookups coalesce through the find batcher
    and return the same traces as the sequential path."""
    traces = make_traces(60, seed=11, n_spans=5)
    db = _mkdb()
    m = db.write_block(TENANT, traces)
    ids = [tid for tid, _ in traces[:10]]
    seq = [db._device_find(db.find_candidates(TENANT, i), i) for i in ids]
    with ThreadPoolExecutor(10) as ex:
        futs = [ex.submit(db.find_trace_by_id, TENANT, i) for i in ids]
        got = [f.result() for f in futs]
    for i, (s, g) in enumerate(zip(seq, got)):
        assert (g is not None) == bool(s)
        if s:
            from tempo_tpu.wire.combine import combine_traces
            from tempo_tpu.wire import otlp_json

            assert otlp_json.dumps(g) == otlp_json.dumps(combine_traces(s))
    assert TEL.batch_stats().get("find", {}).get("queries", 0) >= 10


def test_lone_query_skips_window():
    """A lone query on an idle executor must not pay the admission
    window (and can never be delayed past it)."""
    db = _mkdb(batch_window_ms=500.0)
    traces = make_traces(40, seed=13, n_spans=4)
    m = db.write_block(TENANT, traces)
    req = SearchRequest(query='{ name != "zzz" }', limit=5)
    db.search_blocks(TENANT, [m], req)  # warm: staging + compiles
    t0 = time.perf_counter()
    db.search_blocks(TENANT, [m], req)
    assert time.perf_counter() - t0 < 0.5  # ran without the 500 ms window
    # back-to-back sequential traffic (search and find) must not pay the
    # window either: only a concurrent submitter holds it open
    db.find_trace_by_id(TENANT, traces[0][0])  # warm find path
    t0 = time.perf_counter()
    for tid, _ in traces[1:5]:
        assert db.find_trace_by_id(TENANT, tid) is not None
    assert time.perf_counter() - t0 < 4 * 0.5  # 4 lookups, no 500 ms waits


# --------------------------------------------------------------- fairness


def test_tenant_fairness_under_flood():
    """Tenant B's job is dequeued fairly (and joins batches) while
    tenant A floods the queue; B is never starved past one rotation."""
    from tempo_tpu.services.frontend import RequestQueue, _Job

    q = RequestQueue()
    for i in range(50):
        q.enqueue("A", _Job(kind="search_blocks", payload={}, fn=None,
                            args=(), batch_key=("k", "A")))
    q.enqueue("B", _Job(kind="search_blocks", payload={}, fn=None,
                        args=(), batch_key=("k", "B")))
    seen_tenants = []
    for _ in range(2):
        tenant, job, extras = q.dequeue_batch(
            timeout=0.1, max_batch=8, key_fn=lambda j: j.batch_key)
        seen_tenants.append(tenant)
    assert "B" in seen_tenants  # one rotation at most, despite A's flood


def test_batch_executor_cross_tenant_group():
    """Items from different submitters under one key demux correctly,
    and per-item runner errors only fail their own submitter."""
    from tempo_tpu.db.batchexec import BatchExecutor

    def runner(key, items):
        return [ValueError("boom") if it == "bad" else f"ok:{it}"
                for it in items]

    ex = BatchExecutor("test", runner, window_s=0.05, max_batch=8)
    results = {}
    errs = {}

    def submit(item):
        try:
            results[item] = ex.submit("k", item)
        except ValueError as e:
            errs[item] = e

    ts = [threading.Thread(target=submit, args=(it,))
          for it in ("a", "bad", "c")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results == {"a": "ok:a", "c": "ok:c"}
    assert "bad" in errs


# ---------------------------------------------------------- queue pruning


def test_request_queue_prunes_drained_tenants():
    """Regression: tenants were appended to the rotation on first
    enqueue but never removed when their deque drained."""
    from tempo_tpu.services.frontend import RequestQueue, _Job

    q = RequestQueue()
    for i in range(100):
        tenant = f"churn-{i}"
        q.enqueue(tenant, _Job(kind="x", payload={}, fn=None, args=()))
        assert q.dequeue(timeout=0.1) is not None
    with q.lock:
        assert len(q.order) == 0
        assert len(q.queues) == 0
    # interleaved: live tenants stay, drained ones go
    q.enqueue("live", _Job(kind="x", payload={}, fn=None, args=()))
    q.enqueue("live", _Job(kind="x", payload={}, fn=None, args=()))
    q.enqueue("dead", _Job(kind="x", payload={}, fn=None, args=()))
    got = {q.dequeue(timeout=0.1)[0] for _ in range(2)}
    assert got == {"live", "dead"}
    with q.lock:
        assert list(q.order) == ["live"]
    assert q.dequeue(timeout=0.1)[0] == "live"
    with q.lock:
        assert len(q.order) == 0 and len(q.queues) == 0


# ------------------------------------------------------ staged-LRU sweep


def test_staged_lru_sweeps_dead_weakrefs():
    """An entry whose block weakref died must release its nbytes from
    the global staged-cache accounting on the next insert/evict."""
    from tempo_tpu.block.builder import build_block_from_traces
    from tempo_tpu.block.reader import BackendBlock
    from tempo_tpu.ops import stage
    from tempo_tpu.ops.filter import required_columns
    from tempo_tpu.ops.stage import stage_block

    backend = MemBackend()
    m1 = build_block_from_traces(backend, TENANT, make_traces(20, seed=31))
    m2 = build_block_from_traces(backend, TENANT, make_traces(20, seed=32))
    blk1 = BackendBlock(backend, m1)
    blk2 = BackendBlock(backend, m2)
    cols = ["span.name_id", "trace.span_off", "span.trace_sid"]
    stage_block(blk1, cols)
    key1 = id(blk1)
    with stage._lru_lock:
        assert any(k[0] == key1 for k in stage._lru)  # entry accounted
    del blk1
    gc.collect()
    with stage._lru_lock:  # dead weakref still resident until a sweep
        dead = [k for k, e in stage._lru.items() if e[0]() is None]
    assert dead  # blk1's entry died with its arrays
    # the next insert sweeps the dead entry: accounted bytes must equal
    # the sum of LIVE entries' nbytes exactly, with no dead keys left
    stage_block(blk2, cols)
    with stage._lru_lock:
        assert stage._lru_bytes == sum(
            e[1] for e in stage._lru.values() if e[0]() is not None)
        assert all(e[0]() is not None for e in stage._lru.values())
    del blk2
    gc.collect()


# --------------------------------------------------- frontend multi wire


def test_frontend_poll_merges_same_key_jobs():
    """poll_job hands a remote worker ONE `multi` wire job for same-key
    queued jobs; complete_job demuxes the result list."""
    from tempo_tpu.db.search import SearchResponse, response_to_dict
    from tempo_tpu.services.frontend import Frontend, _Job
    from tempo_tpu.services.querier import Querier

    db = _mkdb()
    m = db.write_block(TENANT, make_traces(10, seed=41, n_spans=3))
    querier = Querier(db, ring=None, client_for=lambda a: None)
    fe = Frontend(querier, n_workers=0)
    try:
        jobs = []
        for i in range(3):
            j = _Job(kind="search_blocks",
                     payload={"req": {"limit": 5}, "block_ids": [m.block_id]},
                     fn=None, args=(),
                     batch_key=("search_blocks", TENANT, (m.block_id,)))
            jobs.append(j)
            fe.queue.enqueue(TENANT, j)
        wire = fe.poll_job(wait_s=1.0)
        assert wire is not None and wire["kind"] == "multi"
        assert wire["payload"]["kind"] == "search_blocks"
        assert len(wire["payload"]["jobs"]) == 3
        resp = SearchResponse()
        fe.complete_job(wire["id"], ok=True, result={
            "results": [response_to_dict(resp)] * 3})
        for j in jobs:
            assert j.done.is_set()
            assert j.error is None
            assert j.result is not None
    finally:
        fe.stop()


def test_frontend_multi_failure_fails_every_leased_job():
    """A worker posting ok=False (or a short results list) for a multi
    lease must fail/retry EVERY leased job -- a short list must never
    strand window-mates until the dispatch deadline."""
    from tempo_tpu.services.frontend import Frontend, _Job
    from tempo_tpu.services.querier import Querier

    db = _mkdb()
    m = db.write_block(TENANT, make_traces(10, seed=42, n_spans=3))
    querier = Querier(db, ring=None, client_for=lambda a: None)
    fe = Frontend(querier, n_workers=0)
    try:
        for bad_result in (None, {"results": []}):
            jobs = []
            for i in range(3):
                j = _Job(kind="search_blocks",
                         payload={"req": {"limit": 5}, "block_ids": [m.block_id]},
                         fn=None, args=(),
                         batch_key=("search_blocks", TENANT, (m.block_id,)))
                j.tries = 99  # exhaust retries: failure must surface now
                jobs.append(j)
                fe.queue.enqueue(TENANT, j)
            wire = fe.poll_job(wait_s=1.0)
            assert wire is not None and wire["kind"] == "multi"
            fe.complete_job(wire["id"], ok=bad_result is not None,
                            result=bad_result, error="worker exploded",
                            retryable=True)
            for j in jobs:
                assert j.done.is_set()  # not stranded
                assert j.error is not None
    finally:
        fe.stop()


def test_worker_executes_multi_wire_job():
    from tempo_tpu.db.search import request_to_dict
    from tempo_tpu.services.querier import Querier
    from tempo_tpu.services.worker import execute_job

    db = _mkdb()
    m = db.write_block(TENANT, make_traces(30, seed=43, n_spans=4))
    db.poll_now()
    querier = Querier(db, ring=None, client_for=lambda a: None)
    req = SearchRequest(query='{ name != "zzz" }', limit=5)
    payload = {"kind": "search_blocks",
               "tenants": [TENANT, TENANT],
               "jobs": [{"req": request_to_dict(req),
                         "block_ids": [m.block_id]}] * 2}
    out = execute_job(querier, TENANT, "multi", payload)
    assert len(out["results"]) == 2
    blk = db.open_block(m)
    expect = _dicts(search_block(blk, req))
    for r in out["results"]:
        assert r["traces"] == expect
