"""Single-binary HTTP API e2e: OTLP ingest -> query/search/tags/metrics.

The analog of the reference's TestAllInOne (integration/e2e/e2e_test.go:40):
push real OTLP over HTTP, assert metrics counters, query by id, search,
force flush, query again from the backend.
"""

import json
import socket
import time
import urllib.parse
import urllib.request

import pytest

from tempo_tpu.services.app import App, AppConfig
from tempo_tpu.services.ingester import IngesterConfig
from tempo_tpu.util.testdata import make_traces
from tempo_tpu.wire import otlp_json


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    root = tmp_path_factory.mktemp("tempo-data")
    cfg = AppConfig(
        storage_path=str(root),
        http_port=_free_port(),
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    yield app, f"http://127.0.0.1:{cfg.http_port}"
    app.stop()


def _get(base, path, expect=200):
    try:
        with urllib.request.urlopen(base + path) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        assert e.code == expect, (e.code, e.read())
        return e.code, e.read()


def _post(base, path, body, ctype="application/json"):
    req = urllib.request.Request(base + path, data=body, headers={"Content-Type": ctype})
    with urllib.request.urlopen(req) as r:
        return r.status, r.read()


def test_http_e2e(server):
    app, base = server
    st, body = _get(base, "/api/echo")
    assert st == 200 and body == b"echo"
    st, _ = _get(base, "/ready")
    assert st == 200

    traces = make_traces(12, seed=42, n_spans=5)
    for _, tr in traces:
        st, _ = _post(base, "/v1/traces", otlp_json.dumps(tr).encode())
        assert st == 200

    # metrics counted the spans
    st, body = _get(base, "/metrics")
    total = sum(t.span_count() for _, t in traces)
    assert f"tempo_distributor_spans_received_total {total}" in body.decode()

    # query by id from live ingester
    tid, tr = traces[0]
    st, body = _get(base, f"/api/traces/{tid.hex()}")
    assert st == 200
    got = otlp_json.loads(body)
    assert got.span_count() == tr.span_count()

    # flush to backend blocks, then query again
    st, _ = _post(base, "/flush", b"")
    assert st == 204
    app.db.poll_now()
    tid, tr = traces[1]
    st, body = _get(base, f"/api/traces/{tid.hex()}")
    assert st == 200
    assert otlp_json.loads(body).span_count() == tr.span_count()

    # 404 for a missing trace
    st, _ = _get(base, "/api/traces/" + "00" * 16, expect=404)
    assert st == 404

    # search by tag + TraceQL
    expect_db = {
        tid.hex()
        for tid, t in traces
        if any(r.service_name == "db" for r, _, _ in t.all_spans())
    }
    st, body = _get(base, "/api/search?tags=service.name%3Ddb&limit=100")
    assert st == 200
    got_ids = {t["traceID"] for t in json.loads(body)["traces"]}
    assert got_ids == expect_db

    q = urllib.parse.quote('{ resource.service.name = "db" }')
    st, body = _get(base, f"/api/search?q={q}&limit=100")
    assert {t["traceID"] for t in json.loads(body)["traces"]} == expect_db

    # tag discovery
    st, body = _get(base, "/api/search/tags")
    tags = json.loads(body)["tagNames"]
    assert "service.name" in tags
    st, body = _get(base, "/api/search/tag/service.name/values")
    vals = json.loads(body)["tagValues"]
    assert "db" in vals

    # span-metrics from the generator tap (async: drains within ms)
    deadline = time.time() + 5
    while True:
        st, body = _get(base, "/metrics")
        if "traces_spanmetrics_calls_total" in body.decode() or time.time() > deadline:
            break
        time.sleep(0.05)
    assert "traces_spanmetrics_calls_total" in body.decode()


def test_zipkin_ingest(server):
    """Zipkin v2 JSON spans round-trip through the distributor."""
    app, base = server
    zipkin_payload = json.dumps([
        {
            "traceId": "0af7651916cd43dd8448eb211c80319c",
            "id": "b7ad6b7169203331",
            "name": "get /api",
            "timestamp": 1700000001000000,
            "duration": 207000,
            "kind": "SERVER",
            "localEndpoint": {"serviceName": "zip-frontend"},
            "tags": {"http.method": "GET", "http.status_code": "200"},
        },
        {
            "traceId": "0af7651916cd43dd8448eb211c80319c",
            "id": "d2f9288a2904503d",
            "parentId": "b7ad6b7169203331",
            "name": "query db",
            "timestamp": 1700000001010000,
            "duration": 50000,
            "kind": "CLIENT",
            "localEndpoint": {"serviceName": "zip-frontend"},
            "remoteEndpoint": {"serviceName": "zip-db"},
        },
    ]).encode()
    st, _ = _post(base, "/api/v2/spans", zipkin_payload)
    assert st == 202
    st, body = _get(base, "/api/traces/0af7651916cd43dd8448eb211c80319c")
    assert st == 200
    got = otlp_json.loads(body)
    assert got.span_count() == 2
    spans = {sp.name: (res, sp) for res, _, sp in got.all_spans()}
    assert spans["get /api"][0].service_name == "zip-frontend"
    assert spans["get /api"][1].attrs["http.status_code"] == 200
    assert spans["query db"][1].attrs["peer.service"] == "zip-db"
    # findable via TraceQL on the converted attrs
    q = urllib.parse.quote('{ span.http.method = "GET" && resource.service.name = "zip-frontend" }')
    st, body = _get(base, f"/api/search?q={q}&limit=10")
    assert "0af7651916cd43dd8448eb211c80319c" in {t["traceID"] for t in json.loads(body)["traces"]}


def test_jaeger_query_shim(server):
    """The tempo-query analog renders Jaeger UI JSON."""
    app, base = server
    traces = make_traces(1, seed=123, n_spans=3)
    tid, tr = traces[0]
    _post(base, "/v1/traces", otlp_json.dumps(tr).encode())
    st, body = _get(base, f"/jaeger/api/traces/{tid.hex()}")
    assert st == 200
    j = json.loads(body)
    assert j["data"][0]["traceID"] == tid.hex()
    assert len(j["data"][0]["spans"]) == 3
    assert j["data"][0]["processes"]
    sp = j["data"][0]["spans"][0]
    assert {"traceID", "spanID", "operationName", "startTime", "duration",
            "tags", "processID"} <= set(sp)


def test_otlp_grpc_ingest(tmp_path):
    """Push via OTLP gRPC (the default OTel exporter transport) to a
    -target=all app and read the trace back by id over HTTP (reference:
    receiver shim's gRPC receiver, modules/distributor/receiver/shim.go)."""
    grpc = pytest.importorskip("grpc")
    from tempo_tpu.wire import otlp_pb

    cfg = AppConfig(
        storage_path=str(tmp_path / "store"),
        http_port=_free_port(),
        otlp_grpc_port=-1,  # ephemeral
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    try:
        assert cfg.otlp_grpc_port > 0  # receiver bound an ephemeral port
        ch = grpc.insecure_channel(f"127.0.0.1:{cfg.otlp_grpc_port}")
        export = ch.unary_unary(
            "/opentelemetry.proto.collector.trace.v1.TraceService/Export",
            request_serializer=None, response_deserializer=None,
        )
        traces = make_traces(5, seed=31, n_spans=4)
        for _, tr in traces:
            # ExportTraceServiceRequest wire == TracesData wire
            resp = export(otlp_pb.encode_trace(tr))
            assert resp == b""
        base = f"http://127.0.0.1:{cfg.http_port}"
        tid, tr = traces[2]
        with urllib.request.urlopen(f"{base}/api/traces/{tid.hex()}", timeout=10) as r:
            got = otlp_json.loads(r.read())
        assert got.span_count() == tr.span_count()
        # malformed payload maps to INVALID_ARGUMENT, not a hung stream
        with pytest.raises(grpc.RpcError) as ei:
            export(b"\xff\xff\xff")
        assert ei.value.code() in (grpc.StatusCode.INVALID_ARGUMENT,
                                   grpc.StatusCode.INTERNAL)
        ch.close()
    finally:
        app.stop()


def test_opencensus_grpc_ingest(tmp_path):
    """Push via the OpenCensus agent protocol (bidi stream, sticky
    per-stream node/resource) and read the trace back over HTTP
    (reference: shim.go:98 registers the opencensus receiver). The
    second request message omits node+resource to prove the stream
    state sticks."""
    grpc = pytest.importorskip("grpc")
    from tempo_tpu.wire import pbwire as w

    def trunc(s):
        b = bytearray()
        w.write_string_field(b, 1, s)
        return bytes(b)

    def ts(ns):
        b = bytearray()
        w.write_varint_field(b, 1, ns // 10**9)
        w.write_varint_field(b, 2, ns % 10**9)
        return bytes(b)

    def attr_val(v):
        b = bytearray()
        if isinstance(v, bool):
            w.write_varint_field(b, 3, 1 if v else 0)
        elif isinstance(v, str):
            w.write_message_field(b, 1, trunc(v))
        elif isinstance(v, int):
            w.write_varint_field(b, 2, v)
        elif isinstance(v, float):
            w.write_double_field(b, 4, v)
        return bytes(b)

    def attributes(d):
        b = bytearray()
        for k, v in d.items():
            e = bytearray()
            w.write_string_field(e, 1, k)
            w.write_message_field(e, 2, attr_val(v))
            w.write_message_field(b, 1, bytes(e))
        return bytes(b)

    T0 = 1_700_000_000_000_000_000
    tid = bytes(range(16))

    def oc_span(span_id, name, kind=1, parent=b"", attrs=None, status=None,
                annotation=None):
        # field numbers per the OC proto (census-instrumentation
        # opencensus-proto trace.pb.go), NOT OTLP's renumbered fork:
        # 3=parent, 4=name, 5=start, 6=end, 7=attributes,
        # 9=time_events, 11=status, 14=kind
        b = bytearray()
        w.write_bytes_field(b, 1, tid)
        w.write_bytes_field(b, 2, span_id)
        if parent:
            w.write_bytes_field(b, 3, parent)
        w.write_message_field(b, 4, trunc(name))
        w.write_message_field(b, 5, ts(T0))
        w.write_message_field(b, 6, ts(T0 + 5_000_000))
        if attrs:
            w.write_message_field(b, 7, attributes(attrs))
        if annotation:
            tev = bytearray()
            w.write_message_field(tev, 1, ts(T0 + 1_000_000))
            ann = bytearray()
            w.write_message_field(ann, 1, trunc(annotation))
            w.write_message_field(tev, 2, bytes(ann))
            evs = bytearray()
            w.write_message_field(evs, 1, bytes(tev))
            w.write_message_field(b, 9, bytes(evs))
        if status:
            st = bytearray()
            w.write_varint_field(st, 1, status[0])
            w.write_string_field(st, 2, status[1])
            w.write_message_field(b, 11, bytes(st))
        w.write_varint_field(b, 14, kind)
        return bytes(b)

    # node { identifier { host_name } , service_info { name } }
    node = bytearray()
    ident = bytearray()
    w.write_string_field(ident, 1, "host-7")
    w.write_message_field(node, 1, bytes(ident))
    svc = bytearray()
    w.write_string_field(svc, 1, "oc-svc")
    w.write_message_field(node, 3, bytes(svc))
    # resource { type, labels }
    res = bytearray()
    w.write_string_field(res, 1, "container")
    lbl = bytearray()
    w.write_string_field(lbl, 1, "region")
    w.write_string_field(lbl, 2, "eu-1")
    w.write_message_field(res, 2, bytes(lbl))

    req1 = bytearray()
    w.write_message_field(req1, 1, bytes(node))
    w.write_message_field(req1, 2, oc_span(
        b"\x01" * 8, "root", kind=1,
        attrs={"s": "x", "i": 42, "b": True, "d": 2.5},
        annotation="checkpoint"))
    w.write_message_field(req1, 3, bytes(res))
    req2 = bytearray()  # NO node/resource: inherits the stream's
    w.write_message_field(req2, 2, oc_span(
        b"\x02" * 8, "child", kind=2, parent=b"\x01" * 8,
        status=(13, "boom")))

    cfg = AppConfig(
        storage_path=str(tmp_path / "store"),
        http_port=_free_port(),
        opencensus_grpc_port=-1,  # ephemeral
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    try:
        assert cfg.opencensus_grpc_port > 0
        ch = grpc.insecure_channel(f"127.0.0.1:{cfg.opencensus_grpc_port}")
        export = ch.stream_stream(
            "/opencensus.proto.agent.trace.v1.TraceService/Export",
            request_serializer=None, response_deserializer=None,
        )
        acks = list(export(iter([bytes(req1), bytes(req2)])))
        assert acks == [b"", b""]
        ch.close()

        base = f"http://127.0.0.1:{cfg.http_port}"
        with urllib.request.urlopen(f"{base}/api/traces/{tid.hex()}",
                                    timeout=10) as r:
            got = otlp_json.loads(r.read())
        spans = {sp.name: (resr, sp) for resr, _, sp in got.all_spans()}
        assert set(spans) == {"root", "child"}
        res_root, root = spans["root"]
        res_child, child = spans["child"]
        # node + resource identity applied to BOTH messages (sticky)
        for resr in (res_root, res_child):
            assert resr.attrs["service.name"] == "oc-svc"
            assert resr.attrs["host.hostname"] == "host-7"
            assert resr.attrs["region"] == "eu-1"
            assert resr.attrs["opencensus.resourcetype"] == "container"
        assert root.kind == 2  # OC SERVER -> model SERVER
        assert child.kind == 3  # OC CLIENT -> model CLIENT
        assert root.attrs == {"s": "x", "i": 42, "b": True, "d": 2.5}
        assert root.events[0].name == "checkpoint"
        assert root.events[0].time_unix_nano == T0 + 1_000_000
        assert child.parent_span_id == b"\x01" * 8
        assert child.status_code == 2 and child.status_message == "boom"
        assert root.start_unix_nano == T0

        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            metrics = r.read().decode()
        assert "tempo_opencensus_receiver_spans_total 2" in metrics
    finally:
        app.stop()


def test_metrics_depth(server):
    """/metrics exposes latency histograms plus a broad counter set
    (>=25 series) across roles (reference: promauto instrumentation on
    every subsystem, distributor.go:56-103, poller.go:26-68)."""
    app, base = server
    # generate some traffic so histograms have observations
    traces = make_traces(3, seed=77, n_spans=3)
    for _, tr in traces:
        req = urllib.request.Request(base + "/v1/traces",
                                     data=otlp_json.dumps(tr).encode(),
                                     headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10)
    urllib.request.urlopen(f"{base}/api/search?limit=10", timeout=15)
    urllib.request.urlopen(f"{base}/api/traces/{traces[0][0].hex()}", timeout=15)
    with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
        text = r.read().decode()
    lines = [l for l in text.splitlines() if l and not l.startswith("#")]
    assert len(lines) >= 25, f"only {len(lines)} series"
    assert any("tempo_distributor_push_duration_seconds_bucket" in l for l in lines)
    assert any("tempo_frontend_query_duration_seconds_bucket" in l
               and 'op="search"' in l for l in lines)
    assert any("tempo_frontend_query_duration_seconds_bucket" in l
               and 'op="traces"' in l for l in lines)
    assert any(l.startswith("tempo_blocklist_polls_total") for l in lines)
    assert any(l.startswith("tempo_blocklist_length") for l in lines)


def test_usage_stats(server):
    """Cluster seed persists in the backend; /status/usage-stats serves
    the report (reference: pkg/usagestats, deployment-local here)."""
    app, base = server
    with urllib.request.urlopen(base + "/status/usage-stats", timeout=10) as r:
        rep = json.loads(r.read())
    assert rep["clusterID"] and rep["target"] == "all"
    assert "blocklist_length" in rep["metrics"]
    # stable across reads (seed persisted, not regenerated)
    with urllib.request.urlopen(base + "/status/usage-stats", timeout=10) as r:
        assert json.loads(r.read())["clusterID"] == rep["clusterID"]


def test_self_tracing(tmp_path):
    """With self-tracing on, a user query produces a queryable trace of
    ITSELF (root span + per-job children) under the self tenant."""
    cfg = AppConfig(
        storage_path=str(tmp_path / "store"),
        http_port=_free_port(),
        multitenancy=True,
        self_tracing_tenant="self",
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    base = f"http://127.0.0.1:{cfg.http_port}"
    try:
        traces = make_traces(3, seed=88, n_spans=3)
        for _, tr in traces:
            req = urllib.request.Request(base + "/v1/traces",
                                         data=otlp_json.dumps(tr).encode(),
                                         headers={"Content-Type": "application/json",
                                                  "X-Scope-OrgID": "t1"})
            urllib.request.urlopen(req, timeout=10)
        # a user-tenant search gets traced...
        req = urllib.request.Request(base + "/api/search?limit=10",
                                     headers={"X-Scope-OrgID": "t1"})
        urllib.request.urlopen(req, timeout=15)
        app.frontend.self_tracer.flush()  # async shipper drains
        # ...and the self tenant can be queried for it with the product
        req = urllib.request.Request(
            base + "/api/search?tags=" + urllib.parse.quote("name=frontend.search") + "&limit=10",
            headers={"X-Scope-OrgID": "self"})
        with urllib.request.urlopen(req, timeout=15) as r:
            hits = json.loads(r.read())["traces"]
        assert hits, "no self-trace recorded"
        # the self trace has job child spans
        with urllib.request.urlopen(
            urllib.request.Request(base + f"/api/traces/{hits[0]['traceID']}",
                                   headers={"X-Scope-OrgID": "self"}), timeout=15) as r:
            tr = otlp_json.loads(r.read())
        names = [sp.name for _, _, sp in tr.all_spans()]
        assert "frontend.search" in names
        assert any(n.startswith("job:") for n in names), names
        # and querying the self tenant did NOT recurse into more traces
        assert app.frontend.self_tracer.spans_emitted < 50
    finally:
        app.stop()


def test_status_config_modes(server):
    """/status/config?mode=defaults serves the built-in config,
    mode=diff only the fields this instance changed (the reference's
    runtime-config mode variants); an unknown mode is a 400."""
    app, base = server
    st, body = _get(base, "/status/config")
    full = json.loads(body)
    st, body = _get(base, "/status/config?mode=defaults")
    defaults = json.loads(body)
    assert set(defaults) == set(full)
    assert defaults["http_port"] != full["http_port"]  # fixture port
    st, body = _get(base, "/status/config?mode=diff")
    diff = json.loads(body)
    assert 0 < len(diff) < len(full)
    assert diff["storage_path"] == full["storage_path"]
    assert all(full[k] == v and defaults.get(k) != v for k, v in diff.items())
    assert _get(base, "/status/config?mode=bogus", expect=400)[0] == 400


def test_debug_endpoints(server):
    """/debug/threads (the pprof goroutine-dump analog) and
    /debug/profile (sampling CPU profile across all threads)."""
    app, base = server
    st, body = _get(base, "/debug/threads")
    assert st == 200 and body.decode().count("--- thread") >= 2
    st, body = _get(base, "/debug/profile?seconds=0.3")
    assert st == 200 and "sampling profile" in body.decode()


def test_config_expand_env(tmp_path, monkeypatch):
    """--config.expand-env substitutes ${VAR} / ${VAR:-default} before
    YAML parse (the reference's envsubst option); without the flag the
    file is taken literally."""
    from tempo_tpu.services.app import load_config_file

    cfg = tmp_path / "tempo.yaml"
    cfg.write_text(
        "target: ${TEMPO_TARGET:-all}\n"
        "storage_path: ${TEMPO_STORE}\n"
        "http_port: 0\n"
    )
    monkeypatch.setenv("TEMPO_STORE", "/data/blocks")
    monkeypatch.delenv("TEMPO_TARGET", raising=False)
    data = load_config_file(str(cfg), expand_env=True)
    assert data["target"] == "all"
    assert data["storage_path"] == "/data/blocks"
    monkeypatch.setenv("TEMPO_TARGET", "querier")
    assert load_config_file(str(cfg), expand_env=True)["target"] == "querier"
    # shell ':-' semantics: set-but-EMPTY also takes the default
    monkeypatch.setenv("TEMPO_TARGET", "")
    assert load_config_file(str(cfg), expand_env=True)["target"] == "all"
    # unset without a default fails at config load, not deep in startup
    monkeypatch.delenv("TEMPO_STORE")
    with pytest.raises(ValueError, match="TEMPO_STORE"):
        load_config_file(str(cfg), expand_env=True)
    monkeypatch.setenv("TEMPO_STORE", "/data/blocks")
    # literal without the flag
    assert load_config_file(str(cfg))["storage_path"] == "${TEMPO_STORE}"
