"""TraceQL grammar conformance: every query vector from the reference's
pkg/traceql/test_examples.yaml, in the same three buckets — valid
(parse + validate), parse_fails (lexer/grammar error), validate_fails
(parses, then type checking rejects). The vectors are the reference's
own test DATA (a spec of the language surface), exercised here against
our hand-rolled parser + validator."""

import pytest

from tempo_tpu.traceql.ast import ParseError
from tempo_tpu.traceql.parser import _Parser, tokenize
from tempo_tpu.traceql.validate import ValidationError, validate

VALID = [
    # spanset filters
    '{ true }',
    '{ !true }',
    '{ true && false }',
    '{ true || false }',
    '{ 1 = 2 }',
    '{ 1 != 2 }',
    '{ 1 > 2 }',
    '{ 1 >= 2 }',
    '{ 1 < 2 }',
    '{ 1 <= 2 }',
    '{ 1 + 1 = 2 }',
    '{ 1 - 1 = 2 }',
    '{ 1 * 1 = 2 }',
    '{ 1 / 1 = 2 }',
    '{ 1 ^ 1 = 2 }',
    '{ -1 = 2 }',
    '{ "test" =~ "test" }',
    '{ "test" !~ "test" }',
    '{ "test" = "test" }',
    '{ "test" != "test" }',
    '{ .a }',
    '{ !.a }',
    '{ .a && false }',
    '{ .a || true }',
    '{ .a = 2 }',
    '{ .a != 2 }',
    '{ .a > 2 }',
    '{ .a >= 2 }',
    '{ .a < 2 }',
    '{ .a <= 2 }',
    '{ .a + 1 = 2 }',
    '{ .a - 1 = 2 }',
    '{ .a * 1 = 2 }',
    '{ .a / 1 = 2 }',
    '{ .a ^ 1 = 2 }',
    '{ -.a = 2 }',
    '{ .a =~ "test" }',
    '{ .a !~ "test" }',
    '{ .a = "test" }',
    '{ .a != "test" }',
    '{ parent.a != 3 }',
    '{ parent.resource.a && true }',
    '{ parent.span.a > 3 }',
    '{ parent.duration = 1h }',
    '{ resource.a != 3 }',
    '{ span.a != 3 }',
    '{ !("test" != .c || ((true && .b) || 3 < .a)) }',
    '{ parent = nil }',
    '{ status = ok }',
    '{ status = unset }',
    '{ status = error }',
    '{ status != error }',
    '{ duration > 1s }',
    '{ duration > 1s * 2s }',
    '{ .foo = nil }',
    '{ 1 = childCount }',
    '{ 1 * 1h = 1 }',
    '{ 1 / 1.1 = 1 }',
    '{ 1 < 1h }',
    '{ 1 <= 1.1 }',
    # spanset expressions
    '{ true } && { true }',
    '{ true } || { true }',
    '{ true } >> { true }',
    '{ true } > { true }',
    '{ true } ~ { true }',
    # scalar filters
    'avg(.field) > 1',
    'min(childCount) < 2',
    'max(duration) >= 1s',
    'min(.field) < max(duration)',
    'sum(.field) = min(.field)',
    'max(duration) > 1',
    'min(.field) + max(.field) > 1',
    'min(.field) + max(childCount) > max(duration) - min(.field)',
    'avg(.field) > 1 - 3',
    'min(childCount) < 2 / 6',
    'max(1 - (2 + .field)) < avg(3 * duration ^ 2)',
    '3 = 2',
    # pipelines
    '{ true } | { .a }',
    '{ true } | count() = 1',
    '{ true } | max(duration) = 1h',
    '{ true } | min(duration) = 1h',
    '{ true } | avg(duration) = 1h',
    '{ true } | sum(duration) = 1h',
    '{ true } | count() + count() = 1',
    'count() = 1 | { true }',
    '{ true } | max(.a) = 1',
    '{ true } | max(parent.a) = 1',
    '{ true } | max(span.a) = 1',
    '{ true } | max(resource.a) = 1',
    '{ true } | max(1 + .a) = 1',
    '{ true } | max((1 + .a) * 2) = 1',
    '{ true } | coalesce()',
    '{ true } | by(.a)',
    '{ true } | by(1 + .a)',
    'by(.a) | { true }',
    '{ true } | by(1 + .a) | coalesce()',
    '{ true } | by(name) | count() > 2',
    '{ true } | by(.field) | avg(.b) = 2',
    '{ true } | by(3 * .field - 2) | max(duration) < 1s',
    '{ true } | count() = 1 | { true }',
    # pipeline expressions
    '({ true } | count()) + ({ true } | count()) = 1',
    '({ true } | count()) - ({ true } | count()) <= 1',
    '({ true } | count()) / ({ true } | count()) > ({ true } | count()) / ({ true } | count())',
    '({ true } | count()) * ({ true } | count()) < ({ true } | count()) / ({ true } | count())',
    '({ true } | count() > 1 | { false }) && ({ true } | count() > 1 | { false })',
    '({ true } | count() > 1 | { false }) || ({ true } | count() > 1 | { false })',
    '({ true } | count() > 1 | { false }) >> ({ true } | count() > 1 | { false })',
    '({ true } | count() > 1 | { false }) > ({ true } | count() > 1 | { false })',
    '({ true } | count() > 1 | { false }) ~ ({ true } | count() > 1 | { false })',
    # random
    'max(duration) > 3s | { status = error || .http.status = 500 }',
    '{ .http.status = 200 } | max(.field) - min(.field) > 3',
    '({ .http.status = 200 } | count()) + ({ name = `foo` } | avg(duration)) = 2',
    '{ (-(3 / 2) * .test - parent.blerg + .other)^3 = 2 }',
    '({ .a } | count()) > ({ .b } | count())',
]

PARSE_FAILS = [
    'true',
    '[ true ]',
    '( true )',
    # spanset filters
    '{ }',
    '{ . }',
    '{ < }',
    '{ .a < }',
    '{ .a < 3',
    '{ (.a < 3 }',
    '{ attribute = 4 }',
    '{ .attribute == 4 }',
    '{ span. }',
    # spanset expressions
    '{ true } + { true }',
    '{ true } - { true }',
    '{ true } * { true }',
    '{ true } / { true }',
    '{ true } ^ { true }',
    '{ true } = { true }',
    '{ true } <= { true }',
    '{ true } >= { true }',
    '{ true } < { true }',
    # scalar filters
    'avg(.field) + 1',
    'sum(3) - 2',
    'min(childCount) && 2',
    # pipelines
    'coalesce() | { true }',
    'count() > 3 && { true }',
    '{ true } | count()',
    '{ true } | notAnAggregate() = 1',
    '{ true } | count = 1',
    '{ true } | max() = 1',
    '{ true } | by()',
    # pipeline expressions
    '({ true }) + (count()) = 1',
    '({ true }) && (count())',
    '({ true } | count()) && ({ true } | count()) = 1',
    '({ true }) + ({ true }) = 1',
    '({ true } | count()) + ({ true } | count())',
    '(by(namespace) | count()) > 2 * 2',
    '(by(namespace) | count()) * 2 > 2',
    '2 < (by(namespace) | count())',
]

VALIDATE_FAILS = [
    # span expressions must evaluate to a boolean
    '{ 1 + 1 }',
    '{ parent }',
    '{ status }',
    '{ ok }',
    '{ 1.1 }',
    '{ 1h }',
    '{ "foo" }',
    # binary operators - incorrect types
    '{ 1 + "foo" = 1 }',
    '{ 1 - true = 1 }',
    '{ 1 / ok = 1 }',
    '{ 1 % parent = 1 }',
    '{ 1 ^ name = 1 }',
    '{ 1 = "foo" }',
    '{ 1 != true }',
    '{ 1 > ok }',
    '{ 1 >= parent }',
    '{ 1 = name }',
    '{ 1 =~ 2}',
    '{ 1 && "foo" }',
    '{ 1 || ok }',
    '{ true || 1.1 }',
    '{ "foo" = childCount }',
    '{ status > ok }',
    # unary operators - incorrect types
    '{ -true }',
    '{ -"foo" = "bar" }',
    '{ -ok = status }',
    '{ -parent = nil }',
    '{ -name = "foo" }',
    '{ !"foo" = "bar" }',
    '{ !ok = status }',
    '{ !parent = nil }',
    '{ !name = "foo" }',
    '{ !1 = 1 }',
    '{ !1h = 1 }',
    '{ !1.1 = 1.1 }',
    # scalar expressions must evaluate to a number
    'max(name) = "foo"',
    'min(parent) = nil',
    'avg("foo") = "bar"',
    'max(status) = ok',
    'min(1 = 3) = 1',
    # scalar expressions must reference the span
    'sum(3) = 2',
    'sum(3) = min(14)',
    'min(2h) < max(duration)',
    'max(1h + 2h) > 1',
    'min(1.1 - 3) > 1',
    'min(3) = max(duration)',
    'min(1) = max(2) + 3',
    # group expressions must reference the span
    '{ true } | by(1)',
    '{ true } | by("foo")',
    # scalar filters have to match types
    'min(1) = "foo"',
    'avg(childCount) > "foo"',
    'max(duration) < ok',
]


def _parse_only(src: str):
    """Parse without validation (validate_fails vectors must get PAST
    the grammar)."""
    p = _Parser(tokenize(src))
    return p.parse_query()


@pytest.mark.parametrize("q", VALID)
def test_valid(q):
    ast = _parse_only(q)
    validate(ast)


@pytest.mark.parametrize("q", PARSE_FAILS)
def test_parse_fails(q):
    with pytest.raises(ParseError):
        _parse_only(q)


@pytest.mark.parametrize("q", VALIDATE_FAILS)
def test_validate_fails(q):
    ast = _parse_only(q)  # must parse...
    with pytest.raises(ValidationError):
        validate(ast)  # ...and fail type checking
