// Native runtime layer: the host-side hot loops around the TPU compute
// path (SURVEY.md 2.10 "native components"): batch hashing for ring
// tokens + bloom positions, bloom filter insertion, WAL record framing,
// and multi-threaded zstd (de)compression feeding column chunks.
//
// The reference leans on optimized Go libraries for these (klauspost
// compression, willf/bloom, segmentio/parquet-go page codecs); here the
// equivalents are C++ behind a C ABI consumed through ctypes
// (tempo_tpu/native/__init__.py), with pure-Python fallbacks when the
// shared library is absent.
//
// Build: make -C native   (g++ -O3 -shared -fPIC, links libzstd)

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

// images with the runtime libzstd but no dev headers (dpkg ships
// libzstd1 without libzstd-dev) still build: the handful of stable-ABI
// symbols used below are declared directly and the Makefile links the
// soname file (-l:libzstd.so.1) when the dev symlink is absent
#if defined(__has_include) && !__has_include(<zstd.h>)
extern "C" {
typedef struct ZSTD_CCtx_s ZSTD_CCtx;
typedef struct ZSTD_DCtx_s ZSTD_DCtx;
static const int ZSTD_c_compressionLevel = 100;
size_t ZSTD_compressBound(size_t srcSize);
unsigned ZSTD_isError(size_t code);
ZSTD_CCtx* ZSTD_createCCtx(void);
size_t ZSTD_freeCCtx(ZSTD_CCtx* cctx);
size_t ZSTD_CCtx_setParameter(ZSTD_CCtx* cctx, int param, int value);
size_t ZSTD_compress2(ZSTD_CCtx* cctx, void* dst, size_t dstCapacity,
                      const void* src, size_t srcSize);
ZSTD_DCtx* ZSTD_createDCtx(void);
size_t ZSTD_freeDCtx(ZSTD_DCtx* dctx);
size_t ZSTD_decompressDCtx(ZSTD_DCtx* dctx, void* dst, size_t dstCapacity,
                           const void* src, size_t srcSize);
}
#else
#include <zstd.h>
#endif

extern "C" {

// ---------------------------------------------------------------- hashing

// fnv1a32 over (tenant || trace_id) per row: ring tokens for a batch of
// trace ids (pkg/util/hash.go TokenFor analog).
void vtpu_ring_tokens(const uint8_t* tenant, int tenant_len,
                      const uint8_t* ids, int id_len, int n,
                      uint32_t* out) {
  for (int i = 0; i < n; i++) {
    uint32_t h = 2166136261u;
    for (int j = 0; j < tenant_len; j++) {
      h ^= tenant[j];
      h *= 16777619u;
    }
    const uint8_t* id = ids + (size_t)i * id_len;
    for (int j = 0; j < id_len; j++) {
      h ^= id[j];
      h *= 16777619u;
    }
    out[i] = h;
  }
}

// splitmix64: the bloom position generator (util/hashing.py bloom_hashes)
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

static inline uint64_t fnv1a64(const uint8_t* p, int n) {
  uint64_t h = 14695981039346656037ull;
  for (int i = 0; i < n; i++) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

// ------------------------------------------------------------------ bloom

static inline uint32_t fnv1a32(const uint8_t* p, int n) {
  uint32_t h = 2166136261u;
  for (int i = 0; i < n; i++) {
    h ^= p[i];
    h *= 16777619u;
  }
  return h;
}

// Batch-insert n trace ids into a sharded bloom filter. Bit-for-bit the
// same scheme as the Python side (block/bloom.py + util/hashing.py):
// shard = fnv1a32(id) % n_shards; Kirsch-Mitzenmacher double hashing
// h_i = h1 + i*(splitmix64(h1)|1) over fnv1a64(id).
void vtpu_bloom_add_batch(uint32_t* words, int n_shards, int words_per_shard,
                          int shard_bits, int k,
                          const uint8_t* ids, int id_len, int n) {
  for (int i = 0; i < n; i++) {
    const uint8_t* id = ids + (size_t)i * id_len;
    int shard = (int)(fnv1a32(id, id_len) % (uint32_t)n_shards);
    uint32_t* w = words + (size_t)shard * words_per_shard;
    uint64_t h1 = fnv1a64(id, id_len);
    uint64_t h2 = splitmix64(h1) | 1ull;
    for (int j = 0; j < k; j++) {
      uint32_t pos = (uint32_t)((h1 + (uint64_t)j * h2) % (uint64_t)shard_bits);
      w[pos >> 5] |= (1u << (pos & 31));
    }
  }
}

// ------------------------------------------------------------- wal frames

// Scan uvarint-framed records: data = repeated [uvarint len][body].
// Fills offsets/lengths (body position/size); returns count, or -count-1
// if a torn tail starts at offsets[count] (replay truncates there).
int vtpu_varint_frames(const uint8_t* data, int64_t n,
                       int64_t* offsets, int64_t* lengths, int max_frames) {
  int64_t pos = 0;
  int count = 0;
  while (pos < n && count < max_frames) {
    int64_t start = pos;
    uint64_t len = 0;
    int shift = 0;
    bool ok = false;
    while (pos < n && shift < 64) {
      uint8_t b = data[pos++];
      len |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) {
        ok = true;
        break;
      }
      shift += 7;
    }
    if (!ok || len > (uint64_t)(n - pos)) {  // unsigned: >=2^63 len must read as torn, not negative
      offsets[count] = start;  // torn tail marker
      return -count - 1;
    }
    offsets[count] = pos;
    lengths[count] = (int64_t)len;
    pos += (int64_t)len;
    count++;
  }
  return count;
}

// ------------------------------------------------------------ id bisect

// Batched binary search of q 16-byte trace ids over a sorted (n, 16)
// id table (memcmp order == big-endian lexicographic == the block's
// trace.id sort). out[i] = row of an exact match, else -1. The host
// twin of the device lockstep-bisection kernel (ops/find.py): numpy's
// void16 searchsorted pays per-probe object machinery; this is a tight
// memcmp loop.
void vtpu_lex_bisect16(const uint8_t* ids, int64_t n, const uint8_t* queries,
                       int64_t q, int32_t* out) {
  for (int64_t i = 0; i < q; i++) {
    const uint8_t* key = queries + i * 16;
    int64_t lo = 0, hi = n;
    while (lo < hi) {
      int64_t mid = (lo + hi) >> 1;
      if (memcmp(ids + mid * 16, key, 16) < 0) lo = mid + 1;
      else hi = mid;
    }
    out[i] = (lo < n && memcmp(ids + lo * 16, key, 16) == 0)
                 ? (int32_t)lo : -1;
  }
}

// --------------------------------------------------------- otlp span scan

// Structural scan of an OTLP ExportTraceServiceRequest / TracesData:
// locate every span submessage (byte range + owning resource/scope
// envelope) and pull exactly three fields out of each span body --
// trace_id (1), start (7) and end (8) -- WITHOUT decoding anything
// else. The distributor's fast ingest path re-batches spans by trace
// id by SPLICING these ranges back together under re-used envelope
// bytes (wire/otlp_splice.py), replacing the Python
// decode-model-re-encode round trip.
//
// Envelopes: for each ResourceSpans, every field EXCEPT scope_spans(2)
// verbatim (tag+len+body); for each ScopeSpans, every field except
// spans(2). Copied into env_buf so the Python side splices with two
// slices per group.
//
// Returns 0 ok; 1 malformed (caller falls back to the Python decode
// path); 2 capacity exceeded (caller re-calls with larger buffers).

static inline bool oscan_varint(const uint8_t* d, int64_t n, int64_t* pos,
                                uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (*pos < n && shift < 64) {
    uint8_t b = d[(*pos)++];
    v |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

int vtpu_otlp_scan(const uint8_t* buf, int64_t n,
                   int64_t* span_off, int64_t* span_len, int32_t* span_rs,
                   int32_t* span_ss, uint8_t* trace_ids, uint64_t* start_ns,
                   uint64_t* end_ns, int64_t cap_spans,
                   uint8_t* env_buf, int64_t cap_env,        // rs envelopes
                   uint8_t* senv_buf, int64_t cap_senv,      // ss envelopes
                   int64_t* rs_env_off, int64_t* rs_env_len, int64_t cap_rs,
                   int64_t* ss_env_off, int64_t* ss_env_len, int32_t* ss_rs,
                   int64_t cap_ss,
                   int64_t* counts /* [n_spans, n_rs, n_ss, env, senv] */) {
  int64_t ns_count = 0, nrs = 0, nss = 0, env_pos = 0, senv_pos = 0;
  int64_t pos = 0;
  while (pos < n) {  // TracesData: repeated resource_spans = 1
    uint64_t tag;
    int64_t tag_start = pos;
    (void)tag_start;
    if (!oscan_varint(buf, n, &pos, &tag)) return 1;
    uint64_t fno = tag >> 3, wt = tag & 7;
    if (wt != 2) return 1;  // top level is only length-delimited RS
    uint64_t len;
    // Compare unsigned against remaining bytes: casting len to int64_t
    // would let a crafted >=2^63 varint go negative and bypass the check.
    if (!oscan_varint(buf, n, &pos, &len) || len > (uint64_t)(n - pos)) return 1;
    if (fno != 1) {  // unknown top-level field: keep nothing, skip
      pos += (int64_t)len;
      continue;
    }
    // ---- one ResourceSpans
    if (nrs >= cap_rs) return 2;
    int64_t rs_idx = nrs++;
    rs_env_off[rs_idx] = env_pos;
    int64_t rs_end = pos + (int64_t)len;
    while (pos < rs_end) {
      int64_t f_start = pos;
      uint64_t ftag;
      if (!oscan_varint(buf, rs_end, &pos, &ftag)) return 1;
      uint64_t ffno = ftag >> 3, fwt = ftag & 7;
      int64_t body_off = pos, body_len = 0;
      if (fwt == 2) {
        uint64_t blen;
        if (!oscan_varint(buf, rs_end, &pos, &blen) ||
            blen > (uint64_t)(rs_end - pos))
          return 1;
        body_off = pos;
        body_len = (int64_t)blen;
        pos += body_len;
      } else if (fwt == 0) {
        uint64_t v;
        if (!oscan_varint(buf, rs_end, &pos, &v)) return 1;
      } else if (fwt == 1) {
        if (pos + 8 > rs_end) return 1;
        pos += 8;
      } else if (fwt == 5) {
        if (pos + 4 > rs_end) return 1;
        pos += 4;
      } else {
        return 1;
      }
      if (!(ffno == 2 && fwt == 2)) {  // non-scope_spans: envelope verbatim
        int64_t flen = pos - f_start;
        if (env_pos + flen > cap_env) return 2;
        memcpy(env_buf + env_pos, buf + f_start, (size_t)flen);
        env_pos += flen;
        continue;
      }
      // ---- one ScopeSpans
      if (nss >= cap_ss) return 2;
      int64_t ss_idx = nss++;
      ss_rs[ss_idx] = (int32_t)rs_idx;
      ss_env_off[ss_idx] = senv_pos;
      int64_t ss_end = body_off + body_len;
      int64_t spos = body_off;
      while (spos < ss_end) {
        int64_t sf_start = spos;
        uint64_t stag;
        if (!oscan_varint(buf, ss_end, &spos, &stag)) return 1;
        uint64_t sfno = stag >> 3, swt = stag & 7;
        int64_t sb_off = spos, sb_len = 0;
        if (swt == 2) {
          uint64_t blen;
          if (!oscan_varint(buf, ss_end, &spos, &blen) ||
              blen > (uint64_t)(ss_end - spos))
            return 1;
          sb_off = spos;
          sb_len = (int64_t)blen;
          spos += sb_len;
        } else if (swt == 0) {
          uint64_t v;
          if (!oscan_varint(buf, ss_end, &spos, &v)) return 1;
        } else if (swt == 1) {
          if (spos + 8 > ss_end) return 1;
          spos += 8;
        } else if (swt == 5) {
          if (spos + 4 > ss_end) return 1;
          spos += 4;
        } else {
          return 1;
        }
        if (!(sfno == 2 && swt == 2)) {  // non-span field: ss envelope
          int64_t flen = spos - sf_start;
          if (senv_pos + flen > cap_senv) return 2;
          memcpy(senv_buf + senv_pos, buf + sf_start, (size_t)flen);
          senv_pos += flen;
          continue;
        }
        // ---- one Span: record range + pull trace_id/start/end
        if (ns_count >= cap_spans) return 2;
        int64_t sp = ns_count++;
        span_off[sp] = sb_off;
        span_len[sp] = sb_len;
        span_rs[sp] = (int32_t)rs_idx;
        span_ss[sp] = (int32_t)ss_idx;
        start_ns[sp] = 0;
        end_ns[sp] = 0;
        bool got_tid = false;
        int64_t p2 = sb_off, sp_end = sb_off + sb_len;
        while (p2 < sp_end) {
          uint64_t t2;
          if (!oscan_varint(buf, sp_end, &p2, &t2)) return 1;
          uint64_t f2 = t2 >> 3, w2 = t2 & 7;
          if (w2 == 2) {
            uint64_t blen;
            if (!oscan_varint(buf, sp_end, &p2, &blen) ||
                blen > (uint64_t)(sp_end - p2))
              return 1;
            if (f2 == 1 && blen == 16) {
              memcpy(trace_ids + sp * 16, buf + p2, 16);
              got_tid = true;
            }
            p2 += (int64_t)blen;
          } else if (w2 == 1) {
            if (p2 + 8 > sp_end) return 1;
            uint64_t v;
            memcpy(&v, buf + p2, 8);  // little-endian hosts only (x86/arm)
            if (f2 == 7) start_ns[sp] = v;
            else if (f2 == 8) end_ns[sp] = v;
            p2 += 8;
          } else if (w2 == 0) {
            uint64_t v;
            if (!oscan_varint(buf, sp_end, &p2, &v)) return 1;
            // tolerate nonconformant varint timestamps
            if (f2 == 7) start_ns[sp] = v;
            else if (f2 == 8) end_ns[sp] = v;
          } else if (w2 == 5) {
            if (p2 + 4 > sp_end) return 1;
            p2 += 4;
          } else {
            return 1;
          }
        }
        if (!got_tid) return 1;  // spans without a 16B trace id: fall back
      }
      ss_env_len[ss_idx] = senv_pos - ss_env_off[ss_idx];
    }
    rs_env_len[rs_idx] = env_pos - rs_env_off[rs_idx];
  }
  counts[0] = ns_count;
  counts[1] = nrs;
  counts[2] = nss;
  counts[3] = env_pos;
  counts[4] = senv_pos;
  return 0;
}

// ----------------------------------------------------------- otlp splice

// Scan + group-by-trace-id + emit, one call: the distributor's whole
// rebatch loop (wire/otlp_splice.py used to drive vtpu_otlp_scan from
// Python and splice per-trace bytes in a Python loop -- the single
// biggest ingest cost). Emits finished wire segments back to back into
// `out`: 9-byte header (version 0x01, u32 start_s, u32 end_s, little
// endian -- wire/segment._HDR) followed by the per-trace TracesData
// built from envelope + span slices of the original payload.
//
// Returns 0 ok (counts = [n_traces, out_bytes, n_spans]);
//         1 malformed (caller falls back to the Python model path);
//         2 capacity: counts[0]/counts[1] carry the needed trace count
//           and out bytes -- re-call with buffers at least that big.

static inline int vsize(uint64_t v) {
  int s = 1;
  while (v >= 128) { v >>= 7; s++; }
  return s;
}

static inline void vput(uint8_t** p, uint64_t v) {
  while (v >= 128) { *(*p)++ = (uint8_t)(v | 0x80); v >>= 7; }
  *(*p)++ = (uint8_t)v;
}

int vtpu_otlp_splice(const uint8_t* buf, int64_t n,
                     uint8_t* out, int64_t cap_out,
                     uint8_t* tids_out, int64_t cap_traces,
                     int64_t* seg_off, int64_t* seg_len,
                     int64_t* start_s_out, int64_t* end_s_out,
                     int64_t* counts) {
  // scan with internally managed buffers (grow-on-demand mirrors the
  // Python binding's retry loop)
  std::vector<int64_t> sp_off, sp_len, rs_eoff, rs_elen, ss_eoff, ss_elen;
  std::vector<int32_t> sp_rs, sp_ss, ss_rsv;
  std::vector<uint8_t> tids, env, senv;
  std::vector<uint64_t> st_ns, en_ns;
  int64_t cap_spans = n / 24 + 16, cap_g = n / 64 + 8;
  int64_t c[5];
  int rc = 1;
  for (int t = 0; t < 4; t++) {
    sp_off.resize(cap_spans); sp_len.resize(cap_spans);
    sp_rs.resize(cap_spans); sp_ss.resize(cap_spans);
    tids.resize((size_t)cap_spans * 16);
    st_ns.resize(cap_spans); en_ns.resize(cap_spans);
    env.resize(n + 16); senv.resize(n + 16);
    rs_eoff.resize(cap_g); rs_elen.resize(cap_g);
    ss_eoff.resize(cap_g); ss_elen.resize(cap_g); ss_rsv.resize(cap_g);
    rc = vtpu_otlp_scan(buf, n, sp_off.data(), sp_len.data(), sp_rs.data(),
                        sp_ss.data(), tids.data(), st_ns.data(), en_ns.data(),
                        cap_spans, env.data(), (int64_t)env.size(),
                        senv.data(), (int64_t)senv.size(),
                        rs_eoff.data(), rs_elen.data(), cap_g,
                        ss_eoff.data(), ss_elen.data(), ss_rsv.data(), cap_g, c);
    if (rc == 2) { cap_spans *= 4; cap_g *= 4; continue; }
    break;
  }
  if (rc != 0) return 1;
  const int64_t k = c[0];
  counts[2] = k;
  if (k == 0) { counts[0] = 0; counts[1] = 0; return 0; }

  // stable order by 16-byte id keeps spans of a trace in payload order
  std::vector<int32_t> order(k);
  for (int64_t i = 0; i < k; i++) order[i] = (int32_t)i;
  const uint8_t* tp = tids.data();
  std::stable_sort(order.begin(), order.end(), [tp](int32_t a, int32_t b) {
    return memcmp(tp + (size_t)a * 16, tp + (size_t)b * 16, 16) < 0;
  });

  // one trace's TracesData body size: same rs/ss-run walk as the emit
  // pass, arithmetic only. [g0, g1) index into `order`.
  auto body_size = [&](int64_t g0, int64_t g1, uint64_t* lo, uint64_t* hi) {
    int64_t body = 0;
    int64_t a = g0;
    while (a < g1) {
      int32_t rs = sp_rs[order[a]];
      int64_t rs_body = rs_elen[rs];
      while (a < g1 && sp_rs[order[a]] == rs) {
        int32_t ss = sp_ss[order[a]];
        int64_t ss_body = ss_elen[ss];
        while (a < g1 && sp_ss[order[a]] == ss) {
          int32_t j = order[a];
          ss_body += 1 + vsize((uint64_t)sp_len[j]) + sp_len[j];
          if (st_ns[j] < *lo) *lo = st_ns[j];
          if (en_ns[j] > *hi) *hi = en_ns[j];
          a++;
        }
        rs_body += 1 + vsize((uint64_t)ss_body) + ss_body;
      }
      body += 1 + vsize((uint64_t)rs_body) + rs_body;
    }
    return body;
  };

  // pass A: total output size + trace count (capacity check up front so
  // the emit pass never has to be abandoned half-written); per-trace
  // results are cached so pass B never re-walks the sizes
  int64_t total_out = 0, n_tr = 0;
  std::vector<int64_t> tr_start, tr_body;
  std::vector<uint64_t> tr_lo, tr_hi;
  for (int64_t i = 0; i < k;) {
    int64_t g0 = i;
    while (i < k && memcmp(tp + (size_t)order[i] * 16,
                           tp + (size_t)order[g0] * 16, 16) == 0)
      i++;
    uint64_t lo = UINT64_MAX, hi = 0;
    int64_t body = body_size(g0, i, &lo, &hi);
    tr_start.push_back(g0);
    tr_body.push_back(body);
    tr_lo.push_back(lo);
    tr_hi.push_back(hi);
    total_out += 9 + body;
    n_tr++;
  }
  if (n_tr > cap_traces || total_out > cap_out) {
    counts[0] = n_tr;
    counts[1] = total_out;
    return 2;
  }

  // pass B: emit
  int64_t out_pos = 0;
  tr_start.push_back(k);  // sentinel: trace u spans order[tr_start[u] : tr_start[u+1]]
  for (int64_t u = 0; u < n_tr; u++) {
    int64_t g0 = tr_start[u], i = tr_start[u + 1];
    int64_t body = tr_body[u];
    uint64_t lo = tr_lo[u], hi = tr_hi[u];
    memcpy(tids_out + (size_t)u * 16, tp + (size_t)order[g0] * 16, 16);
    seg_off[u] = out_pos;
    seg_len[u] = 9 + body;
    uint64_t lo_s = lo == UINT64_MAX ? 0 : lo / 1000000000ull;
    // overflow-free exact ceil(hi / 1e9): end timestamps near 2^64 (the
    // scanner tolerates nonconformant varints) must not wrap to ~0 --
    // the Python oracle computes this with bignums
    uint64_t hi_s = hi ? (hi - 1) / 1000000000ull + 1 : 0;
    start_s_out[u] = (int64_t)lo_s;
    end_s_out[u] = (int64_t)hi_s;
    uint8_t* p = out + out_pos;
    *p++ = 0x01;
    uint32_t w32 = (uint32_t)lo_s;
    memcpy(p, &w32, 4); p += 4;
    w32 = (uint32_t)hi_s;
    memcpy(p, &w32, 4); p += 4;
    int64_t a = g0;
    while (a < i) {
      int32_t rs = sp_rs[order[a]];
      // recompute the run sizes inline (cheap arithmetic; avoids
      // buffering per-run size vectors between passes)
      int64_t rs_body = rs_elen[rs];
      {
        int64_t a2 = a;
        while (a2 < i && sp_rs[order[a2]] == rs) {
          int32_t ss = sp_ss[order[a2]];
          int64_t ss_body = ss_elen[ss];
          while (a2 < i && sp_ss[order[a2]] == ss) {
            ss_body += 1 + vsize((uint64_t)sp_len[order[a2]]) + sp_len[order[a2]];
            a2++;
          }
          rs_body += 1 + vsize((uint64_t)ss_body) + ss_body;
        }
      }
      *p++ = 0x0A;  // TracesData.resource_spans
      vput(&p, (uint64_t)rs_body);
      memcpy(p, env.data() + rs_eoff[rs], (size_t)rs_elen[rs]);
      p += rs_elen[rs];
      while (a < i && sp_rs[order[a]] == rs) {
        int32_t ss = sp_ss[order[a]];
        int64_t ss_body = ss_elen[ss];
        {
          int64_t a2 = a;
          while (a2 < i && sp_ss[order[a2]] == ss) {
            ss_body += 1 + vsize((uint64_t)sp_len[order[a2]]) + sp_len[order[a2]];
            a2++;
          }
        }
        *p++ = 0x12;  // ResourceSpans.scope_spans
        vput(&p, (uint64_t)ss_body);
        memcpy(p, senv.data() + ss_eoff[ss], (size_t)ss_elen[ss]);
        p += ss_elen[ss];
        while (a < i && sp_ss[order[a]] == ss) {
          int32_t j = order[a];
          *p++ = 0x12;  // ScopeSpans.spans
          vput(&p, (uint64_t)sp_len[j]);
          memcpy(p, buf + sp_off[j], (size_t)sp_len[j]);
          p += sp_len[j];
          a++;
        }
      }
    }
    out_pos += 9 + body;
  }
  counts[0] = n_tr;
  counts[1] = out_pos;
  return 0;
}

// ------------------------------------------------------------------- zstd

// Compress n chunks in parallel. in_offsets[i]..+in_lens[i] index into
// src; outputs go to dst at out_offsets (caller sizes dst with
// ZSTD_compressBound per chunk via vtpu_zstd_bound). Returns 0 on
// success; out_lens gets per-chunk compressed sizes.
int64_t vtpu_zstd_bound(int64_t n) { return (int64_t)ZSTD_compressBound((size_t)n); }

int vtpu_zstd_compress_batch(const uint8_t* src, const int64_t* in_offsets,
                             const int64_t* in_lens, uint8_t* dst,
                             const int64_t* out_offsets, int64_t* out_lens,
                             int n_chunks, int level, int n_threads) {
  std::atomic<int> next(0), failed(0);
  auto work = [&]() {
    ZSTD_CCtx* ctx = ZSTD_createCCtx();
    // advanced API: the one-shot ZSTD_compressCCtx treats level <= 0 as
    // "default", silently ignoring the fast negative levels
    ZSTD_CCtx_setParameter(ctx, ZSTD_c_compressionLevel, level);
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n_chunks) break;
      size_t r = ZSTD_compress2(ctx, dst + out_offsets[i],
                                (size_t)(vtpu_zstd_bound(in_lens[i])),
                                src + in_offsets[i], (size_t)in_lens[i]);
      if (ZSTD_isError(r)) {
        failed.store(1);
        break;
      }
      out_lens[i] = (int64_t)r;
    }
    ZSTD_freeCCtx(ctx);
  };
  int nt = std::max(1, std::min(n_threads, n_chunks));
  std::vector<std::thread> ts;
  for (int t = 1; t < nt; t++) ts.emplace_back(work);
  work();  // calling thread is worker 0 (no spawn cost when nt == 1)
  for (auto& t : ts) t.join();
  return failed.load();
}

// Decompress n chunks in parallel into caller-provided slots (exact
// decompressed sizes known from the column footer).
int vtpu_zstd_decompress_batch(const uint8_t* src, const int64_t* in_offsets,
                               const int64_t* in_lens, uint8_t* dst,
                               const int64_t* out_offsets, const int64_t* out_lens,
                               int n_chunks, int n_threads) {
  std::atomic<int> next(0), failed(0);
  auto work = [&]() {
    ZSTD_DCtx* ctx = ZSTD_createDCtx();
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n_chunks) break;
      size_t r = ZSTD_decompressDCtx(ctx, dst + out_offsets[i], (size_t)out_lens[i],
                                     src + in_offsets[i], (size_t)in_lens[i]);
      if (ZSTD_isError(r) || (int64_t)r != out_lens[i]) {
        failed.store(1);
        break;
      }
    }
    ZSTD_freeDCtx(ctx);
  };
  // calling thread is worker 0: single-threaded calls (1-core hosts,
  // small batches) pay zero spawn/join overhead
  int nt = std::max(1, std::min(n_threads, n_chunks));
  std::vector<std::thread> ts;
  for (int t = 1; t < nt; t++) ts.emplace_back(work);
  work();
  for (auto& t : ts) t.join();
  return failed.load();
}

// ----------------------------------------------------- snappy block codec
//
// Hand-rolled snappy + lz4 block codecs (reference: tempodb/backend/
// encoding.go carries both; klauspost's Go implementations are the
// upstream analog). Both are self-contained -- no external library --
// and ship threaded batch entry points shaped exactly like the zstd
// ones above, so the column layer's cold-read pipeline can decompress
// any registered codec's chunk batch on native threads. Formats are the
// standard public ones (snappy raw block framing, lz4 block format), so
// chunks interoperate with any other conformant implementation.

}  // pause extern "C": internal helpers use C++ linkage freely

// snappy raw block format: uvarint uncompressed length, then elements
// tagged by the low 2 bits (00 literal, 01/10/11 copies with 1/2/4-byte
// offsets). Compression works in 64 KiB fragments (like upstream) so a
// 16-bit position table suffices and every copy fits the 2-byte-offset
// form.
static const int kSnHashBits = 14;

static inline uint32_t sn_hash(uint32_t v) { return (v * 0x1e35a7bdu) >> (32 - kSnHashBits); }

static inline uint8_t* sn_emit_literal(uint8_t* p, const uint8_t* s, size_t len) {
  while (len > 0) {
    size_t l = len > 65536 ? 65536 : len;
    size_t n1 = l - 1;
    if (n1 < 60) {
      *p++ = (uint8_t)(n1 << 2);
    } else if (n1 < 256) {
      *p++ = 60 << 2;
      *p++ = (uint8_t)n1;
    } else {
      *p++ = 61 << 2;
      *p++ = (uint8_t)(n1 & 0xff);
      *p++ = (uint8_t)(n1 >> 8);
    }
    memcpy(p, s, l);
    p += l;
    s += l;
    len -= l;
  }
  return p;
}

static inline uint8_t* sn_emit_copy(uint8_t* p, size_t offset, size_t len) {
  while (len > 0) {
    size_t l = len > 64 ? 64 : len;
    *p++ = (uint8_t)(((l - 1) << 2) | 2);  // type 10: 2-byte offset
    *p++ = (uint8_t)(offset & 0xff);
    *p++ = (uint8_t)(offset >> 8);
    len -= l;
  }
  return p;
}

// one 64 KiB fragment: greedy 4-byte hash matching within the fragment
static uint8_t* sn_compress_fragment(const uint8_t* src, size_t n, uint8_t* p,
                                     uint16_t* table) {
  memset(table, 0, sizeof(uint16_t) << kSnHashBits);
  size_t i = 0, lit = 0;
  if (n >= 16) {
    size_t limit = n - 15;
    while (i < limit) {
      uint32_t v;
      memcpy(&v, src + i, 4);
      uint32_t h = sn_hash(v);
      size_t cand = table[h];
      table[h] = (uint16_t)i;
      uint32_t w;
      memcpy(&w, src + cand, 4);
      if (cand < i && w == v) {
        size_t len = 4;
        while (i + len < n && src[cand + len] == src[i + len]) len++;
        p = sn_emit_literal(p, src + lit, i - lit);
        p = sn_emit_copy(p, i - cand, len);
        i += len;
        lit = i;
      } else {
        i++;
      }
    }
  }
  return sn_emit_literal(p, src + lit, n - lit);
}

static size_t snappy_compress_one(const uint8_t* src, size_t n, uint8_t* dst,
                                  uint16_t* table) {
  uint8_t* p = dst;
  uint64_t v = n;
  while (v >= 128) {
    *p++ = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  *p++ = (uint8_t)v;
  for (size_t off = 0; off < n; off += 65536) {
    size_t frag = n - off > 65536 ? 65536 : n - off;
    p = sn_compress_fragment(src + off, frag, p, table);
  }
  return (size_t)(p - dst);
}

static int snappy_decompress_one(const uint8_t* src, size_t n, uint8_t* dst,
                                 size_t dn) {
  size_t pos = 0;
  uint64_t len = 0;
  int shift = 0;
  for (;;) {
    if (pos >= n || shift > 35) return 1;
    uint8_t b = src[pos++];
    len |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  if (len != (uint64_t)dn) return 1;
  size_t d = 0;
  while (pos < n) {
    uint8_t tag = src[pos++];
    int type = tag & 3;
    if (type == 0) {
      size_t l = (size_t)(tag >> 2) + 1;
      if (l > 60) {
        int extra = (int)l - 60;  // 1..4 length bytes, little endian
        if (pos + (size_t)extra > n) return 1;
        l = 0;
        for (int k = 0; k < extra; k++) l |= (size_t)src[pos + k] << (8 * k);
        l += 1;
        pos += (size_t)extra;
      }
      if (pos + l > n || d + l > dn) return 1;
      memcpy(dst + d, src + pos, l);
      pos += l;
      d += l;
      continue;
    }
    size_t l, off;
    if (type == 1) {
      if (pos >= n) return 1;
      l = (size_t)((tag >> 2) & 7) + 4;
      off = ((size_t)(tag >> 5) << 8) | src[pos++];
    } else if (type == 2) {
      if (pos + 2 > n) return 1;
      l = (size_t)(tag >> 2) + 1;
      off = (size_t)src[pos] | ((size_t)src[pos + 1] << 8);
      pos += 2;
    } else {
      if (pos + 4 > n) return 1;
      l = (size_t)(tag >> 2) + 1;
      off = (size_t)src[pos] | ((size_t)src[pos + 1] << 8) |
            ((size_t)src[pos + 2] << 16) | ((size_t)src[pos + 3] << 24);
      pos += 4;
    }
    if (off == 0 || off > d || d + l > dn) return 1;
    const uint8_t* s = dst + d - off;
    if (off >= l) {
      memcpy(dst + d, s, l);
    } else {
      for (size_t k = 0; k < l; k++) dst[d + k] = s[k];  // overlapped RLE copy
    }
    d += l;
  }
  return d == dn ? 0 : 1;
}

// lz4 block format: sequences of [token][lit-ext][literals][2B offset]
// [match-ext]; the final sequence is literals-only. End-of-block rules
// honored: the last match starts >= 12 bytes before the end and never
// covers the last 5 bytes.
static inline uint32_t lz4_hash(uint32_t v) { return (v * 2654435761u) >> 16; }

static size_t lz4_compress_one(const uint8_t* src, size_t n, uint8_t* dst,
                               int32_t* table) {
  memset(table, 0xff, sizeof(int32_t) << 16);  // -1 = empty
  uint8_t* p = dst;
  size_t i = 0, lit = 0;
  if (n > 16) {
    size_t mflimit = n - 12;  // last match must start before here
    while (i < mflimit) {
      uint32_t v;
      memcpy(&v, src + i, 4);
      uint32_t h = lz4_hash(v);
      int32_t cand = table[h];
      table[h] = (int32_t)i;
      uint32_t w = 0;
      if (cand >= 0) memcpy(&w, src + cand, 4);
      if (cand >= 0 && w == v && i - (size_t)cand <= 65535) {
        size_t maxlen = n - 5 - i;  // never cover the last 5 bytes
        size_t len = 4;
        while (len < maxlen && src[(size_t)cand + len] == src[i + len]) len++;
        size_t ll = i - lit, ml = len - 4;
        uint8_t* tok = p++;
        if (ll >= 15) {
          *tok = 0xF0;
          size_t r = ll - 15;
          while (r >= 255) {
            *p++ = 255;
            r -= 255;
          }
          *p++ = (uint8_t)r;
        } else {
          *tok = (uint8_t)(ll << 4);
        }
        memcpy(p, src + lit, ll);
        p += ll;
        size_t off = i - (size_t)cand;
        *p++ = (uint8_t)(off & 0xff);
        *p++ = (uint8_t)(off >> 8);
        if (ml >= 15) {
          *tok |= 0x0F;
          size_t r = ml - 15;
          while (r >= 255) {
            *p++ = 255;
            r -= 255;
          }
          *p++ = (uint8_t)r;
        } else {
          *tok |= (uint8_t)ml;
        }
        i += len;
        lit = i;
      } else {
        i++;
      }
    }
  }
  size_t ll = n - lit;  // final literals-only sequence
  uint8_t* tok = p++;
  if (ll >= 15) {
    *tok = 0xF0;
    size_t r = ll - 15;
    while (r >= 255) {
      *p++ = 255;
      r -= 255;
    }
    *p++ = (uint8_t)r;
  } else {
    *tok = (uint8_t)(ll << 4);
  }
  memcpy(p, src + lit, ll);
  p += ll;
  return (size_t)(p - dst);
}

static int lz4_decompress_one(const uint8_t* src, size_t n, uint8_t* dst,
                              size_t dn) {
  size_t pos = 0, d = 0;
  if (n == 0) return dn == 0 ? 0 : 1;
  while (pos < n) {
    uint8_t tok = src[pos++];
    size_t ll = (size_t)(tok >> 4);
    if (ll == 15) {
      uint8_t b;
      do {
        if (pos >= n) return 1;
        b = src[pos++];
        ll += b;
      } while (b == 255);
    }
    if (pos + ll > n || d + ll > dn) return 1;
    memcpy(dst + d, src + pos, ll);
    pos += ll;
    d += ll;
    if (pos == n) break;  // final literals-only sequence
    if (pos + 2 > n) return 1;
    size_t off = (size_t)src[pos] | ((size_t)src[pos + 1] << 8);
    pos += 2;
    size_t ml = (size_t)(tok & 15);
    if (ml == 15) {
      uint8_t b;
      do {
        if (pos >= n) return 1;
        b = src[pos++];
        ml += b;
      } while (b == 255);
    }
    ml += 4;
    if (off == 0 || off > d || d + ml > dn) return 1;
    const uint8_t* s = dst + d - off;
    if (off >= ml) {
      memcpy(dst + d, s, ml);
    } else {
      for (size_t k = 0; k < ml; k++) dst[d + k] = s[k];
    }
    d += ml;
  }
  return d == dn ? 0 : 1;
}

extern "C" {

// worst-case bounds (callers size dst per chunk, like vtpu_zstd_bound)
int64_t vtpu_snappy_bound(int64_t n) { return 32 + n + n / 6; }
int64_t vtpu_lz4_bound(int64_t n) { return 16 + n + n / 255; }

int vtpu_snappy_compress_batch(const uint8_t* src, const int64_t* in_offsets,
                               const int64_t* in_lens, uint8_t* dst,
                               const int64_t* out_offsets, int64_t* out_lens,
                               int n_chunks, int n_threads) {
  std::atomic<int> next(0);
  auto work = [&]() {
    std::vector<uint16_t> table((size_t)1 << kSnHashBits);
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n_chunks) break;
      out_lens[i] = (int64_t)snappy_compress_one(
          src + in_offsets[i], (size_t)in_lens[i], dst + out_offsets[i],
          table.data());
    }
  };
  int nt = std::max(1, std::min(n_threads, n_chunks));
  std::vector<std::thread> ts;
  for (int t = 1; t < nt; t++) ts.emplace_back(work);
  work();  // calling thread is worker 0 (no spawn cost when nt == 1)
  for (auto& t : ts) t.join();
  return 0;
}

int vtpu_snappy_decompress_batch(const uint8_t* src, const int64_t* in_offsets,
                                 const int64_t* in_lens, uint8_t* dst,
                                 const int64_t* out_offsets,
                                 const int64_t* out_lens, int n_chunks,
                                 int n_threads) {
  std::atomic<int> next(0), failed(0);
  auto work = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n_chunks) break;
      if (snappy_decompress_one(src + in_offsets[i], (size_t)in_lens[i],
                                dst + out_offsets[i], (size_t)out_lens[i])) {
        failed.store(1);
        break;
      }
    }
  };
  int nt = std::max(1, std::min(n_threads, n_chunks));
  std::vector<std::thread> ts;
  for (int t = 1; t < nt; t++) ts.emplace_back(work);
  work();
  for (auto& t : ts) t.join();
  return failed.load();
}

int vtpu_lz4_compress_batch(const uint8_t* src, const int64_t* in_offsets,
                            const int64_t* in_lens, uint8_t* dst,
                            const int64_t* out_offsets, int64_t* out_lens,
                            int n_chunks, int n_threads) {
  std::atomic<int> next(0);
  auto work = [&]() {
    std::vector<int32_t> table((size_t)1 << 16);
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n_chunks) break;
      out_lens[i] = (int64_t)lz4_compress_one(src + in_offsets[i],
                                              (size_t)in_lens[i],
                                              dst + out_offsets[i],
                                              table.data());
    }
  };
  int nt = std::max(1, std::min(n_threads, n_chunks));
  std::vector<std::thread> ts;
  for (int t = 1; t < nt; t++) ts.emplace_back(work);
  work();
  for (auto& t : ts) t.join();
  return 0;
}

int vtpu_lz4_decompress_batch(const uint8_t* src, const int64_t* in_offsets,
                              const int64_t* in_lens, uint8_t* dst,
                              const int64_t* out_offsets,
                              const int64_t* out_lens, int n_chunks,
                              int n_threads) {
  std::atomic<int> next(0), failed(0);
  auto work = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n_chunks) break;
      if (lz4_decompress_one(src + in_offsets[i], (size_t)in_lens[i],
                             dst + out_offsets[i], (size_t)out_lens[i])) {
        failed.store(1);
        break;
      }
    }
  };
  int nt = std::max(1, std::min(n_threads, n_chunks));
  std::vector<std::thread> ts;
  for (int t = 1; t < nt; t++) ts.emplace_back(work);
  work();
  for (auto& t : ts) t.join();
  return failed.load();
}

// ---------------------------------------------------------- run gather

// Copy n_runs row ranges from src to dst: run i moves lens[i] rows from
// src row src_offs[i] to dst row dst_offs[i]; rows are itemsize bytes.
// The compaction merge's unit of data movement (columnar_compact
// _assemble): one memcpy per run instead of a per-ELEMENT numpy fancy
// index, so the index arrays (8 bytes/row/column) never exist and the
// traffic is just src+dst.
void vtpu_gather_runs(const uint8_t* src, uint8_t* dst,
                      const int64_t* src_offs, const int64_t* dst_offs,
                      const int64_t* lens, int64_t n_runs, int64_t itemsize) {
  for (int64_t i = 0; i < n_runs; i++) {
    memcpy(dst + dst_offs[i] * itemsize, src + src_offs[i] * itemsize,
           (size_t)(lens[i] * itemsize));
  }
}

// Same, but each run reads from an absolute source ADDRESS: callers
// with K source arrays order runs by destination (dst writes stream
// sequentially, each source reads stream too) and pass per-run
// src pointers computed host-side. dst_offs/lens in rows.
// K-way merges read each run from a RANDOM source position while dst
// streams sequentially: per-run cost is one DRAM round trip (~100 ns),
// which dominates the copy itself for trace-axis runs (one 4-byte row).
// Prefetching a few runs ahead overlaps those misses.
#define VTPU_RUN_PREFETCH 8

void vtpu_gather_runs_addr(const int64_t* src_addrs, uint8_t* dst,
                           const int64_t* dst_offs, const int64_t* lens,
                           int64_t n_runs, int64_t itemsize) {
  // runs are typically a handful of rows (one trace's spans; ONE row on
  // the trace axis) -- glibc memcpy's dispatch overhead dominates at
  // that size, so 4/8-byte rows take a plain word loop instead
  if (itemsize == 4) {
    uint32_t* d32 = (uint32_t*)dst;
    for (int64_t i = 0; i < n_runs; i++) {
      if (i + VTPU_RUN_PREFETCH < n_runs)
        __builtin_prefetch((const void*)(uintptr_t)src_addrs[i + VTPU_RUN_PREFETCH], 0, 1);
      const uint32_t* s = (const uint32_t*)(uintptr_t)src_addrs[i];
      uint32_t* d = d32 + dst_offs[i];
      int64_t n = lens[i];
      for (int64_t j = 0; j < n; j++) d[j] = s[j];
    }
    return;
  }
  if (itemsize == 8) {
    uint64_t* d64 = (uint64_t*)dst;
    for (int64_t i = 0; i < n_runs; i++) {
      if (i + VTPU_RUN_PREFETCH < n_runs)
        __builtin_prefetch((const void*)(uintptr_t)src_addrs[i + VTPU_RUN_PREFETCH], 0, 1);
      const uint64_t* s = (const uint64_t*)(uintptr_t)src_addrs[i];
      uint64_t* d = d64 + dst_offs[i];
      int64_t n = lens[i];
      for (int64_t j = 0; j < n; j++) d[j] = s[j];
    }
    return;
  }
  for (int64_t i = 0; i < n_runs; i++) {
    if (i + VTPU_RUN_PREFETCH < n_runs)
      __builtin_prefetch((const void*)(uintptr_t)src_addrs[i + VTPU_RUN_PREFETCH], 0, 1);
    memcpy(dst + dst_offs[i] * itemsize, (const void*)(uintptr_t)src_addrs[i],
           (size_t)(lens[i] * itemsize));
  }
}

// Gather runs of an int32 code column while remapping codes through a
// lookup table (negative codes = "absent" sentinels pass through):
// compaction's dictionary re-encode fused into the merge copy, so the
// remap costs no extra memory pass. remap_addrs[i]/remap_lens[i] give
// run i's source remap table. Returns the count of out-of-range codes
// (corrupt input); non-zero means the caller must redo via its checked
// fallback -- the kernel writes such codes through unchanged rather
// than reading past the table.
int64_t vtpu_gather_runs_remap(const int64_t* src_addrs, int32_t* dst,
                               const int64_t* dst_offs, const int64_t* lens,
                               const int64_t* remap_addrs,
                               const int64_t* remap_lens, int64_t n_runs) {
  int64_t oob = 0;
  for (int64_t i = 0; i < n_runs; i++) {
    if (i + VTPU_RUN_PREFETCH < n_runs)
      __builtin_prefetch((const void*)(uintptr_t)src_addrs[i + VTPU_RUN_PREFETCH], 0, 1);
    const int32_t* s = (const int32_t*)(uintptr_t)src_addrs[i];
    const int32_t* remap = (const int32_t*)(uintptr_t)remap_addrs[i];
    const int64_t rlen = remap_lens[i];
    int32_t* d = dst + dst_offs[i];
    int64_t n = lens[i];
    for (int64_t j = 0; j < n; j++) {
      int32_t v = s[j];
      if (v >= 0) {
        if (v < rlen) {
          d[j] = remap[v];
        } else {
          d[j] = v;
          oob++;
        }
      } else {
        d[j] = v;
      }
    }
  }
  return oob;
}

// ------------------------------------------------------------ search eval
//
// Host filter primitives for the one-shot/cold search engine
// (ops/hostfilter.py): single-pass C loops replacing multi-pass numpy
// (mask materialization + astype + concatenate + reduceat). The repo's
// counterpart of the reference's hand-tuned parquetquery predicate
// loops (pkg/parquetquery/predicates.go), shaped for a 1-2 core host
// feeding a TPU: memory-bandwidth-bound streaming, no allocation.

// op codes shared with tempo_tpu/native/__init__.py mask_cmp()
enum { CMP_EQ = 0, CMP_NE, CMP_LT, CMP_LE, CMP_GT, CMP_GE, CMP_RANGE, CMP_NE_PRESENT };

}  // pause extern "C": templates cannot carry C language linkage

template <typename T>
static inline void mask_cmp_t(const T* x, int64_t n, int op, int64_t a64,
                              int64_t b64, uint8_t* out) {
  const T a = (T)a64, b = (T)b64;
  switch (op) {
    case CMP_EQ: for (int64_t i = 0; i < n; i++) out[i] = x[i] == a; break;
    case CMP_NE: for (int64_t i = 0; i < n; i++) out[i] = x[i] != a; break;
    case CMP_LT: for (int64_t i = 0; i < n; i++) out[i] = x[i] < a; break;
    case CMP_LE: for (int64_t i = 0; i < n; i++) out[i] = x[i] <= a; break;
    case CMP_GT: for (int64_t i = 0; i < n; i++) out[i] = x[i] > a; break;
    case CMP_GE: for (int64_t i = 0; i < n; i++) out[i] = x[i] >= a; break;
    case CMP_RANGE:
      for (int64_t i = 0; i < n; i++) out[i] = x[i] >= a && x[i] <= b;
      break;
    case CMP_NE_PRESENT:
      for (int64_t i = 0; i < n; i++) out[i] = x[i] != a && x[i] >= 0;
      break;
  }
}

extern "C" {

void vtpu_mask_cmp_i32(const int32_t* x, int64_t n, int op, int64_t a,
                       int64_t b, uint8_t* out) {
  mask_cmp_t<int32_t>(x, n, op, a, b, out);
}

void vtpu_mask_cmp_i64(const int64_t* x, int64_t n, int op, int64_t a,
                       int64_t b, uint8_t* out) {
  mask_cmp_t<int64_t>(x, n, op, a, b, out);
}

// res->span mask through a lookup table: out[j] = lut[idx[j]] for valid
// indices, 0 for negative/out-of-range (absent-resource sentinel).
void vtpu_mask_lut_i32(const int32_t* idx, int64_t n, const uint8_t* lut,
                       int64_t n_lut, uint8_t* out) {
  for (int64_t j = 0; j < n; j++) {
    const int32_t v = idx[j];
    out[j] = ((uint32_t)v < (uint32_t)n_lut) ? lut[v] : 0;
  }
}

// Matched spans per trace: out[t] = sum(mask[off[t] .. off[t+1])), with
// offsets clipped to n_spans (sliced row-group shards clip trailing
// offsets legally).
void vtpu_seg_count_mask(const uint8_t* mask, const int32_t* span_off,
                         int64_t n_traces, int64_t n_spans, int32_t* out) {
  for (int64_t t = 0; t < n_traces; t++) {
    int64_t lo = span_off[t], hi = span_off[t + 1];
    if (lo > n_spans) lo = n_spans;
    if (hi > n_spans) hi = n_spans;
    int32_t c = 0;
    for (int64_t j = lo; j < hi; j++) c += mask[j];
    out[t] = c;
  }
}

// Weighted variant: rows carry fold weights (the tres membership axis,
// where each entry stands for weight[j] spans -- db/search._host_eval).
// Replaces numpy's pad+reduceat, which costs ~5x this linear scan.
void vtpu_seg_weighted_count(const uint8_t* mask, const int32_t* weights,
                             const int32_t* span_off, int64_t n_traces,
                             int64_t n_spans, int64_t* out) {
  for (int64_t t = 0; t < n_traces; t++) {
    int64_t lo = span_off[t], hi = span_off[t + 1];
    if (lo > n_spans) lo = n_spans;
    if (hi > n_spans) hi = n_spans;
    int64_t c = 0;
    for (int64_t j = lo; j < hi; j++) c += mask[j] ? weights[j] : 0;
    out[t] = c;
  }
}

// --------------------------------------------------------- span metrics

// Fused span-metrics fold (the metrics-generator's per-collection
// reduce): one pass scattering into per-series histogram + latency-sum
// accumulators. The (series x bucket) table is ~KBs, so the random
// scatters stay in cache; bucket search is a linear scan (<= ~16
// edges, branch-predictable). Matches numpy's
// searchsorted(edges, dur, side='left') bucketing exactly.
void vtpu_span_metrics(const int32_t* sid, const float* dur, int64_t n,
                       const float* edges, int n_edges, int64_t n_series,
                       int64_t* hist, double* lat_sum) {
  const int nb = n_edges + 1;
  for (int64_t i = 0; i < n; i++) {
    const int32_t s = sid[i];
    if ((uint64_t)s >= (uint64_t)n_series) continue;
    const float d = dur[i];
    int b = 0;
    // !(d <= e) instead of (d > e): NaN then falls through to the LAST
    // bucket, matching searchsorted's "NaN sorts after everything"
    while (b < n_edges && !(d <= edges[b])) b++;
    hist[(int64_t)s * nb + b]++;
    lat_sum[s] += (double)d;
  }
}

// ------------------------------------------------------- dictionary union

// K-way merge of K SORTED string tables (compaction's dictionary union,
// the role of the reference's per-row dictionary re-encode in
// vparquet/compactor.go). Inputs are flattened: source i has counts[i]
// strings; its offsets (counts[i]+1 uint32, 0-based into its own blob)
// start at off_starts[i] in all_offsets, its blob at blob_starts[i] in
// all_blobs. Outputs: merged offsets/blob (caller-allocated at summed
// capacity) and, for every input string in source order, its code in
// the merged table (the per-source remap gather compaction applies to
// every code column). Returns the merged string count, or -1 on error.
int64_t vtpu_dict_union(int64_t n_src, const int64_t* counts,
                        const uint32_t* all_offsets, const int64_t* off_starts,
                        const uint8_t* all_blobs, const int64_t* blob_starts,
                        uint32_t* out_offsets, uint8_t* out_blob,
                        int32_t* remap_flat, const int64_t* remap_starts,
                        int64_t* out_blob_len) {
  struct Head {
    const uint8_t* p;
    uint32_t len;
    int32_t src;
    int64_t idx;
  };
  auto str_at = [&](int64_t s, int64_t i, uint32_t* len) -> const uint8_t* {
    const uint32_t* offs = all_offsets + off_starts[s];
    *len = offs[i + 1] - offs[i];
    return all_blobs + blob_starts[s] + offs[i];
  };
  auto less = [](const Head& a, const Head& b) {
    // min-heap by string (then source for stability): std::push_heap
    // builds a max-heap, so invert
    int c = memcmp(a.p, b.p, a.len < b.len ? a.len : b.len);
    if (c != 0) return c > 0;
    if (a.len != b.len) return a.len > b.len;
    return a.src > b.src;
  };
  std::vector<Head> heap;
  heap.reserve((size_t)n_src);
  for (int64_t s = 0; s < n_src; s++) {
    if (counts[s] > 0) {
      Head h;
      h.p = str_at(s, 0, &h.len);
      h.src = (int32_t)s;
      h.idx = 0;
      heap.push_back(h);
    }
  }
  std::make_heap(heap.begin(), heap.end(), less);
  int64_t n_out = 0, blob_pos = 0;
  const uint8_t* last_p = nullptr;
  uint32_t last_len = 0;
  out_offsets[0] = 0;
  while (!heap.empty()) {
    std::pop_heap(heap.begin(), heap.end(), less);
    Head h = heap.back();
    heap.pop_back();
    bool is_dup = last_p != nullptr && h.len == last_len &&
                  memcmp(h.p, last_p, h.len) == 0;
    if (!is_dup) {
      memcpy(out_blob + blob_pos, h.p, h.len);
      blob_pos += h.len;
      n_out++;
      out_offsets[n_out] = (uint32_t)blob_pos;
      last_p = h.p;
      last_len = h.len;
    }
    remap_flat[remap_starts[h.src] + h.idx] = (int32_t)(n_out - 1);
    if (h.idx + 1 < counts[h.src]) {
      Head nh;
      nh.p = str_at(h.src, h.idx + 1, &nh.len);
      nh.src = h.src;
      nh.idx = h.idx + 1;
      heap.push_back(nh);
      std::push_heap(heap.begin(), heap.end(), less);
    }
  }
  *out_blob_len = blob_pos;
  return n_out;
}

}  // extern "C"
