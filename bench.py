"""BASELINE-config benchmarks, one JSON line each; the LAST line is the
headline END-TO-END search (IO + zstd decode + device staging + filter +
verify), the honest comparable to the reference's 0.18 s vParquet
full-block search that *includes* local-SSD IO
(docs/design-proposals/2022-04 Parquet.md:233-241 => 57.8 M spans/s).

Lines, in order:
  1. traceql_filter_kernel_spans_per_sec_per_chip -- device-resident
     filter kernel only (ceiling metric; no IO/staging).
  1b. search_mesh_1x1_overhead -- the stacked shard_map search program
     vs the plain kernel on a 1x1 mesh, both legs on device-resident
     columns (ROADMAP 2a): the fixed smap/stacking price mesh routing
     must amortize, with the costmodel walker's per-collective comm
     bytes attached (all zero on 1x1 by the ring model).
  2. find_trace_by_id_p50_ms -- BASELINE config #1: trace-ID lookup on a
     local-disk block via the production device Find path (bloom read +
     batched bisection kernel + row materialization).
  2b. find_auto_crossover_rows -- the committed device-vs-host find
     race (ops/find.calibrate_find): both engines timed on the same
     block set, the crossover written to a CostLedger artifact, and the
     `auto` policy proven to route from it (reason ledger_crossover).
  2c. first_query_compile_p99_ms -- cold-process first-query latency
     (the XLA first-compile storm) with and without the persistent
     compilation cache (TEMPO_COMPILE_CACHE_DIR), each sample a fresh
     interpreter.
  3. compaction_mb_per_sec -- BASELINE config #4 shape: level-0->1
     columnar compaction of many small blocks, MB/s of input consumed.
  4. ingest_otlp_mb_per_sec -- raw-bytes OTLP write path (native scan +
     splice + columnar WAL windows), vs the reference's 15 MB/s
     per-tenant rate-limit default; the row carries a per-stage
     breakdown (decode / wal_append / stage_delta / cut / flush ms)
     read from the kerneltel ingest ledger.
  5. spanmetrics_reduce_spans_per_sec -- BASELINE config #5: span-metrics
     segmented reduce (calls + latency sum + histogram) on device.
  5a. spanmetrics_streaming_spans_per_sec / service_graph_edges_per_sec
     -- the streaming metrics-generator plane (PR-17): coded windows
     through push_window (packed-key series assembly + device reduce)
     and client/server pairing through the coded edge store + fused
     edge reduce; the edge row's tel proves the distributor tap costs
     zero extra proto decodes (columnar cache counters).
  5b. search_concurrent_p50_ms -- Q parallel identical-shape queries on
     one hot block through the cross-query batching executor
     (db/batchexec): p50/p95 latency, launches-per-query, occupancy.
  5b2. search_mesh_batched -- one admission window's 16 queries as ONE
     Q-programs x sharded-rows mesh launch (parallel/multiquery) vs 16
     sequential mesh launches (wall ratio; launches/query, occupancy
     and walker comm bytes/query attached), in an 8-virtual-device
     subprocess; search_struct_comm_shrink rides along -- the
     walker-priced per-struct-node collective before/after the
     bit-packed + hoisted gathers (>= 5x is the acceptance gate).
  5c. search_affinity_p99_ms -- the cache-affinity differential: 3
     simulated querier workers (each its own TempoDB = its own staged-
     cache domain), 4 tenants, 50 concurrent Zipf-mixed searches, HBM
     budget pinched to ~1.35x one fleet copy; p99 + staged-cache hit
     rate with affinity routing on vs off, and the re-upload bytes
     affinity avoided.
  6. search_block_e2e_cold_spans_per_sec -- BASELINE config #2, fresh
     reader each query: every byte from disk + staged to device through
     the cold-read streaming pipeline (ops/stream); the row carries
     per-stage ms and the overlap ratio.
  6b. search_block_e2e_cold_find_p50_ms -- trace-ID lookup with fresh
     readers per query: bloom shard, trace index and the trace's
     row-group chunks all come from disk through the pipeline's
     plan -> ranged-fetch -> threaded-decode stages.
  7. search_block_e2e_spans_per_sec -- BASELINE config #2 (headline):
     hot immutable block, staged device arrays cached (the production
     querier pattern; the reference's hot path re-decodes parquet from
     the OS page cache each query).

vs_baseline semantics: for the kernel and e2e search lines it is the
ratio to the reference's 57.8 M spans/s (IO-inclusive), passed
explicitly. Every OTHER row resolves against BASELINE.json's
"published" map -- committed values from prior bench rounds (the
reference publishes no figures for find p50 / compaction MB/s /
span-metrics, so the committed round IS the comparable; direction-aware
so >1 always means improvement). Rows with a null published value
(calibration rows, rows awaiting their first committed round) report
0.0; a row MISSING from the map warns on stderr so it can't ship
baseline-less forever.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

BASELINE_SPANS_PER_SEC = 10.4e6 / 0.18  # reference vParquet search, IO incl.

# committed per-metric baselines (BASELINE.json "published"): rows whose
# comparable is a prior committed bench round rather than a reference
# paper figure resolve vs_baseline here. direction says which way is
# better ("higher" throughput vs "lower" latency) so the ratio always
# reads >1 = improvement. A null value = "intentionally no baseline yet"
# (calibration rows); a MISSING metric key warns on stderr, so a new
# bench row can't silently ship with vs_baseline 0.0 forever.
def _load_published() -> dict:
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as f:
            return json.load(f).get("published", {})
    except Exception as e:
        print(f"bench: BASELINE.json unreadable ({e}); "
              "all unpublished rows report vs_baseline 0.0", file=sys.stderr)
        return {}


_PUBLISHED = _load_published()


def _baseline_ratio(metric: str, value: float) -> float:
    ent = _PUBLISHED.get(metric)
    if ent is None:
        print(f"bench: WARNING metric {metric!r} has no BASELINE.json "
              "published entry (add one, or a null-value placeholder)",
              file=sys.stderr)
        return 0.0
    base = ent.get("value")
    if not base or value <= 0:
        return 0.0
    return (value / base if ent.get("direction", "higher") == "higher"
            else base / value)

# peak HBM bandwidth per chip, for the kernel roofline line
# (vs_baseline = fraction of peak). v5e: 819 GB/s; axon is the tunneled
# TPU platform this box exposes. Unknown platforms (cpu) report 0.
# NOTE: the fraction CAN exceed 1.0 -- the bytes model counts every
# input column per iteration, but across a batch of back-to-back
# queries XLA keeps hot columns resident on-chip (VMEM), so the kernel
# reads HBM less than once per query. >1.0 therefore means "serving
# from on-chip memory", not a measurement error.
_HBM_PEAK_BPS = {"tpu": 819e9, "axon": 819e9}


def adaptive_min(sample, base: int, cap: int) -> float:
    """ONE definition of the stop policy every metric shares: take at
    least `base` samples, keep sampling while the minimum improves >2%
    (a noisy patch squeezes real windows out), stop at `cap`.
    sample() -> seconds for one run."""
    times: list[float] = []
    for i in range(cap):
        dt = sample()
        improved = not times or dt < min(times) * 0.98
        times.append(dt)
        if i + 1 >= base and not improved:
            break
    return min(times)


def best_window(fn, windows: int = 3, max_windows: int | None = None):
    """Best (minimum) wall time of fn() runs -- timeit's rationale: this
    box is a shared core whose neighbors can eat an entire timing
    window; contention only ever adds time, so the best window measures
    the engine and the others measure the neighbors."""

    def sample() -> float:
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    return adaptive_min(sample, windows, max_windows or 2 * windows)


def _tel_mark() -> tuple[int, float, float]:
    """Kernel-telemetry mark: (compiles, device_seconds, wall_t0). Take
    one per measured section; _emit(tel=mark) folds the deltas into the
    bench row so the perf trajectory separates compile cost from
    steady-state device time."""
    from tempo_tpu.util.kerneltel import TEL

    c, d = TEL.totals()
    return c, d, time.perf_counter()


def _tel_close(mark: tuple[int, float, float], workers: int = 1) -> dict:
    """Close a telemetry section at its end (call BEFORE unrelated work
    runs): compile count + share of the section's wall time the device
    spent executing (under sync timing; dispatch share otherwise) --
    distinguishes "slow because recompiling" from "slow kernel".

    `workers`: concurrent threads driving the device inside the section.
    Device seconds accumulate ACROSS threads while wall time doesn't, so
    a Q-wide concurrent section must divide by Q x wall or the share
    reads as Q-ish (BENCH_r06's search_concurrent reported 3.85)."""
    from tempo_tpu.util.kerneltel import TEL

    c0, d0, t0 = mark
    c1, d1 = TEL.totals()
    wall = (time.perf_counter() - t0) * max(1, workers)
    return {"compiles": c1 - c0,
            "device_time_share": round((d1 - d0) / wall, 4) if wall > 0 else 0.0}


def _emit(metric: str, value: float, unit: str,
          vs_baseline: float | None = None,
          tel: dict | tuple | None = None) -> None:
    if vs_baseline is None:  # resolve from the committed published map
        vs_baseline = _baseline_ratio(metric, float(value))
    row = {
        "metric": metric,
        "value": round(float(value), 4),
        "unit": unit,
        "vs_baseline": round(float(vs_baseline), 3),
    }
    if tel is not None:
        row.update(_tel_close(tel) if isinstance(tel, tuple) else tel)
    print(json.dumps(row), flush=True)


# ------------------------------------------------------------------ synth
def _trace_local_res(rng: np.random.Generator, n_traces: int, spans_per: int,
                     n_res: int) -> np.ndarray:
    """Per-span resource indices with per-trace locality: each trace
    draws 2-4 resources and its spans choose among them."""
    k = 4  # palette size per trace (first 2 always used, rest maybe)
    palette = rng.integers(0, n_res, size=(n_traces, k))
    pick = rng.integers(0, k, size=(n_traces, spans_per))
    pick = np.minimum(pick, rng.integers(1, k, size=(n_traces, 1)))
    return np.take_along_axis(palette, pick, axis=1).reshape(-1).astype(np.int32)


def synth_block(backend, tenant: str, rng: np.random.Generator, n_traces: int,
                spans_per: int, n_res: int = 1024, attrs_per_span: int = 2):
    """Fast numpy construction of a realistic vtpu block (same column set
    the builder emits; conformance-tested in tests/test_bench_synth.py).
    The bench measures the READ side; wire-object building would only
    measure Python."""
    from tempo_tpu.block import schema as S
    from tempo_tpu.block.bloom import ShardedBloom
    from tempo_tpu.block.builder import FinalizedBlock, compute_row_groups, write_block
    from tempo_tpu.block.dictionary import Dictionary
    from tempo_tpu.block.meta import BlockMeta

    keys = [f"attr.key{i:03d}" for i in range(100)]
    vals = [f"value-{i:05d}" for i in range(5000)]
    svcs = [f"svc-{i:03d}" for i in range(64)]
    ops = [f"op-{i:04d}" for i in range(512)]
    strings = sorted({"", *keys, *vals, *svcs, *ops})
    code = {s: i for i, s in enumerate(strings)}
    codes_of = lambda lst: np.asarray([code[s] for s in lst], np.int32)  # noqa: E731
    key_codes, val_codes = codes_of(keys), codes_of(vals)
    svc_codes, op_codes = codes_of(svcs), codes_of(ops)

    n_spans = n_traces * spans_per
    ids = rng.integers(0, 256, size=(n_traces, 16), dtype=np.uint8)
    u = ids.view(">u8").astype(np.uint64).reshape(n_traces, 2)
    order = np.lexsort((u[:, 1], u[:, 0]))
    ids = np.ascontiguousarray(ids[order])
    id_codes = (ids.view(">u4").astype(np.int64) - 0x80000000).astype(np.int32).reshape(n_traces, 4)

    span_off = (np.arange(n_traces + 1, dtype=np.int64) * spans_per).astype(np.int32)
    base_ns = 1_700_000_000_000_000_000
    start_ns = (base_ns + rng.integers(0, 3_600_000_000_000, size=n_spans)).astype(np.uint64)
    dur_us = rng.integers(10, 1_000_000, size=n_spans).astype(np.int32)
    end_ns = (start_ns.astype(np.int64) + dur_us.astype(np.int64) * 1_000).astype(np.uint64)
    tmin = np.minimum.reduceat(start_ns.astype(np.int64), span_off[:-1])
    tmax = np.maximum.reduceat(end_ns.astype(np.int64), span_off[:-1])
    blk_base = int(start_ns.min())

    span_ids = rng.integers(0, 256, size=(n_spans, 8), dtype=np.uint8)
    sat_owner = np.repeat(np.arange(n_spans, dtype=np.int32), attrs_per_span)
    n_sat = sat_owner.shape[0]
    e_i32 = np.empty(0, np.int32)

    cols = {
        "span.trace_sid": np.repeat(np.arange(n_traces, dtype=np.int32), spans_per),
        "span.name_id": rng.choice(op_codes, size=n_spans).astype(np.int32),
        "span.service_id": np.full(n_spans, -1, np.int32),
        "span.kind": rng.integers(1, 6, size=n_spans).astype(np.int32),
        "span.status": (rng.random(n_spans) < 0.05).astype(np.int32) * 2,
        "span.start_ms": ((start_ns.astype(np.int64) - blk_base) // 1_000_000).astype(np.int32),
        "span.dur_us": dur_us,
        "span.dur_lo": np.zeros(n_spans, np.int32),
        "span.http_status": rng.choice(np.asarray([200, 200, 200, 404, 500], np.int32), size=n_spans),
        "span.http_method_id": np.full(n_spans, -1, np.int32),
        "span.http_url_id": np.full(n_spans, -1, np.int32),
        # realistic resource locality: a trace's spans come from a
        # handful of services (2-4 resources per trace), the shape the
        # reference's nested ResourceSpans model assumes -- NOT one
        # random resource per span, which no tracing workload produces
        "span.res_idx": _trace_local_res(rng, n_traces, spans_per, n_res),
        "span.start_ns": start_ns,
        "span.end_ns": end_ns,
        "span.id": span_ids,
        # simple chain topology: span k's parent is span k-1 of the same
        # trace (first span is the root) -- gives structural queries a
        # real tree to walk; parent_id bytes mirror parent_idx so host
        # verification over materialized traces agrees with the device
        "span.parent_id": np.where(
            (np.arange(n_spans) % spans_per == 0)[:, None],
            np.zeros((1, 8), np.uint8), np.roll(span_ids, 1, axis=0)),
        "span.parent_idx": np.where(
            np.arange(n_spans, dtype=np.int32) % spans_per == 0,
            np.int32(-1), np.arange(n_spans, dtype=np.int32) - 1),
        "span.trace_state_id": np.zeros(n_spans, np.int32),
        "span.status_msg_id": np.zeros(n_spans, np.int32),
        "span.dropped_attrs": np.zeros(n_spans, np.int32),
        "span.scope_idx": np.zeros(n_spans, np.int32),
        "trace.id": ids,
        "trace.id_codes": id_codes,
        "trace.span_off": span_off,
        "trace.start_ms": ((tmin - blk_base) // 1_000_000).astype(np.int32),
        "trace.end_ms": ((tmax - blk_base) // 1_000_000).astype(np.int32),
        "trace.dur_us": np.clip((tmax - tmin) // 1_000, 0, 2**31 - 1).astype(np.int32),
        "trace.dur_lo": np.zeros(n_traces, np.int32),
        "trace.root_service_id": rng.choice(svc_codes, size=n_traces).astype(np.int32),
        "trace.root_name_id": rng.choice(op_codes, size=n_traces).astype(np.int32),
        "trace.start_ns": tmin.astype(np.uint64),
        "trace.end_ns": tmax.astype(np.uint64),
        "scope.name_id": np.zeros(1, np.int32),
        "scope.version_id": np.zeros(1, np.int32),
        "ev.span": e_i32, "ev.time_ns": np.empty(0, np.uint64),
        "ev.name_id": e_i32, "ev.dropped": e_i32,
        "ln.span": e_i32, "ln.trace_id": np.empty((0, 16), np.uint8),
        "ln.span_id": np.empty((0, 8), np.uint8), "ln.state_id": e_i32,
        **{f"{p}.{f}": np.empty(0, dt)
           for p, owner in (("evattr", "ev"), ("lnattr", "ln"))
           for f, dt in ((owner, np.int32), ("key_id", np.int32), ("vtype", np.int32),
                         ("str_id", np.int32), ("int32", np.int32), ("f32", np.float32),
                         ("int64", np.int64), ("f64", np.float64))},
        "sattr.span": sat_owner,
        "sattr.key_id": rng.choice(key_codes, size=n_sat).astype(np.int32),
        "sattr.vtype": np.zeros(n_sat, np.int32),
        "sattr.str_id": rng.choice(val_codes, size=n_sat).astype(np.int32),
        "sattr.int32": np.zeros(n_sat, np.int32),
        "sattr.f32": np.zeros(n_sat, np.float32),
        "sattr.int64": np.zeros(n_sat, np.int64),
        "sattr.f64": np.zeros(n_sat, np.float64),
        "rattr.res": np.arange(n_res, dtype=np.int32),
        "rattr.key_id": np.full(n_res, key_codes[0], np.int32),
        "rattr.vtype": np.zeros(n_res, np.int32),
        "rattr.str_id": rng.choice(val_codes, size=n_res).astype(np.int32),
        "rattr.int32": np.zeros(n_res, np.int32),
        "rattr.f32": np.zeros(n_res, np.float32),
        "rattr.int64": np.zeros(n_res, np.int64),
        "rattr.f64": np.zeros(n_res, np.float64),
    }
    for col in sorted(set(S.WELL_KNOWN_RES_ATTRS.values())):
        if col == "res.service_id":
            cols[col] = rng.choice(svc_codes, size=n_res).astype(np.int32)
        else:
            cols[col] = np.full(n_res, -1, np.int32)
    from tempo_tpu.block.builder import build_tres

    cols.update(build_tres(cols["span.trace_sid"], cols["span.res_idx"], n_traces))

    axes, col_axis, row_groups = compute_row_groups(
        cols, cols["span.start_ms"], cols["span.dur_us"], S.DEFAULT_ROW_GROUP_SPANS
    )
    m = BlockMeta.new(tenant)
    m.total_traces, m.total_spans = n_traces, n_spans
    m.min_id, m.max_id = ids[0].tobytes().hex(), ids[-1].tobytes().hex()
    m.start_time_unix_nano = blk_base
    m.end_time_unix_nano = int(end_ns.max())
    m.dict_size = len(strings)
    m.row_groups = row_groups
    bloom = ShardedBloom.for_estimated_items(n_traces)
    bloom.add_many([ids[i].tobytes() for i in range(n_traces)])
    m.bloom_shards, m.bloom_shard_bits = bloom.n_shards, bloom.shard_bits
    fin = FinalizedBlock(m, cols, axes, col_axis, Dictionary(strings), bloom)
    return write_block(backend, fin), ids


# ------------------------------------------------------------ benchmarks
def bench_analysis() -> None:
    """Static-checker cost, tracked beside kernel perf: the tier-1 gate
    runs on every CI pass, so its wall time is part of the build budget.
    The row carries rule and file counts so a scan-scope regression
    (rules silently skipping files) shows up as a trend break."""
    from tempo_tpu.analysis import RULES, default_root, run_analysis

    t0 = time.perf_counter()
    report = run_analysis(default_root())
    wall_ms = (time.perf_counter() - t0) * 1e3
    _emit("static_analysis_ms", wall_ms, "ms",
          tel={"rules": len(RULES), "files_scanned": report.files_scanned,
               "findings": len(report.findings),
               "suppressed": report.suppressed,
               "family_ms": {k: round(v, 1)
                             for k, v in sorted(report.family_ms.items())}})


def bench_kernel() -> None:
    import jax
    import jax.numpy as jnp

    from tempo_tpu.ops.filter import Cond, Operands, T_RES, T_SATTR, T_SPAN, eval_block

    rng = np.random.default_rng(42)
    N_SPANS, N_TRACES, N_RES = 1 << 22, 1 << 17, 1 << 10
    N_SATTR = N_SPANS * 2
    cols = {
        "span.trace_sid": rng.integers(0, N_TRACES, size=N_SPANS).astype(np.int32),
        "span.dur_us": rng.integers(0, 1_000_000, size=N_SPANS).astype(np.int32),
        "span.res_idx": rng.integers(0, N_RES, size=N_SPANS).astype(np.int32),
        "res.service_id": rng.integers(0, 64, size=N_RES).astype(np.int32),
        "sattr.span": np.sort(rng.integers(0, N_SPANS, size=N_SATTR)).astype(np.int32),
        "sattr.key_id": rng.integers(0, 100, size=N_SATTR).astype(np.int32),
        "sattr.vtype": np.zeros(N_SATTR, dtype=np.int32),
        "sattr.str_id": rng.integers(0, 5_000, size=N_SATTR).astype(np.int32),
    }
    dcols = {k: jax.device_put(jnp.asarray(v)) for k, v in cols.items()}
    conds = (
        Cond(target=T_RES, col="res.service_id", op="eq"),
        Cond(target=T_SPAN, col="span.dur_us", op="ge"),
        Cond(target=T_SATTR, col="str", op="eq"),
    )
    tree = ("and", ("cond", 0), ("cond", 1), ("cond", 2))

    def run(svc, dur, key, val):
        operands = Operands.build(
            [(0, svc, 0, 0.0, 0.0), (0, dur, 0, 0.0, 0.0), (key, val, 0, 0.0, 0.0)]
        )
        return eval_block((tree, conds), dcols, operands, N_SPANS, N_TRACES,
                          N_SPANS, N_RES, N_TRACES)

    mark = _tel_mark()
    jax.block_until_ready(run(1, 500_000, 3, 17))
    iters = 10

    def window():
        for i in range(iters):
            out = run(i % 64, 400_000 + i, i % 100, i % 5_000)
        jax.block_until_ready(out)

    # windows are ~0.1 s here, so sample generously: the kernel line is
    # the ceiling metric and must not record a neighbor's timeslice
    dt = best_window(window, windows=6, max_windows=15)
    tel = _tel_close(mark)
    sps = N_SPANS * iters / dt
    _emit("traceql_filter_kernel_spans_per_sec_per_chip", sps, "spans/s",
          sps / BASELINE_SPANS_PER_SEC, tel=tel)
    # roofline accounting: unique input column bytes the query touches
    # per iteration / kernel time, as a fraction of the chip's peak HBM
    # bandwidth -- says whether the kernel is near the memory roofline
    # or leaving headroom (the spans/s line alone has no denominator)
    bytes_touched = sum(v.nbytes for v in cols.values())
    bps = bytes_touched * iters / dt
    peak = _HBM_PEAK_BPS.get(jax.devices()[0].platform, 0.0)
    _emit("traceql_filter_kernel_bytes_per_sec", bps, "B/s",
          bps / peak if peak else 0.0, tel=tel)


def bench_mesh_1x1_overhead() -> None:
    """ROADMAP item 2a: what the stacked shard_map search program COSTS
    over the plain single-block kernel when the mesh buys nothing (a
    1x1 mesh = one device, no collectives). The value is the wall-time
    ratio mesh/plain (>1 = overhead; the fixed price of smap dispatch,
    operand stacking and the block axis), so mesh routing below this
    block count is pure loss. The row carries the per-collective comm
    bytes the PR-10 jaxpr walker priced for the mesh program -- on a
    1x1 mesh every ring term is x(k-1)=0, and the row PROVES that:
    nonzero bytes here would mean the walker is charging collectives
    that cannot move wire data."""
    import jax
    import jax.numpy as jnp

    from tempo_tpu.ops.filter import Cond, Operands, T_RES, T_SPAN, eval_block
    from tempo_tpu.parallel.mesh import make_mesh
    from tempo_tpu.parallel.search import sharded_search
    from tempo_tpu.util import costmodel

    rng = np.random.default_rng(21)
    N, NT, R = 1 << 20, 1 << 15, 1 << 10
    flat = {
        "span.trace_sid": rng.integers(0, NT, size=N).astype(np.int32),
        "span.dur_us": rng.integers(0, 1_000_000, size=N).astype(np.int32),
        "span.res_idx": rng.integers(0, R, size=N).astype(np.int32),
        "res.service_id": rng.integers(0, 64, size=R).astype(np.int32),
    }
    conds = (
        Cond(target=T_RES, col="res.service_id", op="eq"),
        Cond(target=T_SPAN, col="span.dur_us", op="ge"),
    )
    tree = ("and", ("cond", 0), ("cond", 1))
    operands = Operands.build([(0, 3, 0, 0.0, 0.0),
                               (0, 500_000, 0, 0.0, 0.0)])

    # plain kernel: the single-block device path (trace mask + counts)
    dcols = {k: jax.device_put(jnp.asarray(v)) for k, v in flat.items()}
    mark = _tel_mark()
    run_plain = lambda: eval_block(  # noqa: E731
        (tree, conds), dcols, operands, N, NT, N, R, NT, span_out=False)
    jax.block_until_ready(run_plain())
    iters = 8
    plain_s = best_window(
        lambda: jax.block_until_ready([run_plain() for _ in range(iters)]),
        windows=4) / iters

    # stacked mesh program on a 1x1 mesh: same rows as one (B=1) block.
    # Columns are device-put ONCE (sharded_search's jnp.asarray is a
    # no-op on resident arrays), matching the plain leg's staged dcols
    # -- the ratio must price the smap/stacking program overhead, not a
    # per-call host->device transfer the production staged-column path
    # never pays.
    mesh = make_mesh(1)
    stacked = {k: jax.device_put(jnp.asarray(v[None]))
               for k, v in flat.items()}
    n_spans = np.asarray([N], dtype=np.int32)
    tm, sc = sharded_search(mesh, tree, conds, operands, stacked, n_spans,
                            nt=NT)
    # correctness anchor: both engines agree on the trace verdicts
    ptm, psc = (np.asarray(x) for x in run_plain())
    assert (tm[0] == ptm).all() and (sc[0] == psc).all(), \
        "mesh and plain kernels disagree on a 1x1 mesh"
    mesh_s = best_window(
        lambda: [sharded_search(mesh, tree, conds, operands, stacked,
                                n_spans, nt=NT) for _ in range(iters)],
        windows=4) / iters
    tel = _tel_close(mark)

    # per-collective comm bytes from the costmodel's static jaxpr
    # walker (captured in the background on the program's first
    # compile); a 1x1 mesh must price every ring collective at 0 bytes
    costmodel.COST.drain(timeout=10.0)
    comm = costmodel.COST.comm_for("mesh_search", str(N))
    tel.update({
        "plain_ms": round(plain_s * 1e3, 3),
        "mesh_ms": round(mesh_s * 1e3, 3),
        "comm_bytes_per_launch": {c: int(b) for c, b in sorted(comm.items())},
        "comm_bytes_total": int(sum(comm.values())),
    })
    _emit("search_mesh_1x1_overhead", mesh_s / plain_s, "ratio", tel=tel)


def bench_find_and_search(tmp: str) -> tuple[float, float, dict, dict]:
    """BASELINE config #2 shape: a 10-block local backend holding the
    reference's own dataset size (~150 K traces / 10.4 M spans total,
    docs/design-proposals/2022-04 Parquet.md:211-218), searched through
    the PRODUCTION engine (TempoDB.search -> search_blocks_fused: the
    same path the frontend's block-batch jobs execute)."""
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest, search_block

    rng = np.random.default_rng(7)
    backend = LocalBackend(tmp + "/store")
    n_blocks, n_traces, spans_per = 10, 1 << 15, 32  # 10 x 1.05 M = 10.5 M spans
    metas, ids_per = [], []
    for _ in range(n_blocks):
        meta, ids = synth_block(backend, "bench", rng, n_traces, spans_per)
        metas.append(meta)
        ids_per.append(ids)
    total_spans = n_blocks * n_traces * spans_per

    db = TempoDB(TempoDBConfig(wal_path=tmp + "/wal"), backend=backend)
    db.poll_now()

    # --- find p50 (bloom gates + batched lookup + row materialization
    # across the 10-block backend). Steady-state: warm each block's
    # row-group chunk cache first (the production querier's long-lived
    # readers sit on hot caches; the reference's 0.18 s figure likewise
    # rides the OS page cache)
    mark = _tel_mark()
    group_traces = (1 << 16) // spans_per  # traces per 64Ki-span row group
    for b in range(n_blocks):
        for sid in range(0, n_traces, group_traces):
            assert db.find_trace_by_id("bench", ids_per[b][sid].tobytes()) is not None
    picks = rng.integers(0, n_traces, size=120)
    lat = []
    for i, p in enumerate(picks[20:]):
        tid = ids_per[i % n_blocks][int(p)].tobytes()
        t0 = time.perf_counter()
        got = db.find_trace_by_id("bench", tid)
        lat.append(time.perf_counter() - t0)
        assert got is not None
    _emit("find_trace_by_id_p50_ms", float(np.median(lat) * 1e3), "ms",
          tel=_tel_close(mark))

    # --- batched lookup, production auto path (the frontend ID-shard /
    # multi-block unit): on one chip this is the host vectorized
    # searchsorted engine (each device dispatch+fetch costs a full link
    # RTT); on a mesh the device kernel takes over (parallel/find.py)
    from tempo_tpu.ops.find import lookup_ids_blocks_cached

    blocks = [db.open_block(m) for m in metas]
    mark = _tel_mark()
    Q = 256
    qidx = rng.integers(0, n_traces, size=Q)
    qcodes = (ids_per[0][qidx].view(">u4").astype(np.int64) - 0x80000000).astype(np.int32).reshape(Q, 4)
    sids = lookup_ids_blocks_cached(blocks, qcodes)  # warm
    assert (sids[0] >= 0).all()
    iters_f = 10
    dt = best_window(
        lambda: [lookup_ids_blocks_cached(blocks, qcodes) for _ in range(iters_f)],
        windows=3)
    # ids RESOLVED per second (each call answers Q ids against all 10
    # blocks' indexes); the per-block bisection work is 10x that
    _emit("find_batched_device_ids_per_sec", Q * iters_f / dt, "ids/s",
          tel=_tel_close(mark))

    # --- find calibration race (ops/find.calibrate_find): measure both
    # engines over the same 10-block index set, commit the crossover to
    # a CostLedger artifact, then PROVE the `auto` policy consults it
    # (routing reason ledger_crossover). The row's value is the modeled
    # id-row count where the device engine starts winning.
    from tempo_tpu.ops.find import calibrate_find
    from tempo_tpu.util import costledger
    from tempo_tpu.util.kerneltel import TEL as _TEL

    costledger.configure(tmp + "/cost_ledger.json")
    mark = _tel_mark()
    entry = calibrate_find(blocks, qcodes, repeats=3)
    r0 = _TEL.routing_counts()
    auto_sids = lookup_ids_blocks_cached(blocks, qcodes, mode="auto")
    assert (auto_sids == sids).all(), "auto policy changed find results"
    r1 = _TEL.routing_counts()
    routed = [k for k, n in r1.items()
              if k[0] == "find" and n > r0.get(k, 0)]
    import jax as _jax

    want = "ledger_crossover" if len(_jax.devices()) == 1 else "mesh"
    assert any(k[2] == want for k in routed), (want, routed)
    tel = _tel_close(mark)
    tel.update({"winner": entry["winner"],
                "host_ms": round(entry["host_s"] * 1e3, 3),
                "device_ms": round(entry["device_s"] * 1e3, 3),
                "rows": entry["rows"], "queries": entry["queries"],
                "ledger": costledger.ledger().path})
    _emit("find_auto_crossover_rows", entry["crossover_rows"], "rows",
          tel=tel)

    # --- e2e search over the 10-block backend through TempoDB.search.
    # Correctness gate first: the fused device engine must agree with a
    # per-block host-engine scan.
    mark = _tel_mark()
    req = SearchRequest(tags={"service.name": "svc-003"},
                        min_duration_ms=100, limit=50)
    # touch 1 = host engine; touch 2 = staging upload; touch 3+ = pure
    # device (search_blocks_fused promote-on-second-touch policy)
    for _ in range(3):
        resp = db.search("bench", req)
    assert resp.inspected_spans == total_spans
    assert len(resp.traces) == req.limit
    # correctness gate: every trace the device engine returned must be a
    # REAL match -- materialize it and check the predicate holds on the
    # wire form (a span whose resource is svc-003, trace duration >=
    # min_duration) -- and the per-block host engine must agree on the
    # global newest-first frontier (within the 1 s device key granularity)
    for t in resp.traces:
        tr = db.find_trace_by_id("bench", bytes.fromhex(t.trace_id))
        assert tr is not None
        assert t.duration_ms >= req.min_duration_ms
        svcs = {rs.resource.attrs.get("service.name") for rs in tr.resource_spans}
        assert "svc-003" in svcs, svcs
    host_newest = []
    for m in metas:
        r = search_block(db.open_block(m), req, mode="host")
        host_newest.extend(r.traces)
    host_newest.sort(key=lambda t: -t.start_time_unix_nano)
    got_ids = {t.trace_id for t in resp.traces}
    cutoff = min(t.start_time_unix_nano for t in resp.traces)
    missed = [t for t in host_newest[: req.limit]
              if t.trace_id not in got_ids
              and t.start_time_unix_nano > cutoff + 1_000_000_000]
    assert not missed, f"device engine missed {len(missed)} strictly-newer matches"

    # cold: a fresh TempoDB + readers every iteration => every byte from
    # disk + zstd decode + filter. MIN per-iteration time (timeit's
    # methodology): this box is a shared single CPU core whose
    # contention swings individual iterations 2-3x; external noise only
    # ever ADDS time, so the minimum is the measurement of the engine
    # and the median is a measurement of the neighbors.
    iters = 6
    smark = _stream_mark()
    n_cold = {"n": 0}

    def cold_sample() -> float:
        n_cold["n"] += 1
        dbc = TempoDB(TempoDBConfig(wal_path=tmp + "/wal"), backend=backend)
        dbc.poll_now()
        t0 = time.perf_counter()
        resp = dbc.search("bench", req)
        dt = time.perf_counter() - t0
        assert resp.inspected_spans == total_spans
        dbc.close()
        return dt

    cold = total_spans / adaptive_min(cold_sample, iters, 2 * iters)
    cold_tel = {**_tel_close(mark), **_stream_close(smark, per=n_cold["n"])}

    # cold find p50: fresh readers per lookup, so the bloom shard, the
    # trace index and the trace's row-group chunks all come off disk
    # through the pipeline's plan -> ranged-fetch -> threaded-decode
    # stages (colio plan_fetch/_run_plan)
    mark = _tel_mark()
    smark = _stream_mark()
    fpicks = rng.integers(0, n_traces, size=9)
    flat = []
    for i, p in enumerate(fpicks):
        dbf = TempoDB(TempoDBConfig(wal_path=tmp + "/wal"), backend=backend)
        dbf.poll_now()
        tid = ids_per[i % n_blocks][int(p)].tobytes()
        t0 = time.perf_counter()
        got = dbf.find_trace_by_id("bench", tid)
        flat.append(time.perf_counter() - t0)
        assert got is not None
        dbf.close()
    _emit("search_block_e2e_cold_find_p50_ms", float(np.median(flat) * 1e3),
          "ms",
          tel={**_tel_close(mark), **_stream_close(smark, per=len(flat))})
    mark = _tel_mark()

    # hot: long-lived readers (the production querier pattern over
    # immutable blocks) => staged device arrays cached; ~one device sync
    # per query. The reference's analog hot path still re-decodes
    # parquet pages from the OS page cache each query.
    def warm_sample() -> float:
        t0 = time.perf_counter()
        resp = db.search("bench", req)
        dt = time.perf_counter() - t0
        assert resp.inspected_spans == total_spans
        return dt

    warm = total_spans / adaptive_min(warm_sample, 2 * iters, 4 * iters)
    warm_tel = _tel_close(mark)

    # --- TraceQL metrics range query over the same 10-block backend
    # (db/metrics_exec): fused filter->bucketize->fold per block, device
    # for blocks whose staged columns are already hot. No reference
    # figure exists (the reference's traceql-metrics shipped unbenched),
    # so vs_baseline stays 0.0.
    from tempo_tpu.db.metrics_exec import align_params

    mark = _tel_mark()
    base_s = 1_700_000_000
    mreq = align_params(
        '{ span.http.status_code >= 200 } | rate() by(resource.service.name)',
        base_s, base_s + 3600, 60)
    mresp = db.metrics_query_range("bench", mreq)
    assert mresp.series, "metrics bench query matched nothing"
    total_counted = sum(int(s["count"].sum()) for s in mresp.series.values())
    assert total_counted > 0

    def metrics_sample() -> float:
        t0 = time.perf_counter()
        r = db.metrics_query_range("bench", mreq)
        dt = time.perf_counter() - t0
        assert r.inspected_spans == total_spans
        return dt

    msec = adaptive_min(metrics_sample, 4, 10)
    _emit("metrics_query_range_spans_per_sec", total_spans / msec, "spans/s",
          tel=_tel_close(mark))

    db.close()
    return cold, warm, cold_tel, warm_tel


def _stream_mark() -> dict:
    """Cold-read stream-pipeline telemetry mark (kerneltel stream stats)."""
    from tempo_tpu.util.kerneltel import TEL

    return TEL.stream_stats()


def _stream_close(mark: dict, per: int = 1) -> dict:
    """Close a cold-read section: per-query stage ms (fetch/decompress/
    assemble/upload) and the overlap ratio (stage seconds / pipeline
    wall seconds; >1 = stages of different units genuinely ran at the
    same time) -- the "where did the cold time go" row extension."""
    from tempo_tpu.util.kerneltel import TEL

    now = TEL.stream_stats()
    per = max(1, per)
    stage_s = {k: v - mark["stage_seconds"].get(k, 0.0)
               for k, v in now["stage_seconds"].items()}
    wall = now["wall_seconds"] - mark["wall_seconds"]
    return {"stream": {
        "runs": now["runs"] - mark["runs"],
        "units": now["units"] - mark["units"],
        "stage_ms_per_query": {k: round(v * 1000 / per, 2)
                               for k, v in stage_s.items()},
        "overlap_ratio": round(sum(stage_s.values()) / wall, 3) if wall > 0 else 0.0,
    }}


def _compact_mark() -> dict:
    """Compaction-pipeline telemetry mark (kerneltel compaction stats)."""
    from tempo_tpu.util.kerneltel import TEL

    return TEL.compaction_stats()


def _compact_close(mark: dict) -> dict:
    """Close a compaction section: PER-RUN averages (a section times the
    same job set over several best_window repetitions, so totals would
    be ~windows x the headline run) -- per-stage ms, overlap ratio
    (stage seconds / wall seconds; >1 = stages genuinely overlapped),
    peak jobs in flight and prefetch outcomes: the "where did the time
    go" row extension for the compaction metrics."""
    from tempo_tpu.util.kerneltel import TEL

    now = TEL.compaction_stats()
    runs = max(1, now["runs"] - mark["runs"])
    stage_s = {k: v - mark["stage_seconds"].get(k, 0.0)
               for k, v in now["stage_seconds"].items()}
    wall = now["wall_seconds"] - mark["wall_seconds"]
    return {"pipeline": {
        "runs": runs,
        "jobs_per_run": round((now["jobs"] - mark["jobs"]) / runs, 2),
        # run-scoped peak (reset per pipeline run): every window in a
        # section runs the same job set, so the last run's peak IS the
        # section's -- the lifetime max would leak across sections
        "max_jobs_inflight": now["run_max_jobs_inflight"],
        "stage_ms_per_run": {k: round(v * 1000 / runs, 1)
                             for k, v in stage_s.items()},
        "overlap_ratio": round(sum(stage_s.values()) / wall, 3) if wall > 0 else 0.0,
        "prefetch_per_run": {
            k: round((now["prefetch"].get(k, 0) - mark["prefetch"].get(k, 0)) / runs, 2)
            for k in now["prefetch"]},
    }}


def bench_compaction(tmp: str) -> None:
    """Two shapes, both through the pipelined concurrent executor
    (db/compact_pipeline; TEMPO_COMPACT_CONCURRENCY workers, >= 4 here):
    the realistic level-1 job (8 mid-size blocks, the compactor's
    steady-state diet) is the headline compaction_mb_per_sec; the
    adversarial many-tiny-blocks shape (per-block fixed costs dominate)
    runs as the production compactor sees it -- select_jobs-size batches
    of max_input_blocks executing concurrently through the admission
    gate, with concat part copies as backend-side hardlinks. Rows carry
    pipeline stats (jobs in flight, per-stage ms, overlap ratio,
    prefetch outcomes) so the snapshot shows where the time goes.
    Single-core-friendly host work by design -- the TPU plays no role in
    compaction."""
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db.compact_pipeline import CompactionPipeline, resolve_concurrency
    from tempo_tpu.db.compactor import CompactionJob, CompactorConfig

    rng = np.random.default_rng(11)
    cfg = CompactorConfig()
    # the canonical env parser, floored at the acceptance shape's >= 4
    conc = max(4, resolve_concurrency(cfg))

    backend = LocalBackend(tmp + "/cstore-realistic")
    metas = [synth_block(backend, "bench", rng, 1 << 14, 24, n_res=256)[0]
             for _ in range(8)]
    total = sum(m.size_bytes for m in metas)
    mark = _compact_mark()
    # best of 3 (same min-under-noise rationale as the search timings;
    # one run of this job is ~2 s, and any window can catch a neighbor)
    def job():
        outs = CompactionPipeline(backend, cfg, concurrency=conc).run(
            {"bench": [CompactionJob("bench", metas)]})
        assert outs[0].error is None, outs[0].error
        assert outs[0].result.traces_out == 8 * (1 << 14)

    best = best_window(job, windows=3)
    _emit("compaction_mb_per_sec", total / best / 1e6, "MB/s",
          tel=_compact_close(mark))

    backend2 = LocalBackend(tmp + "/cstore-small")
    metas2 = [synth_block(backend2, "bench", rng, 200, 8, n_res=16)[0]
              for _ in range(100)]
    total2 = sum(m.size_bytes for m in metas2)
    k = cfg.max_input_blocks
    jobs2 = [CompactionJob("bench", metas2[i:i + k])
             for i in range(0, len(metas2), k)]
    mark2 = _compact_mark()

    def job2():
        outs = CompactionPipeline(backend2, cfg, concurrency=conc).run(
            {"bench": jobs2})
        errs = [o.error for o in outs if o.error is not None]
        assert not errs, errs
        assert sum(o.result.traces_out for o in outs) == 100 * 200

    best2 = best_window(job2, windows=2)
    _emit("compaction_small_blocks_mb_per_sec", total2 / best2 / 1e6, "MB/s",
          tel=_compact_close(mark2))


def bench_ingest(tmp: str) -> None:
    """OTLP raw-bytes ingest through the production write path
    (push_raw: native structural scan + byte splice -> rate limit ->
    WAL append + live map), distributor-role shape (no generator tap --
    the tap is async and in production runs on other cores/hosts).
    vs_baseline is the ratio to the reference's 15 MB/s per-tenant
    ingest rate-limit default (modules/overrides/limits.go:92-99): >= 1
    means one tenant at the default limit can't saturate this path."""
    from tempo_tpu.services.app import App, AppConfig, IngesterConfig
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire import otlp_pb

    cfg = AppConfig(
        target="all", http_port=0, storage_path=tmp + "/ingest-store",
        ingester=IngesterConfig(max_trace_idle_s=9999, max_block_age_s=9999,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    try:
        app.distributor.generator_forward = None
        app.distributor.generator_ring = None
        tenant = app.tenant_of({})
        traces = make_traces(200, seed=3, n_spans=20)
        payloads = [otlp_pb.encode_trace(t) for _, t in traces]
        raw_bytes = sum(len(p) for p in payloads)
        # collectors batch: one export request carries many traces
        # (concatenated Export payloads are protobuf-valid), and the
        # columnar WAL turns each window into ONE framed record
        per_window = 40
        windows = [b"".join(payloads[i:i + per_window])
                   for i in range(0, len(payloads), per_window)]
        app.distributor.push_raw(tenant, windows[0])  # warm
        iters = 2

        def window():
            for _ in range(iters):
                for p in windows:
                    app.distributor.push_raw(tenant, p)

        dt = best_window(window, windows=3)
        mbs = raw_bytes * iters / dt / 1e6

        # per-stage breakdown (ISSUE 16): one more measured pass, then a
        # staging refresh + forced cut/flush so every write-path stage
        # records into the kerneltel ingest ledger
        from tempo_tpu.util.kerneltel import TEL

        def _stage_s(stats: dict) -> dict:
            return {k: v["seconds"] for k, v in stats["stages"].items()}

        inst = app.ingester.instance(tenant)
        if inst.live_engine is not None:  # drain the timing passes' backlog
            inst.live_engine.maybe_refresh()
        app.ingester.sweep_all(force=True)
        s0 = _stage_s(TEL.ingest_stats())
        window()
        if inst.live_engine is not None:
            inst.live_engine.maybe_refresh()
        app.ingester.sweep_all(force=True)
        s1 = _stage_s(TEL.ingest_stats())
        tel = {f"{st}_ms": round((s1.get(st, 0.0) - s0.get(st, 0.0)) * 1e3, 2)
               for st in ("decode", "wal_append", "stage_delta", "cut", "flush")}
        _emit("ingest_otlp_mb_per_sec", mbs, "MB/s", mbs / 15.0, tel=tel)
    finally:
        app.stop()


def bench_search_concurrent(tmp: str) -> None:
    """Cross-query batching executor (db/batchexec): Q parallel
    identical-shape queries against ONE hot staged block. Reports
    per-query p50/p95 latency plus launches-per-query and batch
    occupancy from kernel telemetry -- the sequential comparable is 2
    launches per query (filter + select); a healthy batcher lands well
    under 1."""
    from concurrent.futures import ThreadPoolExecutor

    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest
    from tempo_tpu.util.kerneltel import TEL

    rng = np.random.default_rng(23)
    backend = LocalBackend(tmp + "/store-conc")
    meta, _ = synth_block(backend, "bench", rng, 1 << 15, 32)  # 1.05 M spans
    db = TempoDB(
        TempoDBConfig(wal_path=tmp + "/wal-conc", device_promote_touches=1),
        backend=backend)
    db.poll_now()
    req = SearchRequest(query="{ duration > 100ms }", limit=20)
    Q, iters = 16, 3

    def one(_):
        t0 = time.perf_counter()
        r = db.search_blocks("bench", [meta], req)
        assert r.traces
        return time.perf_counter() - t0

    with ThreadPoolExecutor(Q) as ex:  # warm: staging + both compiles
        list(ex.map(one, range(Q)))
    mark = _tel_mark()
    l0 = TEL.launch_count()
    s0 = TEL.batch_stats().get("search", {"groups": 0, "queries": 0})
    lats: list[float] = []
    for _ in range(iters):
        with ThreadPoolExecutor(Q) as ex:
            lats.extend(ex.map(one, range(Q)))
    launches = TEL.launch_count() - l0
    s1 = TEL.batch_stats().get("search", {"groups": 0, "queries": 0})
    groups = s1["groups"] - s0.get("groups", 0)
    queries = s1["queries"] - s0.get("queries", 0)
    tel = _tel_close(mark, workers=Q)
    tel.update({
        "p95_ms": round(float(np.percentile(lats, 95)) * 1e3, 3),
        "launches_per_query": round(launches / (Q * iters), 3),
        "batch_occupancy": round(queries / groups, 2) if groups else 0.0,
    })

    # tracing-on overhead on the SAME warm batched shape: the timeline
    # spine's hot-path cost is clock reads + locked appends, so this
    # ratio must stay ~1.0 (the test suite asserts < 1.05). Off and on
    # legs are INTERLEAVED round by round (the test_selftrace median
    # scheme): this shared box drifts minute to minute, and back-to-back
    # homogeneous legs read the drift as overhead (BENCH_r06 shipped
    # ratios of 0.64 and 0.44 -- "tracing speeds you up" is a timing
    # artifact, not a result).
    from tempo_tpu.services.selftrace import SelfTracer

    st = SelfTracer(lambda tenant, rss: None)

    def one_traced(_):
        with st.trace("bench") as t:
            token = TEL.set_active_trace(t)
            t0 = time.perf_counter()
            try:
                db.search_blocks("bench", [meta], req)
            finally:
                TEL.reset_active_trace(token)
            return time.perf_counter() - t0

    def batch(fn) -> list[float]:
        with ThreadPoolExecutor(Q) as ex:
            return list(ex.map(fn, range(Q)))

    def interleaved_ratio(off_fn, on_fn, rounds: int = 4) -> float:
        offs: list[float] = []
        ons: list[float] = []
        for _ in range(rounds):
            offs.extend(batch(off_fn))
            ons.extend(batch(on_fn))
        return round(
            float(np.median(ons)) / max(float(np.median(offs)), 1e-9), 4)

    tel["selftrace_overhead_ratio"] = interleaved_ratio(one, one_traced)

    # always-on profiler overhead on the same warm batched shape: the
    # background sampler is ~19 Hz of raw stack walks, so this ratio
    # must stay under the 1.02x gate. Same interleaving: the sampler
    # starts and stops around each ON leg so off legs in the same round
    # are the true contemporaneous comparable.
    from tempo_tpu.util.profiler import PROF

    def batch_profiled(_i):
        return one(_i)

    def profiled_round() -> list[float]:
        PROF.start(hz=19.0)
        try:
            return batch(batch_profiled)
        finally:
            PROF.stop()

    offs_p: list[float] = []
    ons_p: list[float] = []
    for _ in range(4):
        offs_p.extend(batch(one))
        ons_p.extend(profiled_round())
    tel["profile_overhead_ratio"] = round(
        float(np.median(ons_p)) / max(float(np.median(offs_p)), 1e-9), 4)
    _emit("search_concurrent_p50_ms", float(np.median(lats)) * 1e3, "ms",
          tel=tel)
    db.close()


def bench_search_live(tmp: str) -> None:
    """Live-head device engine (db/live_engine): N live traces in one
    ingester instance, C concurrent searches -- device engine vs the
    host index walk (the differential oracle), plus the staging-lag
    stat (push -> device-visible ms) from kernel telemetry."""
    import os
    import random as _random
    from concurrent.futures import ThreadPoolExecutor

    from tempo_tpu.backend import MemBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest
    from tempo_tpu.db.wal import WAL
    from tempo_tpu.services.ingester import Ingester, IngesterConfig
    from tempo_tpu.services.overrides import Overrides
    from tempo_tpu.util.kerneltel import TEL
    from tempo_tpu.util.testdata import make_trace, make_trace_id
    from tempo_tpu.wire.segment import segment_for_write

    db = TempoDB(TempoDBConfig(wal_path=tmp + "/wal-live-db"),
                 backend=MemBackend())
    ing = Ingester(WAL(tmp + "/wal-live"), db, Overrides(), IngesterConfig())
    inst = ing.instance("bench")
    rng = _random.Random(17)
    n_traces, C, iters = 2000, 8, 3
    lag0 = TEL.livestage_stats()
    for i in range(n_traces):
        tid = make_trace_id(rng)
        tr = make_trace(rng, trace_id=tid, n_spans=4,
                        base_time_ns=1_700_000_000_000_000_000 + i * 10**9)
        lo, hi = tr.time_range_nanos()
        s, e = lo // 10**9, hi // 10**9 + 1
        inst.push_segments([(tid, s, e, segment_for_write(tr, s, e))])
    reqs = [SearchRequest(tags={"service.name": "db"}, limit=20),
            SearchRequest(tags={"name": "GET /api"}, limit=20),
            SearchRequest(min_duration_ms=200, limit=20)]

    def run_engine(engine: str) -> list[float]:
        prev = os.environ.get("TEMPO_LIVE_ENGINE")
        os.environ["TEMPO_LIVE_ENGINE"] = engine
        try:
            inst.search_live(reqs[0])  # warm: staging upload + compiles

            def one(i):
                t0 = time.perf_counter()
                r = inst.search_live(reqs[i % len(reqs)])
                assert r.traces
                return time.perf_counter() - t0

            lats: list[float] = []
            for _ in range(iters):
                with ThreadPoolExecutor(C) as ex:
                    lats.extend(ex.map(one, range(C)))
            return lats
        finally:
            if prev is None:  # restore whatever the operator forced
                del os.environ["TEMPO_LIVE_ENGINE"]
            else:
                os.environ["TEMPO_LIVE_ENGINE"] = prev

    mark = _tel_mark()
    dev = run_engine("device")
    host = run_engine("index")
    lag1 = TEL.livestage_stats()
    lag_ms = 0.0
    if lag1["lag_count"] > lag0["lag_count"]:
        lag_ms = ((lag1["lag_avg_s"] * lag1["lag_count"]
                   - lag0["lag_avg_s"] * lag0["lag_count"])
                  / (lag1["lag_count"] - lag0["lag_count"]) * 1e3)
    tel = _tel_close(mark, workers=C)
    tel.update({
        "host_index_p50_ms": round(float(np.median(host)) * 1e3, 3),
        "p95_ms": round(float(np.percentile(dev, 95)) * 1e3, 3),
        "staging_lag_ms": round(lag_ms, 2),
        "live_traces": n_traces,
        "crossover_rows": inst.live_engine.stats()["crossover_rows"],
    })
    _emit("search_live_p50_ms", float(np.median(dev)) * 1e3, "ms",
          tel=tel)
    db.close()


def bench_search_affinity(tmp: str) -> None:
    """Cache-affinity scheduling differential (services/frontend): a
    dispatcher-only frontend + 3 simulated remote querier workers, each
    with its OWN TempoDB over one shared backend -- its own staged-cache
    domain, the in-process analog of 3 chips' HBM. 4 tenants' blocks,
    50 concurrent mixed-tenant searches with Zipf skew, and the staged
    device budget pinched to ~1.35x ONE fleet copy of the working set,
    so placement-blind dequeue (affinity off) duplicates staged columns
    across workers and thrashes the cache while block->querier affinity
    keeps each block staged on exactly one worker. Reports p99 and
    fleet staged-cache hit rate for both modes plus the re-upload bytes
    affinity avoided -- the differential soak gate's numbers."""
    import gc
    import threading as th
    from concurrent.futures import ThreadPoolExecutor

    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.search import SearchRequest
    from tempo_tpu.ops import stage as stage_mod
    from tempo_tpu.services.frontend import Frontend
    from tempo_tpu.services.querier import Querier
    from tempo_tpu.services.worker import execute_job
    from tempo_tpu.util.kerneltel import TEL

    rng = np.random.default_rng(31)
    backend = LocalBackend(tmp + "/store-aff")
    fleet, n_tenants, concurrency, n_queries = 3, 4, 50, 150
    tenants = [f"tenant-{i}" for i in range(n_tenants)]
    for t in tenants:
        for _ in range(2):
            synth_block(backend, t, rng, 1 << 12, 8, n_res=64)
    req = SearchRequest(query="{ duration > 100ms }", limit=10)

    def new_db():
        db = TempoDB(TempoDBConfig(wal_path=tmp + "/wal-aff",
                                   device_promote_touches=1), backend=backend)
        db.poll_now()
        return db

    # measure ONE fleet copy of the staged working set, then pinch the
    # budget: without pressure, placement-blind routing eventually warms
    # every worker and the differential vanishes -- with it, off-mode
    # duplication evicts and re-uploads forever (the million-user shape,
    # where the working set never fits every chip)
    old_budget = stage_mod.staged_cache_stats()["budget_bytes"]
    stage_mod.set_staged_cache_budget(0)  # drop earlier benches' entries
    stage_mod.set_staged_cache_budget(old_budget)
    probe = new_db()
    base = stage_mod.staged_cache_stats()["bytes"]
    for t in tenants:
        probe.search(t, req)  # stages both of t's blocks (promote=1)
    footprint = stage_mod.staged_cache_stats()["bytes"] - base
    probe.close()
    del probe
    gc.collect()
    budget = max(1 << 20, int(footprint * 1.35))
    stage_mod.set_staged_cache_budget(budget)

    zipf = np.array([1.0 / (i + 1) ** 1.1 for i in range(n_tenants)])
    q_tenants = rng.choice(n_tenants, size=n_queries, p=zipf / zipf.sum())

    def run_mode(affinity: bool) -> dict:
        fe_db = new_db()
        fe = Frontend(Querier(fe_db, ring=None, client_for=lambda a: None),
                      n_workers=0, hedge_after_s=0.0,
                      affinity=affinity, affinity_steal_ms=75.0)
        worker_dbs = [new_db() for _ in range(fleet)]
        queriers = [Querier(db, ring=None, client_for=lambda a: None)
                    for db in worker_dbs]
        stop = th.Event()

        def wloop(wid: int):
            qr = queriers[wid]
            while not stop.is_set():
                job = fe.poll_job(wait_s=0.25, worker_id=f"w{wid}")
                if job is None:
                    continue
                tok = TEL.set_affinity_placement(job.get("placement", ""))
                try:
                    try:
                        res = execute_job(qr, job.get("tenant", ""),
                                          job["kind"], job["payload"])
                        fe.complete_job(job["id"], ok=True, result=res)
                    except Exception as e:  # noqa: BLE001 - frontend retries
                        fe.complete_job(job["id"], ok=False, error=str(e),
                                        retryable=True)
                finally:
                    TEL.reset_affinity_placement(tok)

        threads = [th.Thread(target=wloop, args=(i,), daemon=True)
                   for i in range(fleet)]
        for t in threads:
            t.start()
        h0, m0 = TEL.staged_cache_hits.get(), TEL.staged_cache_misses.get()
        b0 = TEL.transfer_bytes.get()
        lats: list[float] = []
        lat_lock = th.Lock()

        def one(i: int):
            tenant = tenants[int(q_tenants[i])]
            t0 = time.perf_counter()
            r = fe.search(tenant, req)
            dt = time.perf_counter() - t0
            assert r.traces
            with lat_lock:
                lats.append(dt)

        with ThreadPoolExecutor(concurrency) as ex:
            list(ex.map(one, range(n_queries)))
        stop.set()
        for t in threads:
            t.join(timeout=5)
        fe.stop()
        hits = TEL.staged_cache_hits.get() - h0
        misses = TEL.staged_cache_misses.get() - m0
        upload = TEL.transfer_bytes.get() - b0
        fe_db.close()
        for db in worker_dbs:
            db.close()
        gc.collect()  # free this fleet's staged entries before the next
        return {
            "p50_ms": round(float(np.median(lats)) * 1e3, 2),
            "p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 2),
            "staged_hit_rate": round(hits / (hits + misses), 4)
                               if hits + misses else 0.0,
            "upload_bytes": int(upload),
        }

    a0 = TEL.affinity_stats()["jobs"]
    on = run_mode(True)
    a1 = TEL.affinity_stats()["jobs"]
    off = run_mode(False)
    stage_mod.set_staged_cache_budget(old_budget)
    tel = {
        "affinity_on": on,
        "affinity_off": off,
        "placements_on": {k: a1.get(k, 0) - a0.get(k, 0)
                          for k in sorted(set(a0) | set(a1))},
        "reupload_bytes_avoided": max(
            0, off["upload_bytes"] - on["upload_bytes"]),
        "workers": fleet, "tenants": n_tenants, "concurrency": concurrency,
        "staged_budget_bytes": budget,
    }
    _emit("search_affinity_p99_ms", on["p99_ms"], "ms", tel=tel)


# mesh-batched probe: runs in a FRESH interpreter with 8 virtual CPU
# devices (the dev box has one chip; mesh rows need a mesh). Measures
# (1) one admission window's 16 queries as ONE Q-programs x
# sharded-rows mesh launch (parallel/multiquery) vs 16 sequential mesh
# launches of the same programs -- launches/query, occupancy and the
# walker's comm bytes/query attached -- and (2) the struct-op
# collective shrink: the walker-priced per-node comm bytes of the
# packed '>' struct program vs the legacy triple-gather program.
_MESH_BATCH_PROBE = r"""
import json, os, time
import numpy as np
import tempfile
from bench import synth_block, best_window
from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.db.search import SearchRequest, search_block, _plan_for_block
from tempo_tpu.db.batchexec import batched_search_block_many
from tempo_tpu.ops.filter import Cond, Operands, T_SPAN, required_columns
from tempo_tpu.ops.multiquery import _p2, lower_plan, pack_queries
from tempo_tpu.ops.stage import stage_block
from tempo_tpu.parallel import make_mesh
from tempo_tpu.parallel.multiquery import mesh_eval_multiquery
from tempo_tpu.parallel.search import sharded_search
from tempo_tpu.util import costmodel
from tempo_tpu.util.kerneltel import TEL

rng = np.random.default_rng(37)
backend = MemBackend()
meta, _ = synth_block(backend, "bench", rng, 1 << 14, 16)  # 256Ki spans
db = TempoDB(TempoDBConfig(wal_path=tempfile.mkdtemp(),
                           device_promote_touches=1), backend=backend)
db.poll_now()
blk = db.open_block(meta)
mesh = make_mesh()
assert mesh.devices.size > 1, "probe needs the virtual-device mesh"
Q = 16
reqs = [SearchRequest(query="{ duration > %dms }" % (100 + i), limit=20)
        for i in range(Q)]

# end-to-end identity + occupancy through the REAL admission window
warm = batched_search_block_many(db.batchers.search, [(blk, reqs[0], None)],
                                 promote_touches=1)
outs = batched_search_block_many(db.batchers.search,
                                 [(blk, r, None) for r in reqs],
                                 promote_touches=1)
d = lambda r: [{**t.to_dict(), "matchedSpans": t.matched_spans}
               for t in r.traces]
for r, o in zip(reqs, outs):
    assert d(o) == d(search_block(blk, r)), "mesh-batched != sequential"
occupancy = TEL.mesh_batch_stats()["occupancy"]

# kernel-level legs: the SAME 16 programs as one batched launch vs 16
# sequential mesh launches (the pre-batching mesh comparable)
lowered = [lower_plan(_plan_for_block(blk, r)) for r in reqs]
assert all(lq is not None for lq in lowered)
p0 = _plan_for_block(blk, reqs[0])
needed = required_columns(p0.conds) + list(p0.extra_cols)
staged = stage_block(blk, needed + ["trace.start_ms"])
q_b = _p2(Q, lo=1)
progs = pack_queries(lowered, q_b)
progs1 = [pack_queries([lq], 1) for lq in lowered]
mesh_eval_multiquery(mesh, lowered, staged, progs)          # warm both
mesh_eval_multiquery(mesh, [lowered[0]], staged, progs1[0])
l0 = TEL.launch_count()
batched_s = best_window(
    lambda: mesh_eval_multiquery(mesh, lowered, staged, progs), windows=4)
batched_launches = TEL.launch_count() - l0
seq_s = best_window(
    lambda: [mesh_eval_multiquery(mesh, [lq], staged, p1)
             for lq, p1 in zip(lowered, progs1)], windows=4)
costmodel.COST.drain(30.0)
comm = costmodel.COST.comm_for("mesh_multiquery", str(staged.n_spans_b))

# struct-op collective shrink: '>' node, packed vs legacy walker bytes
B, S, NT = 2, 1 << 15, 1 << 10
scols = {
    "span.trace_sid": np.sort(
        rng.integers(0, NT, size=(B, S)).astype(np.int32), axis=1),
    "span.dur_us": rng.integers(0, 1000, size=(B, S)).astype(np.int32),
    "span.parent_idx": np.where(
        np.arange(S)[None, :] % 8 == 0, -1,
        np.arange(S, dtype=np.int32)[None, :] - 1) * np.ones((B, 1), np.int32),
}
n_spans = np.asarray([S, S - 1000], np.int32)
sconds = (Cond(target=T_SPAN, col="span.dur_us", op="lt"),
          Cond(target=T_SPAN, col="span.dur_us", op="ge"))
sops = Operands.build([(0, 900, 0, 0.0, 0.0), (0, 50, 0, 0.0, 0.0)])
stree = ("struct", ">", ("cond", 0), ("cond", 1))
os.environ["TEMPO_STRUCT_PACK"] = "1"
tm1, sc1 = sharded_search(mesh, stree, sconds, sops, scols, n_spans, nt=NT)
os.environ["TEMPO_STRUCT_PACK"] = "0"
tm0, sc0 = sharded_search(mesh, stree, sconds, sops, scols, n_spans, nt=NT)
assert (tm1 == tm0).all() and (sc1 == sc0).all(), "struct shrink changed results"
del os.environ["TEMPO_STRUCT_PACK"]
drained = costmodel.COST.drain(30.0)
packed = costmodel.COST.comm_for("mesh_search", str(S))
legacy = costmodel.COST.comm_for("mesh_search_nopack", str(S))
db.close()
# comm rows may be absent (TEMPO_COSTMODEL=0 kill switch, or a drain
# timeout on a loaded box): report 0.0 rather than aborting the bench
shrink = (legacy["all_gather"] / packed["all_gather"]
          if drained and packed.get("all_gather") and legacy.get("all_gather")
          else 0.0)
print(json.dumps({
    "devices": int(mesh.devices.size),
    "batched_ms": batched_s * 1e3, "sequential_ms": seq_s * 1e3,
    "ratio": seq_s / batched_s,
    "launches_per_query": batched_launches / Q,
    "occupancy": occupancy,
    "comm_bytes_per_query": sum(comm.values()) / Q,
    "comm_bytes_per_launch": {c: int(b) for c, b in sorted(comm.items())},
    "struct_before": {c: int(b) for c, b in sorted(legacy.items())},
    "struct_after": {c: int(b) for c, b in sorted(packed.items())},
    "struct_node_shrink": shrink,
}))
"""


def bench_mesh_batched(tmp: str) -> None:
    """search_mesh_batched (ROADMAP 2c): the value is the wall-time
    ratio of 16 sequential mesh launches to the ONE batched mesh launch
    carrying the same window (>1 = batching and chip-parallelism
    multiply). search_struct_comm_shrink: walker-priced per-struct-node
    comm bytes before/after the bit-packed + hoisted gathers (the
    acceptance gate is >= 5x). Both legs run in a subprocess with 8
    virtual CPU devices -- this box has one chip, and the mesh rows
    must measure a real multi-device program."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if not f.startswith("--xla_force_host_platform_device_count"))
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, "-c", _MESH_BATCH_PROBE],
        capture_output=True, text=True, timeout=1200, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    _emit("search_mesh_batched", row["ratio"], "ratio",
          tel={"devices": row["devices"],
               "batched_ms": round(row["batched_ms"], 3),
               "sequential_ms": round(row["sequential_ms"], 3),
               "launches_per_query": round(row["launches_per_query"], 3),
               "occupancy": row["occupancy"],
               "comm_bytes_per_query": round(row["comm_bytes_per_query"], 1),
               "comm_bytes_per_launch": row["comm_bytes_per_launch"]})
    _emit("search_struct_comm_shrink", row["struct_node_shrink"], "ratio",
          tel={"comm_before": row["struct_before"],
               "comm_after": row["struct_after"]})


# the first-query probe a cold subprocess runs: import the kernel layer,
# evaluate ONE tiny filter program, report the first-call wall ms (jit
# trace + XLA compile + execute). The parent varies TEMPO_COMPILE_CACHE_DIR
# to measure the persistent compilation cache's effect on exactly the
# latency a restarted querier's first query pays (ROADMAP item 5).
_COMPILE_PROBE = r"""
import json, os, time
import numpy as np
from tempo_tpu.ops.device import PAD_I32, pad_rows
from tempo_tpu.ops.filter import Cond, Operands, T_SPAN, eval_block
import jax
warmup_ms = 0.0
if os.environ.get("TEMPO_WARMUP") == "1":
    # the --warmup.shapes leg: compile the ledger corpus BEFORE the
    # timed first query (the serving process does this pre-listen)
    from tempo_tpu.util.warmup import run_warmup
    warmup_ms = run_warmup()["wall_ms"]
N, NB = 64, 1024
cols = {"span.trace_sid": pad_rows(np.zeros(N, np.int32), NB, PAD_I32),
        "span.dur_us": pad_rows(np.arange(N, dtype=np.int32), NB, PAD_I32),
        "trace.span_off": pad_rows(np.asarray([0, N], np.int32), NB + 1,
                                   np.int32(N))}
conds = (Cond(target=T_SPAN, col="span.dur_us", op="ge"),)
ops = Operands.build([(0, 10, 0, 0.0, 0.0)])
t0 = time.perf_counter()
out = eval_block((("cond", 0), conds), cols, ops, N, 1, NB, NB, NB)
jax.block_until_ready(out)
print(json.dumps({"first_query_ms": (time.perf_counter() - t0) * 1e3,
                  "warmup_ms": warmup_ms}))
"""


def bench_first_compile(tmp: str) -> None:
    """first_query_compile_p99_ms: the cold-process first-query latency
    (dominated by the first XLA compile), with and without the
    persistent compilation cache (TEMPO_COMPILE_CACHE_DIR). Each sample
    is a REAL fresh interpreter; p99 over so few samples is the max --
    honest for a storm metric, where the worst cold start is the one
    that pages. The row's value is the no-cache figure (the regression
    being engineered away); tel carries the with-cache figure and the
    measured speedup."""
    cache_dir = tmp + "/compile-cache"

    def probe_full(env_extra: dict) -> dict:
        env = dict(os.environ)
        env.pop("TEMPO_COMPILE_CACHE_DIR", None)
        env.update(env_extra)
        proc = subprocess.run(
            [sys.executable, "-c", _COMPILE_PROBE],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        assert proc.returncode == 0, proc.stderr[-2000:]
        return json.loads(proc.stdout.strip().splitlines()[-1])

    def probe(env_extra: dict) -> float:
        return float(probe_full(env_extra)["first_query_ms"])

    no_cache = [probe({}) for _ in range(2)]
    probe({"TEMPO_COMPILE_CACHE_DIR": cache_dir})  # populate the disk cache
    with_cache = [probe({"TEMPO_COMPILE_CACHE_DIR": cache_dir})
                  for _ in range(2)]
    # warmup-on leg (ROADMAP item 5 / --warmup.shapes): a ledger corpus
    # naming the filter bucket lets the fresh process AOT-compile it
    # (through the disk cache) BEFORE the timed first query -- the
    # figure a warmed-up restarted querier's first query actually pays
    ledger = tmp + "/warmup_ledger.json"
    with open(ledger, "w") as f:
        json.dump({"version": 1, "entries": {
            "compile_corpus": {"pairs": [["filter", "1024"]]}}}, f)
    warm = [probe_full({"TEMPO_COMPILE_CACHE_DIR": cache_dir,
                        "TEMPO_WARMUP": "1", "TEMPO_COST_LEDGER": ledger})
            for _ in range(2)]
    worst_no, worst_with = max(no_cache), max(with_cache)
    worst_warm = max(v["first_query_ms"] for v in warm)
    _emit("first_query_compile_p99_ms", worst_no, "ms",
          tel={"no_cache_ms": [round(v, 1) for v in no_cache],
               "with_disk_cache_ms": [round(v, 1) for v in with_cache],
               "with_warmup_ms": [round(v["first_query_ms"], 1)
                                  for v in warm],
               "warmup_wall_ms": [round(v["warmup_ms"], 1) for v in warm],
               "disk_cache_speedup": round(worst_no / max(worst_with, 1e-9), 2),
               "warmup_speedup": round(worst_no / max(worst_warm, 1e-9), 2),
               "samples_per_variant": 2})


def bench_spanmetrics() -> None:
    import jax

    from tempo_tpu.ops.reduce import span_metrics_reduce

    rng = np.random.default_rng(13)
    N, S = 1 << 22, 4096
    sid = rng.integers(0, S, size=N).astype(np.int32)
    dur = rng.random(N).astype(np.float32) * 10.0
    edges = tuple(float(2.0 ** (i - 6)) for i in range(14))
    mark = _tel_mark()
    span_metrics_reduce(sid, dur, S, edges)  # compile
    iters = 5
    dt = best_window(
        lambda: [span_metrics_reduce(sid, dur, S, edges) for _ in range(iters)],
        windows=3)
    _emit("spanmetrics_reduce_spans_per_sec", N * iters / dt, "spans/s",
          tel=_tel_close(mark))


def bench_generator_tap(tmp: str) -> None:
    """Streaming metrics-generator plane (services/generator): the
    PR-17 device reduction path the distributor tap feeds with the
    ingest decode's own coded columns. Two rows:

    - spanmetrics_streaming_spans_per_sec: push_window end to end over
      one coded window -- vectorized packed-key series assembly against
      the LiveDict, device segmented reduce, registry fold.
    - service_graph_edges_per_sec: client/server windows paired through
      the coded edge store ((trace, span/parent) keys), batched through
      the fused edge_metrics_reduce kernel.

    The tel on the edge row carries the zero-extra-decode proof: a real
    App window pushed through distributor -> tap -> generator with the
    columnar cache's decode counter unchanged beyond the ingest decode
    itself (the tap re-uses cached SegFeatures; extra_decodes must be
    0)."""
    from tempo_tpu.ingest.columnar import LiveDict, SpanColumns
    from tempo_tpu.services.generator import MetricsGenerator
    from tempo_tpu.services.overrides import Overrides

    rng = np.random.default_rng(41)
    ld = LiveDict()
    svc_codes = np.asarray([ld.code(f"svc-{i:03d}") for i in range(32)],
                           np.int32)
    name_codes = np.asarray([ld.code(f"op-{i:03d}") for i in range(128)],
                            np.int32)

    # --- span-metrics leg: one realistic coded window per push
    N = 1 << 16
    cols_sm = SpanColumns(
        svc_code=rng.choice(svc_codes, size=N).astype(np.int32),
        name_code=rng.choice(name_codes, size=N).astype(np.int32),
        kind=rng.integers(1, 6, size=N).astype(np.int32),
        status=(rng.random(N) < 0.05).astype(np.int32) * 2,
        dur_s=(rng.random(N).astype(np.float32) * 2.0),
        edge_key=np.zeros(N, np.uint64),
        tid_hex="00" * 16)
    gen = MetricsGenerator(Overrides())
    gen.push_window("bench", [cols_sm], ld)  # warm: compiles + series
    iters = 4
    mark = _tel_mark()
    dt = best_window(
        lambda: [gen.push_window("bench", [cols_sm], ld)
                 for _ in range(iters)], windows=3)
    _emit("spanmetrics_streaming_spans_per_sec", N * iters / dt, "spans/s",
          tel=_tel_close(mark))

    # --- service-graph leg: every window completes E edges (the client
    # part opens them, the server part in the same window closes them,
    # so the pending store drains back to empty each push)
    E = 1 << 14
    ekeys = np.arange(1, E + 1, dtype=np.uint64)
    cols_client = SpanColumns(
        svc_code=rng.choice(svc_codes, size=E).astype(np.int32),
        name_code=rng.choice(name_codes, size=E).astype(np.int32),
        kind=np.full(E, 3, np.int32), status=np.zeros(E, np.int32),
        dur_s=(rng.random(E).astype(np.float32) * 2.0),
        edge_key=ekeys, tid_hex="00" * 16)
    cols_server = SpanColumns(
        svc_code=rng.choice(svc_codes, size=E).astype(np.int32),
        name_code=rng.choice(name_codes, size=E).astype(np.int32),
        kind=np.full(E, 2, np.int32),
        status=(rng.random(E) < 0.05).astype(np.int32) * 2,
        dur_s=(rng.random(E).astype(np.float32) * 2.0),
        edge_key=ekeys, tid_hex="00" * 16)
    gen2 = MetricsGenerator(Overrides())
    gen2.push_window("bench", [cols_client, cols_server], ld)  # warm
    sg = gen2._procs("bench")["service-graphs"]
    assert not sg.pending, "paired window left edges pending"
    mark = _tel_mark()
    dt = best_window(
        lambda: [gen2.push_window("bench", [cols_client, cols_server], ld)
                 for _ in range(iters)], windows=3)
    tel = _tel_close(mark)

    # --- zero-extra-decode proof through the REAL tap (App write path)
    from tempo_tpu.services.app import App, AppConfig, IngesterConfig
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire import otlp_pb

    cfg = AppConfig(
        target="all", http_port=0, storage_path=tmp + "/gen-store",
        ingester=IngesterConfig(max_trace_idle_s=9999, max_block_age_s=9999,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    try:
        tenant = app.tenant_of({})
        for _, tr in make_traces(16, seed=5, n_spans=8):
            app.distributor.push_raw(tenant, otlp_pb.encode_trace(tr))
        app.distributor.flush_generator_tap()
        st = app.ingester.instance(tenant).columnar.stats()
        series = sum(1 for line in app.generator.metrics_text()
                     if line.startswith("traces_spanmetrics_calls_total"))
        extra = st["decodes"] - st["cached"]
        assert extra == 0, f"tap cost {extra} extra decodes: {st}"
        assert series > 0, "tap produced no generated series"
        tel.update({"tap_segments": st["cached"],
                    "tap_decodes": st["decodes"],
                    "tap_extra_decodes": extra,
                    "tap_series": series})
    finally:
        app.stop()
    _emit("service_graph_edges_per_sec", E * iters / dt, "edges/s", tel=tel)


def bench_caching(tmp: str) -> None:
    """The tiered cache plane, two rows:

    - search_result_cache_hit_p50_ms: p50 of a repeated search through
      the frontend once the result cache holds the entry -- the
      dashboard-refresh hot path, admitted AHEAD of the QoS queue. The
      tel carries the zero-work proof: device launches during the
      measured hits must be 0.
    - chunk_cache_restage_speedup: stage_block served from the host
      chunk pool (a demoted, recompressed HBM eviction victim) vs the
      cold path (backend ranged read + decode + pad + upload) on the
      same (block, columns) entry. The acceptance bar is >= 3x.
    """
    from tempo_tpu.services.app import App, AppConfig, IngesterConfig
    from tempo_tpu.util.kerneltel import TEL
    from tempo_tpu.util.testdata import make_traces
    from tempo_tpu.wire import otlp_pb

    cfg = AppConfig(
        target="all", http_port=0, storage_path=tmp + "/cache-store",
        compaction_cycle_s=9999,
        ingester=IngesterConfig(max_trace_idle_s=0.0, max_block_age_s=0.0,
                                flush_check_period_s=9999),
    )
    app = App(cfg)
    app.start()
    try:
        from tempo_tpu.db.search import SearchRequest

        tenant = app.tenant_of({})
        for _, tr in make_traces(64, seed=7, n_spans=8):
            app.distributor.push_raw(tenant, otlp_pb.encode_trace(tr))
        app.ingester.flush_all()
        app.db.poll_now()
        req = SearchRequest(query="{ true }", limit=20)
        r0 = app.frontend.search(tenant, req)  # miss: executes + stores
        assert r0.traces, "bench corpus not searchable"
        app.frontend.search(tenant, req)  # warm: first hit
        rc = app.frontend.result_cache
        assert rc is not None and rc.stats_hits >= 1, \
            "result cache did not hit on the repeat"
        l0 = TEL.launch_count()
        lats: list[float] = []
        for _ in range(400):
            t0 = time.perf_counter()
            app.frontend.search(tenant, req)
            lats.append(time.perf_counter() - t0)
        launches = TEL.launch_count() - l0
        assert launches == 0, f"cache hits launched {launches} kernels"
    finally:
        app.stop()
    _emit("search_result_cache_hit_p50_ms",
          float(np.percentile(lats, 50)) * 1e3, "ms",
          tel={"hits": len(lats),
               "p95_ms": round(float(np.percentile(lats, 95)) * 1e3, 4),
               "device_launches_during_hits": launches})

    # --- chunk-tier restage vs cold stage, same entry
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.ops import chunkpool
    from tempo_tpu.ops.filter import Cond, required_columns
    from tempo_tpu.ops.stage import set_staged_cache_budget, stage_block

    rng = np.random.default_rng(29)
    backend = LocalBackend(tmp + "/store-chunk")
    meta_a, _ = synth_block(backend, "bench", rng, 1 << 14, 24)
    meta_b, _ = synth_block(backend, "bench", rng, 1 << 14, 24)
    db = TempoDB(TempoDBConfig(wal_path=tmp + "/wal-chunk"), backend=backend)
    db.poll_now()
    blk_a, blk_b = db.open_block(meta_a), db.open_block(meta_b)
    needed = required_columns(
        (Cond(target="res", col="res.service_id", op="eq"),))

    # cold leg: a FRESH reader per sample (the pack object keeps its
    # own decoded-chunk/column caches, which a warm reader would serve
    # from) and cache=False to skip the HBM store and the pool probe --
    # every sample pays footer + ranged reads + decode + pad + upload,
    # the exact work a pool hit skips
    from tempo_tpu.block.versioned import open_block_versioned

    stage_block(blk_a, needed, cache=False)  # compile/warm the upload
    cold_dt = best_window(
        lambda: stage_block(open_block_versioned(backend, meta_a),
                            needed, cache=False), windows=3)

    chunkpool.clear()
    pool_hits0 = chunkpool.stats()["hits"]
    restage_lats: list[float] = []
    for _ in range(6):
        # park A in the pool: stage A then B (A becomes the LRU head),
        # squeeze the HBM budget so A demotes, restore the budget
        stage_block(blk_a, needed)
        stage_block(blk_b, needed)
        set_staged_cache_budget(1)
        set_staged_cache_budget(4 << 30)
        assert chunkpool.probe(meta_a.block_id,
                               (tuple(needed), None)), "demotion missed"
        t0 = time.perf_counter()
        stage_block(blk_a, needed)
        restage_lats.append(time.perf_counter() - t0)
    pool_hits = chunkpool.stats()["hits"] - pool_hits0
    assert pool_hits >= len(restage_lats), \
        f"only {pool_hits} pool hits across {len(restage_lats)} restages"
    restage_dt = min(restage_lats)
    set_staged_cache_budget(4 << 30)
    _emit("chunk_cache_restage_speedup", cold_dt / restage_dt, "x",
          tel={"cold_ms": round(cold_dt * 1e3, 3),
               "restage_ms": round(restage_dt * 1e3, 3),
               "codec": chunkpool.codec_name(),
               "pool_hits": pool_hits})


def bench_fleet() -> None:
    """`python bench.py --fleet`: multi-process fleet certification.

    Delegates to tempo_tpu.fleet.harness (QPS scaling 1->4 queriers +
    rolling ingester restart at RF=2 under vulture) and emits the two
    headline numbers as bench rows alongside the FLEET_SCALE.json
    artifact. Kept out of the default run: it spawns ~8 processes and
    owns its own wall-clock budget."""
    from tempo_tpu.fleet import harness as fleet_harness

    base = tempfile.mkdtemp(prefix="tempo-fleet-bench-")
    try:
        artifact = fleet_harness.certify("FLEET_SCALE.json", base,
                                         quick="--quick" in sys.argv)
    finally:
        shutil.rmtree(base, ignore_errors=True)
    qps = artifact["qps_scaling"]
    _emit("fleet_qps_scaling_ratio_4q", qps["ratio"], "x",
          qps["ratio"] / qps["target_ratio"])
    rolling = artifact["rolling_restart"]
    _emit("fleet_rolling_restart_miss_free_cycles",
          float(rolling["cycles"]), "cycles",
          1.0 if rolling["pass"] else 0.0)


def main() -> None:
    if "--fleet" in sys.argv:
        bench_fleet()
        return
    bench_analysis()
    bench_kernel()
    bench_mesh_1x1_overhead()
    tmp = tempfile.mkdtemp(prefix="tempo-tpu-bench-")
    try:
        cold, warm, cold_tel, warm_tel = bench_find_and_search(tmp)
        bench_first_compile(tmp)
        bench_compaction(tmp)
        bench_ingest(tmp)
        bench_spanmetrics()
        bench_generator_tap(tmp)
        bench_search_concurrent(tmp)
        bench_mesh_batched(tmp)
        bench_search_live(tmp)
        bench_search_affinity(tmp)
        bench_caching(tmp)
        _emit("search_block_e2e_cold_spans_per_sec", cold, "spans/s",
              cold / BASELINE_SPANS_PER_SEC, tel=cold_tel)
        # headline LAST: hot-block search (cached device staging), the
        # production querier pattern; cold line above is the every-byte-
        # from-disk comparable to the reference's 0.18 s figure
        _emit("search_block_e2e_spans_per_sec", warm, "spans/s",
              warm / BASELINE_SPANS_PER_SEC, tel=warm_tel)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
