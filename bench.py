"""Headline benchmark: TraceQL predicate-filter throughput, spans/sec/chip.

Runs the production filter kernel (ops/filter.eval_block -- the same
jitted program the query path executes) over a synthetic block shaped
like the reference's representative block (BASELINE.md: ~600 MB, 150 K
traces, 10.4 M spans), with a 3-condition query touching the span axis,
the resource axis, and the generic span-attr table:

    { resource.service.name = X && span.dur > Y && span.attr = Z }

Baseline: the reference's best published number -- vParquet full-block
search of 154,414 traces / 10.4 M spans in 0.18 s on a local SSD dev box
(docs/design-proposals/2022-04 Parquet.md:233-241) = 57.8 M spans/s.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_SPANS_PER_SEC = 10.4e6 / 0.18  # reference vParquet search


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tempo_tpu.ops.filter import (
        Cond,
        Operands,
        T_RES,
        T_SATTR,
        T_SPAN,
        eval_block,
    )

    rng = np.random.default_rng(42)
    N_SPANS = 1 << 22  # 4.2 M spans (power of two: no pad waste)
    N_TRACES = 1 << 17  # ~131 K traces
    N_RES = 1 << 10
    N_SATTR = N_SPANS * 2  # 2 generic attrs per span

    cols = {
        "span.trace_sid": rng.integers(0, N_TRACES, size=N_SPANS).astype(np.int32),
        "span.dur_us": rng.integers(0, 1_000_000, size=N_SPANS).astype(np.int32),
        "span.res_idx": rng.integers(0, N_RES, size=N_SPANS).astype(np.int32),
        "res.service_id": rng.integers(0, 64, size=N_RES).astype(np.int32),
        "sattr.span": np.sort(rng.integers(0, N_SPANS, size=N_SATTR)).astype(np.int32),
        "sattr.key_id": rng.integers(0, 100, size=N_SATTR).astype(np.int32),
        "sattr.vtype": np.zeros(N_SATTR, dtype=np.int32),  # all strings
        "sattr.str_id": rng.integers(0, 5_000, size=N_SATTR).astype(np.int32),
    }
    dcols = {k: jax.device_put(jnp.asarray(v)) for k, v in cols.items()}

    conds = (
        Cond(target=T_RES, col="res.service_id", op="eq"),
        Cond(target=T_SPAN, col="span.dur_us", op="ge"),
        Cond(target=T_SATTR, col="str", op="eq"),
    )
    tree = ("and", ("cond", 0), ("cond", 1), ("cond", 2))

    def run(svc: int, dur: int, key: int, val: int):
        operands = Operands.build(
            [(0, svc, 0, 0.0, 0.0), (0, dur, 0, 0.0, 0.0), (key, val, 0, 0.0, 0.0)]
        )
        return eval_block(
            (tree, conds), dcols, operands, N_SPANS, N_TRACES, N_SPANS, N_RES, N_TRACES
        )

    # warmup / compile
    out = run(1, 500_000, 3, 17)
    jax.block_until_ready(out)

    iters = 20
    t0 = time.perf_counter()
    for i in range(iters):
        out = run(i % 64, 400_000 + i, i % 100, i % 5_000)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    spans_per_sec = N_SPANS * iters / dt
    print(
        json.dumps(
            {
                "metric": "traceql_filter_spans_scanned_per_sec_per_chip",
                "value": round(spans_per_sec, 1),
                "unit": "spans/s",
                "vs_baseline": round(spans_per_sec / BASELINE_SPANS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
